"""Device-resident open-addressing fingerprint set (the HBM FPSet).

The sorted-pair visited set (ops/dedup.py) pays O(capacity) per level to
scatter-merge new fingerprints into sorted order — profiled at 74% of the
whole level step on the flagship bench (engine/bfs.py notes).  This module
replaces sort + binary-search probe + rank-merge with one structure and one
kernel: a power-of-two hash table of (hi, lo) uint32 pairs in device
memory, probed and claimed with fixed-trip-count linear probing — O(batch)
per level, independent of table size, with all-deterministic tie-breaks
(scatter-min claims), so BFS discovery order and counterexample traces stay
reproducible.

Duplicate handling inside one batch needs no pre-sort: rows carrying the
same fingerprint land on the same probe slot; the claim scatter-min picks
the lowest row index as the winner, the losers observe the winner's
fingerprint on re-read and report "seen".

Insertion is insert-or-find: after `probe_insert`, `is_new[i]` is True for
exactly one row per distinct fingerprint not already in the table.  The
caller must re-run with a grown table when `overflow` is set (a row
exhausted its probe budget) — with load kept under ~0.5 the expected probe
count is ~1.5 and P=32 budgets are astronomically safe, but correctness
never depends on that: overflow is detected, never silently dropped.

TPU notes: fingerprints ride as two uint32 lanes (no 64-bit int ALU); the
probe loop is a `lax.fori_loop` with static trip count; gathers/scatters
are the only memory ops and vectorize over the batch.  Sharded engines give
each shard its own table over its owned fingerprint range.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# empty-slot sentinel: the all-ones pair never occurs as a fingerprint.
# Hashed mode: ops/fingerprint.hash_pair remaps it.  Exact64 mode: packing
# demotes any schema that could legally pack to all-ones in both lanes to
# hashed fingerprints at build time (StateSpec._may_hit_sentinel,
# ops/packing.py) — the guarantee is enforced by construction, not assumed.
# Engine padding is masked before reaching the table.
SENT = 0xFFFFFFFF


def _fmix32(h):
    """murmur3 finalizer: full 32-bit avalanche."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def new_table(cap: int):
    """Empty table of `cap` slots (cap must be a power of two)."""
    assert cap & (cap - 1) == 0, "hash table capacity must be a power of 2"
    return (
        jnp.full((cap,), SENT, jnp.uint32),
        jnp.full((cap,), SENT, jnp.uint32),
    )


CLAIM_FREE = 0x7FFFFFFF  # int32 max: "this slot was never claimed"


def probe_insert(t_hi, t_lo, q_hi, q_lo, valid, max_probes: int = 32, claim=None):
    """Insert-or-find a batch of fingerprints.

    t_hi/t_lo: uint32[cap] table (cap power of two).
    q_hi/q_lo: uint32[M] batch; `valid` masks live rows.
    claim: optional int32[cap] claim lattice carried ACROSS calls (see
    below); pass the one returned by the previous call (or new_claim) to
    avoid the O(cap) per-call initialization, or None to allocate fresh.
    Returns (t_hi', t_lo', claim', is_new[M], n_new, overflow).

    Per probe round, every still-pending row:
      1. reads its current slot;
      2. on fingerprint match -> seen (done, not new);
      3. on empty slot -> claims it via scatter-min of the row index, the
         winner writes its pair and is new; losers (in-batch duplicates or
         colliding strangers) re-read and either match (dup, done) or move
         to the next slot;
      4. on a foreign occupant -> moves to the next slot.

    The claim lattice never needs resetting — between rounds or between
    calls: a slot's claim is only consulted in the round that scatter-mins
    into it, and every claimed slot receives its winner's pair in that
    same round, so a slot carrying a stale claim is never empty again and
    its claim is never read.  (Claim values are row indices, so the free
    sentinel is int32-max and min-scatter always prefers a real row.)
    """
    cap = t_hi.shape[0]
    M = q_hi.shape[0]
    mask = jnp.uint32(cap - 1)
    sent = jnp.uint32(SENT)
    rows = jnp.arange(M, dtype=jnp.int32)
    # full avalanche before slotting: exact64-mode fingerprints are raw
    # packed states whose low bits carry almost no entropy (structured
    # fields), and linear probing collapses under clustered home slots —
    # murmur fmix on both lanes makes the slot uniform for either mode
    pos0 = ((_fmix32(q_lo ^ _fmix32(q_hi)) & mask)).astype(jnp.int32)
    if claim is None:
        claim = new_claim(cap)

    def body(_, carry):
        t_hi, t_lo, claim, pos, pending, is_new = carry
        cur_hi = t_hi[pos]
        cur_lo = t_lo[pos]
        match = pending & (cur_hi == q_hi) & (cur_lo == q_lo)
        empty = pending & (cur_hi == sent) & (cur_lo == sent)
        # deterministic claim: lowest row index wins the slot
        claim = claim.at[jnp.where(empty, pos, cap)].min(rows, mode="drop")
        won = empty & (claim[pos] == rows)
        t_hi = t_hi.at[jnp.where(won, pos, cap)].set(q_hi, mode="drop")
        t_lo = t_lo.at[jnp.where(won, pos, cap)].set(q_lo, mode="drop")
        # losers of the claim re-check the slot next round (it now holds
        # the winner's pair: an in-batch duplicate will match there)
        advance = pending & ~match & ~won & ~empty
        pos = jnp.where(advance, (pos + 1) & (cap - 1), pos)
        pending = pending & ~match & ~won
        is_new = is_new | won
        return t_hi, t_lo, claim, pos, pending, is_new

    t_hi, t_lo, claim, _pos, pending, is_new = jax.lax.fori_loop(
        0,
        max_probes,
        body,
        (t_hi, t_lo, claim, pos0, valid, jnp.zeros((M,), bool)),
    )
    return (
        t_hi,
        t_lo,
        claim,
        is_new,
        jnp.sum(is_new, dtype=jnp.int32),
        jnp.any(pending),
    )


def new_claim(cap: int):
    """Fresh claim lattice for a `cap`-slot table (see probe_insert)."""
    return jnp.full((cap,), CLAIM_FREE, jnp.int32)


def table_from_pairs(hi, lo, min_cap: int = 1 << 10, chunk: int = 1 << 20):
    """Build a table containing exactly the given (assumed-distinct) pairs.

    Streams the pairs through probe_insert in chunks; a probe-budget
    overflow (possible in principle even at low load, just improbable)
    grows the table and retries instead of failing — shared by table
    growth and every checkpoint-resume/init reinsertion path.
    Returns (t_hi, t_lo) with capacity >= max(min_cap, 4*len) rounded up
    to a power of two.
    """
    n = int(hi.shape[0])
    cap = max(int(min_cap), 4 * n, 2)
    cap = 1 << (cap - 1).bit_length()
    while True:
        nh, nl = new_table(cap)
        ok = True
        for start in range(0, n, chunk):
            h = jnp.asarray(hi[start : start + chunk])
            lo_c = jnp.asarray(lo[start : start + chunk])
            nh, nl, _c, _m, _n2, ovf = probe_insert(
                nh, nl, h, lo_c, jnp.ones(h.shape[0], bool)
            )
            if bool(ovf):  # pragma: no cover - improbable at 1/4 load
                ok = False
                break
        if ok:
            return nh, nl
        cap *= 2


def rehash_into(t_hi, t_lo, new_cap: int, chunk: int = 1 << 20):
    """Grow: re-insert every live pair into a (>=) `new_cap` table.

    Host-driven (runs between BFS levels, amortized O(n) per doubling);
    streams the old table in chunks through probe_insert so peak memory is
    old + new + one chunk.
    """
    import numpy as np

    old_hi = np.asarray(t_hi)
    old_lo = np.asarray(t_lo)
    live = ~((old_hi == SENT) & (old_lo == SENT))
    return table_from_pairs(old_hi[live], old_lo[live], min_cap=new_cap, chunk=chunk)
