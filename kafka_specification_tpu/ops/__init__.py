from .packing import Field, StateSpec
from .fingerprint import fingerprint_lanes

__all__ = ["Field", "StateSpec", "fingerprint_lanes"]
