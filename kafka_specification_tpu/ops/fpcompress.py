"""Bit-packed delta codec for sorted fingerprint exchange payloads.

The sharded engine's ``all_to_all`` routes each chunk's candidate
fingerprints to their owner shard in per-destination buckets of W slots,
padded with sentinels — at typical enablement the buckets are mostly
padding, and every slot ships 8 bytes of (hi, lo) u32 lanes regardless.
This module is the compressed wire format (ROADMAP item 5):

- each destination bucket is **sorted** (stable, sentinels last — the
  property that keeps dedup winners bit-identical, see
  parallel/sharded.py), so its live prefix is an ascending u64 sequence;
- the live values are **delta-encoded** from a per-bucket base (the
  first value rides the header), deltas of the padding tail are forced
  to zero via the bucket's live count (also in the header);
- deltas are **bit-packed** in blocks of :data:`BLK` values, each block
  at the bitwidth of its largest delta — zero-delta padding blocks pack
  to zero bits, live blocks to ~(64 - log2(live density)) bits/value —
  into a static ``n_words``-word u32 stream (static shapes under jit;
  a stream that does not fit raises the overflow flag and the chunk
  re-runs wider, the same ladder as every other exchange overflow).

Everything is u32-lane arithmetic (64-bit values as (hi, lo) pairs with
explicit carries/borrows): TPUs run with x64 disabled, exactly like the
fingerprint lanes themselves.  ``pack_np``/``unpack_np`` are the numpy
twins — bit-identical to the traced kernels (tests/test_overlap.py
round-trips both).  Integrity: the exchange's in-jit framing digests are
computed over the *decoded* payload (parallel/sharded.py), so a bit the
fabric flips anywhere in the packed stream, the header, or the codec
itself desyncs the sent/received digests — compression does not weaken
the PR 9 fabric contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: values per bit-packing block (one shared bitwidth per block; 32 keeps
#: the block-granularity waste tolerable on small destination buckets)
BLK = 32
#: header layout: [count, first_hi, first_lo] + one bitwidth per block
HDR = 3

_SENT = 0xFFFFFFFF


def n_blocks(width: int) -> int:
    return -(-int(width) // BLK)


def header_words(width: int) -> int:
    return HDR + n_blocks(width)


def default_stream_words(width: int) -> int:
    """Default packed-stream budget: ONE u32 word per slot — a 2x byte
    reduction on the fingerprint lanes (vs 2 words/slot raw).  Random
    64-bit fingerprints only delta-compress to ~(66 - log2(live count))
    bits/value, so the real win is the padding tail packing to zero
    bits: one word/slot fits live prefixes up to ~1/2 bucket occupancy;
    denser chunks trip the overflow flag and re-run on the existing
    destination-width ladder (one doubling halves the occupancy)."""
    return max(BLK, int(width))


def _nbits32(x):
    """Bit length of a u32 (0 -> 0), branch-free (no clz in jnp)."""
    x = x.astype(jnp.uint32)
    n = jnp.zeros(x.shape, jnp.int32)
    for s in (16, 8, 4, 2, 1):
        big = x >> s
        take = big > jnp.uint32(0)
        x = jnp.where(take, big, x)
        n = n + take.astype(jnp.int32) * s
    return n + (x > 0).astype(jnp.int32)


def pack_sorted(hi, lo, count, n_words: int):
    """Pack one sorted bucket's fingerprint lanes -> (words, header, ovf).

    hi/lo: [W] u32 lanes, ascending as u64 with the sentinel padding
    tail sorted last.  count: live (non-sentinel) values.  Returns the
    packed u32 stream [n_words], the header [HDR + n_blocks(W)] u32
    (count, first value lanes, per-block bitwidths), and the overflow
    flag (stream too small for this bucket's delta entropy — outputs are
    then incomplete and the caller must re-run wider).  Traced; static
    shapes from (W, n_words)."""
    W = hi.shape[0]
    NB = n_blocks(W)
    Wp = NB * BLK
    count = jnp.minimum(count, W).astype(jnp.int32)
    idx = jnp.arange(Wp, dtype=jnp.int32)
    live = idx < count
    hi_p = jnp.concatenate(
        [hi, jnp.full((Wp - W,), _SENT, jnp.uint32)]
    ) if Wp > W else hi
    lo_p = jnp.concatenate(
        [lo, jnp.full((Wp - W,), _SENT, jnp.uint32)]
    ) if Wp > W else lo
    first_hi = jnp.where(count > 0, hi_p[0], jnp.uint32(0))
    first_lo = jnp.where(count > 0, lo_p[0], jnp.uint32(0))
    # two-limb delta v[i] - v[i-1] (ascending => non-negative u64);
    # index 0 deltas from the header base (delta 0), padding deltas 0
    ph = jnp.concatenate([first_hi[None], hi_p[:-1]])
    pl = jnp.concatenate([first_lo[None], lo_p[:-1]])
    dlo = lo_p - pl
    borrow = (lo_p < pl).astype(jnp.uint32)
    dhi = hi_p - ph - borrow
    dhi = jnp.where(live, dhi, jnp.uint32(0))
    dlo = jnp.where(live, dlo, jnp.uint32(0))
    bw = jnp.where(dhi > 0, 32 + _nbits32(dhi), _nbits32(dlo))  # [Wp]
    bwb = bw.reshape(NB, BLK).max(axis=1)  # [NB] bits/value per block
    blk_bits = bwb * BLK
    blk_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(blk_bits)[:-1]]
    )
    total_bits = jnp.sum(blk_bits)
    ovf = total_bits > n_words * 32
    b = idx // BLK
    pos = blk_off[b] + (idx % BLK) * bwb[b]
    w = pos // 32
    sh = (pos % 32).astype(jnp.uint32)
    sh32 = (jnp.uint32(32) - sh) & jnp.uint32(31)
    nz = sh > 0
    # a value spans <= 3 words; contributions never overlap bit-wise
    # (each value owns [pos, pos+bw) and bw >= its bit length), so
    # scatter-add composes them exactly like bitwise-or
    c0 = dlo << sh
    c1 = jnp.where(nz, (dlo >> sh32) | (dhi << sh), dhi)
    c2 = jnp.where(nz, dhi >> sh32, jnp.uint32(0))
    words = jnp.zeros((n_words,), jnp.uint32)
    words = words.at[w].add(c0, mode="drop")
    words = words.at[w + 1].add(c1, mode="drop")
    words = words.at[w + 2].add(c2, mode="drop")
    header = jnp.concatenate(
        [
            count.astype(jnp.uint32)[None],
            first_hi[None],
            first_lo[None],
            bwb.astype(jnp.uint32),
        ]
    )
    return words, header, ovf


def unpack_sorted(words, header, width: int):
    """Inverse of :func:`pack_sorted` -> (hi, lo) [width] u32 lanes with
    the sentinel tail restored.  Traced; bit-identical to the numpy
    twin."""
    NB = n_blocks(width)
    Wp = NB * BLK
    count = header[0].astype(jnp.int32)
    first_hi = header[1]
    first_lo = header[2]
    bwb = header[HDR:].astype(jnp.int32)  # [NB]
    blk_bits = bwb * BLK
    blk_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(blk_bits)[:-1]]
    )
    idx = jnp.arange(Wp, dtype=jnp.int32)
    b = idx // BLK
    bw = bwb[b]
    pos = blk_off[b] + (idx % BLK) * bw
    w = pos // 32
    sh = (pos % 32).astype(jnp.uint32)
    sh32 = (jnp.uint32(32) - sh) & jnp.uint32(31)
    nz = sh > 0
    wpad = jnp.concatenate([words, jnp.zeros((4,), jnp.uint32)])
    w0 = wpad[w]
    w1 = wpad[w + 1]
    w2 = wpad[w + 2]
    vlo = (w0 >> sh) | jnp.where(nz, w1 << sh32, jnp.uint32(0))
    vhi = jnp.where(nz, (w1 >> sh) | (w2 << sh32), w1)
    lo_bits = jnp.minimum(bw, 32)
    hi_bits = jnp.maximum(bw - 32, 0)
    one = jnp.uint32(1)
    lo_mask = jnp.where(
        lo_bits >= 32,
        jnp.uint32(_SENT),
        (one << jnp.minimum(lo_bits, 31).astype(jnp.uint32)) - one,
    )
    hi_mask = jnp.where(
        hi_bits >= 32,
        jnp.uint32(_SENT),
        (one << jnp.minimum(hi_bits, 31).astype(jnp.uint32)) - one,
    )
    dlo = vlo & lo_mask
    dhi = vhi & hi_mask

    def _add64(a, bb):
        lo = a[1] + bb[1]
        carry = (lo < bb[1]).astype(jnp.uint32)
        return (a[0] + bb[0] + carry, lo)

    # running 64-bit sum of deltas (associative two-limb addition), then
    # re-base on the header's first value
    shi, slo = jax.lax.associative_scan(_add64, (dhi, dlo))
    lo = slo + first_lo
    hi = shi + first_hi + (lo < first_lo).astype(jnp.uint32)
    live = idx < count
    hi = jnp.where(live, hi, jnp.uint32(_SENT))[:width]
    lo = jnp.where(live, lo, jnp.uint32(_SENT))[:width]
    return hi, lo


def packed_bytes(width: int, n_words: int) -> int:
    """Wire bytes of one packed bucket (stream + header)."""
    return 4 * (int(n_words) + header_words(width))


def raw_bytes(width: int) -> int:
    """Wire bytes of one raw bucket's fingerprint lanes (hi + lo)."""
    return 8 * int(width)


# --- numpy twins (tests; jax-free consumers) ------------------------------


def pack_np(hi, lo, count, n_words: int):
    hi = np.asarray(hi, np.uint32)
    lo = np.asarray(lo, np.uint32)
    W = hi.shape[0]
    NB = n_blocks(W)
    Wp = NB * BLK
    count = int(min(count, W))
    v = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    v = np.concatenate([v, np.full(Wp - W, np.uint64(0xFFFFFFFFFFFFFFFF))])
    first = v[0] if count > 0 else np.uint64(0)
    d = np.zeros(Wp, np.uint64)
    if count > 0:
        d[1:count] = v[1:count] - v[:count - 1]
    bw = np.array([int(x).bit_length() for x in d], np.int64)
    bwb = bw.reshape(NB, BLK).max(axis=1)
    blk_off = np.concatenate([[0], np.cumsum(bwb * BLK)[:-1]])
    total_bits = int((bwb * BLK).sum())
    ovf = total_bits > n_words * 32
    stream = 0
    for i in range(Wp):
        b = i // BLK
        if bwb[b] == 0:
            continue
        pos = int(blk_off[b] + (i % BLK) * bwb[b])
        stream |= int(d[i]) << pos
    words = np.zeros(n_words, np.uint32)
    mask = (1 << 32) - 1
    for wI in range(n_words):
        words[wI] = (stream >> (32 * wI)) & mask
    header = np.concatenate(
        [
            np.asarray(
                [count, int(first >> np.uint64(32)), int(first & np.uint64(0xFFFFFFFF))],
                np.uint32,
            ),
            bwb.astype(np.uint32),
        ]
    )
    return words, header, bool(ovf)


def unpack_np(words, header, width: int):
    words = np.asarray(words, np.uint32)
    header = np.asarray(header, np.uint32)
    NB = n_blocks(width)
    count = int(header[0])
    first = (np.uint64(header[1]) << np.uint64(32)) | np.uint64(header[2])
    bwb = header[HDR:HDR + NB].astype(np.int64)
    blk_off = np.concatenate([[0], np.cumsum(bwb * BLK)[:-1]])
    stream = 0
    for wI in range(words.shape[0] - 1, -1, -1):
        stream = (stream << 32) | int(words[wI])
    out = np.full(width, np.uint64(0xFFFFFFFFFFFFFFFF))
    acc = int(first)
    for i in range(min(count, width)):
        b = i // BLK
        bwv = int(bwb[b])
        if bwv:
            pos = int(blk_off[b] + (i % BLK) * bwv)
            acc = (acc + ((stream >> pos) & ((1 << bwv) - 1))) & (
                (1 << 64) - 1
            )
        out[i] = np.uint64(acc)
    hi = (out >> np.uint64(32)).astype(np.uint32)
    lo = (out & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo
