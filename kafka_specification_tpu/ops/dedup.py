"""Sorting, batch-dedup and sorted-set membership over (hi, lo) uint32 pairs.

This is the device-resident replacement for TLC's FPSet + StateQueue: the
visited set is a sorted array of fingerprint pairs living in HBM; each BFS
level sorts the candidate fingerprints (XLA sort on TPU), drops in-batch
duplicates by adjacent comparison, and probes the visited set with a
fixed-iteration vectorized binary search (jit-friendly: no data-dependent
control flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Sentinel (all-ones) sorts to the end; used to pad invalid slots.
# (kept as a Python int: a module-level jnp constant would initialize the
# default JAX backend at import time, which must not happen on TPU hosts
# where import != run)
SENT = 0xFFFFFFFF


def sort_pairs_with_payload(hi, lo, invalid, payloads):
    """Sort candidates so valid entries come first ordered by (hi, lo).

    invalid: bool[N] — True entries are pushed to the end.
    payloads: tuple of arrays [N, ...] permuted alongside.
    Returns (hi_s, lo_s, invalid_s, payloads_s).
    """
    order = jnp.lexsort((lo, hi, invalid.astype(jnp.uint32)))
    take = lambda a: jnp.take(a, order, axis=0)
    return take(hi), take(lo), take(invalid), tuple(take(p) for p in payloads)


def first_occurrence_mask(hi_s, lo_s, invalid_s):
    """After sorting: True for the first copy of each distinct valid pair."""
    prev_same = jnp.concatenate(
        [jnp.array([False]), (hi_s[1:] == hi_s[:-1]) & (lo_s[1:] == lo_s[:-1])]
    )
    return (~invalid_s) & (~prev_same)


def member_sorted(set_hi, set_lo, set_n, q_hi, q_lo):
    """Vectorized membership probe of queries against a sorted pair set.

    set_hi/set_lo: uint32[cap] sorted ascending on (hi, lo) for the first
    set_n entries (the rest is sentinel padding).  Fixed 32-iteration binary
    search — static trip count, fully vectorized over queries.
    """
    cap = set_hi.shape[0]
    n_q = q_hi.shape[0]
    lo_i = jnp.zeros((n_q,), jnp.int32)
    hi_i = jnp.full((n_q,), set_n, jnp.int32)
    iters = max(1, cap.bit_length())

    def body(_, carry):
        lo_i, hi_i = carry
        mid = (lo_i + hi_i) // 2
        mh = set_hi[mid]
        ml = set_lo[mid]
        less = (mh < q_hi) | ((mh == q_hi) & (ml < q_lo))
        return jnp.where(less, mid + 1, lo_i), jnp.where(less, hi_i, mid)

    lo_i, _ = jax.lax.fori_loop(0, iters, body, (lo_i, hi_i))
    idx = jnp.minimum(lo_i, cap - 1)
    return (lo_i < set_n) & (set_hi[idx] == q_hi) & (set_lo[idx] == q_lo)


def merge_into_sorted(set_hi, set_lo, set_n, new_hi, new_lo, new_valid, out_cap):
    """Merge new pairs into the sorted visited set (concat + sort + slice).

    Invalid new slots are replaced by sentinel pairs so they sort past the
    valid region.  out_cap is a static capacity the caller guarantees to be
    >= set_n + count(new_valid) (host-side doubling policy); the result is
    sliced to it so the jitted caller keeps a fixed visited-set shape.
    Returns (hi[out_cap], lo[out_cap], n).
    """
    sent = jnp.uint32(SENT)
    all_hi = jnp.concatenate([set_hi, jnp.where(new_valid, new_hi, sent)])
    all_lo = jnp.concatenate([set_lo, jnp.where(new_valid, new_lo, sent)])
    order = jnp.lexsort((all_lo, all_hi))
    all_hi, all_lo = all_hi[order], all_lo[order]
    total = all_hi.shape[0]
    if total < out_cap:
        pad = jnp.full((out_cap - total,), SENT, jnp.uint32)
        all_hi = jnp.concatenate([all_hi, pad])
        all_lo = jnp.concatenate([all_lo, pad])
    return all_hi[:out_cap], all_lo[:out_cap], set_n + jnp.sum(new_valid, dtype=jnp.int32)
