"""Sorting, batch-dedup and sorted-set membership over (hi, lo) uint32 pairs.

This is the device-resident replacement for TLC's FPSet + StateQueue: the
visited set is a sorted array of fingerprint pairs living in HBM; each BFS
level sorts the candidate fingerprints (XLA sort on TPU), drops in-batch
duplicates by adjacent comparison, and probes the visited set with a
fixed-iteration vectorized binary search (jit-friendly: no data-dependent
control flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Sentinel (all-ones) sorts to the end; used to pad invalid slots.
# (kept as a Python int: a module-level jnp constant would initialize the
# default JAX backend at import time, which must not happen on TPU hosts
# where import != run)
SENT = 0xFFFFFFFF


def first_occurrence_mask(hi_s, lo_s, invalid_s):
    """After sorting: True for the first copy of each distinct valid pair."""
    prev_same = jnp.concatenate(
        [jnp.array([False]), (hi_s[1:] == hi_s[:-1]) & (lo_s[1:] == lo_s[:-1])]
    )
    return (~invalid_s) & (~prev_same)


def rank_sorted(set_hi, set_lo, set_n, q_hi, q_lo):
    """Vectorized lower-bound rank of queries in a sorted pair set.

    set_hi/set_lo: uint32[cap] sorted ascending on (hi, lo) for the first
    set_n entries (the rest is sentinel padding).  Fixed-iteration binary
    search — static trip count, fully vectorized over queries.  Returns
    (found_mask, rank) where rank is the insertion index (bisect_left).
    """
    cap = set_hi.shape[0]
    n_q = q_hi.shape[0]
    lo_i = jnp.zeros((n_q,), jnp.int32)
    hi_i = jnp.broadcast_to(jnp.asarray(set_n, jnp.int32), (n_q,))
    iters = max(1, cap.bit_length())

    def body(_, carry):
        lo_i, hi_i = carry
        active = lo_i < hi_i  # guard: an empty interval must stay put (mid
        # would read one-past-the-end, which JAX clamps to the last element)
        mid = (lo_i + hi_i) // 2
        midc = jnp.minimum(mid, cap - 1)
        mh = set_hi[midc]
        ml = set_lo[midc]
        less = (mh < q_hi) | ((mh == q_hi) & (ml < q_lo))
        return (
            jnp.where(active & less, mid + 1, lo_i),
            jnp.where(active & ~less, mid, hi_i),
        )

    lo_i, _ = jax.lax.fori_loop(0, iters, body, (lo_i, hi_i))
    idx = jnp.minimum(lo_i, cap - 1)
    found = (lo_i < set_n) & (set_hi[idx] == q_hi) & (set_lo[idx] == q_lo)
    return found, lo_i


def member_sorted(set_hi, set_lo, set_n, q_hi, q_lo):
    """Membership probe (see rank_sorted)."""
    found, _ = rank_sorted(set_hi, set_lo, set_n, q_hi, q_lo)
    return found


def merge_ranked(set_hi, set_lo, set_n, new_hi, new_lo, new_rank, new_n, out_cap):
    """Scatter-merge: sorted visited set + compacted sorted new pairs.

    new_hi/new_lo: [M] with the first new_n entries sorted ascending and
    disjoint from the visited set; new_rank: each new entry's insertion index
    in the visited set (from rank_sorted).  Builds the merged sorted array
    with two scatters instead of re-sorting V+M keys:
      target(new[j])     = rank[j] + j
      target(visited[i]) = i + (# new entries below visited[i])
    Out-of-range targets (sentinel tails) drop or overwrite padding with
    sentinels — both harmless.  Returns (hi[out_cap], lo[out_cap], n).
    """
    cap = set_hi.shape[0]
    M = new_hi.shape[0]
    j = jnp.arange(M, dtype=jnp.int32)
    valid_new = j < new_n
    tgt_new = jnp.where(valid_new, new_rank + j, out_cap)

    # rank of each visited entry within the new list
    _, cnt_before = rank_sorted(new_hi, new_lo, new_n, set_hi, set_lo)
    tgt_old = jnp.arange(cap, dtype=jnp.int32) + cnt_before

    sent = jnp.uint32(SENT)
    out_hi = jnp.full((out_cap,), sent)
    out_lo = jnp.full((out_cap,), sent)
    out_hi = out_hi.at[tgt_old].set(set_hi).at[tgt_new].set(new_hi)
    out_lo = out_lo.at[tgt_old].set(set_lo).at[tgt_new].set(new_lo)
    return out_hi, out_lo, set_n + new_n
