"""64-bit state fingerprints as (hi, lo) uint32 pairs.

TPUs have no native 64-bit integer ALU, so fingerprints are carried as two
uint32 lanes everywhere (sorting via lexsort on the pair, membership via a
pairwise binary search — see ops.dedup).  This replaces TLC's FP64 fingerprint
set (the external Java engine the reference corpus relies on).

Two modes:
- exact: when the packed state fits in <= 64 bits, the fingerprint IS the
  state — dedup is collision-free and distinct-state counts are exact by
  construction (used by the small configs the golden tests pin down).
- hashed: murmur3-style mixing of the uint32 lanes with two different seeds.
  Collision risk for n states is ~n^2/2^65, the same regime TLC accepts.
"""

from __future__ import annotations

import jax.numpy as jnp

# plain ints (not jnp scalars): a module-level jnp constant would initialize
# the default JAX backend at import time
_C1 = 0xCC9E2D51
_C2 = 0x1B873593
SEED_HI = 0x9747B28C
SEED_LO = 0x3C6EF372


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _fmix32(h):
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _murmur3_lanes(lanes: jnp.ndarray, seed: int) -> jnp.ndarray:
    """murmur3_x86_32 over the trailing lane axis. lanes: uint32[..., K]."""
    k = lanes.shape[-1]
    c1, c2 = jnp.uint32(_C1), jnp.uint32(_C2)
    h = jnp.full(lanes.shape[:-1], seed, jnp.uint32)
    for i in range(k):
        kx = lanes[..., i] * c1
        kx = _rotl32(kx, 15) * c2
        h = h ^ kx
        h = _rotl32(h, 13) * jnp.uint32(5) + jnp.uint32(0xE6546B64)
    return _fmix32(h ^ jnp.uint32(4 * k))


def hash_pair(lanes: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Hashed-mode fingerprint pair (shared by the jnp and Pallas paths).

    The all-ones pair is the dedup padding sentinel: a valid state hashing
    to it would be indistinguishable from padding and silently *dropped*
    (worse than an ordinary collision, which merely conflates two states),
    so it is remapped to a reserved neighbour (~n*2^-64 probability per
    state; costs at most one extra ordinary collision).
    """
    hi = _murmur3_lanes(lanes, SEED_HI)
    lo = _murmur3_lanes(lanes, SEED_LO)
    sent = jnp.uint32(0xFFFFFFFF)
    is_sent = (hi == sent) & (lo == sent)
    lo = jnp.where(is_sent, jnp.uint32(0xFFFFFFFE), lo)
    return hi, lo


def fingerprint_lanes(lanes: jnp.ndarray, exact: bool) -> tuple[jnp.ndarray, jnp.ndarray]:
    """uint32[..., K] packed states -> (hi, lo) uint32 fingerprints."""
    if exact:
        k = lanes.shape[-1]
        lo = lanes[..., 0]
        hi = lanes[..., 1] if k > 1 else jnp.zeros_like(lo)
        return hi, lo
    return hash_pair(lanes)
