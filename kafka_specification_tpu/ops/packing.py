"""Fixed-width bit packing of model states into uint32 lanes.

A model checker dedups states by identity, so the tensor encoding of a state
must be *canonical*: one TLA+ state <-> exactly one bit pattern.  The models
guarantee canonical field values (e.g. `TruncateTo` Nil-fills truncated log
slots, /root/reference/FiniteReplicatedLog.tla:105-109, so unwritten slots are
always Nil); this module guarantees a canonical bit layout.

Each field is an integer tensor with a known inclusive value range
[lo, hi].  Values are stored biased (v - lo) in ceil(log2(hi-lo+1)) bits.
Elements never straddle a lane boundary (the packer pads instead), which keeps
pack/unpack a pure gather/shift — friendly to XLA fusion on TPU.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Sequence

import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class Field:
    """One state variable: an integer tensor of `shape` with values in [lo, hi]."""

    name: str
    shape: tuple[int, ...]
    lo: int
    hi: int

    def __post_init__(self):
        assert self.hi >= self.lo, (self.name, self.lo, self.hi)

    @property
    def width(self) -> int:
        span = self.hi - self.lo + 1
        return max(1, math.ceil(math.log2(span)))

    @property
    def num_elements(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) if self.shape else 1


class StateSpec:
    """Bit-layout codec for a tuple of Fields -> uint32[num_lanes].

    pack/unpack are vectorizable (jax.vmap) and jit-friendly: the layout is
    computed once in Python; at trace time packing is a segment-sum of shifted
    values and unpacking a gather + shift + mask.
    """

    def __init__(self, fields: Sequence[Field], force_hashed: bool = False):
        self.fields = tuple(fields)
        self._force_hashed = force_hashed
        names = [f.name for f in self.fields]
        assert len(set(names)) == len(names), "duplicate field names"

        lane_ids, shifts, widths, los = [], [], [], []
        lane, bit = 0, 0
        lane_bits = {}
        for f in self.fields:
            w = f.width
            assert w <= 32, f"field {f.name} needs {w} bits > 32"
            for _ in range(f.num_elements):
                if bit + w > 32:  # never straddle a lane
                    lane, bit = lane + 1, 0
                lane_ids.append(lane)
                shifts.append(bit)
                widths.append(w)
                los.append(f.lo)
                bit += w
                lane_bits[lane] = bit
        self.num_lanes = lane + 1 if bit > 0 else lane
        # a state can only pack to the all-ones sentinel pair (the dedup
        # empty-slot marker, ops/dedup.SENT == ops/hashset.SENT) if every
        # lane is completely full of field bits (pad bits are always 0) AND
        # every field's biased span actually reaches its all-ones bit
        # pattern (a span < 2^width leaves the top pattern unrepresentable);
        # with a single lane the exact fingerprint's hi word is constant 0,
        # so the sentinel pair is unreachable regardless
        spans_full = all(
            f.hi - f.lo + 1 == (1 << f.width) for f in self.fields
        )
        self._may_hit_sentinel = (
            self.num_lanes == 2
            and all(lane_bits.get(i, 0) == 32 for i in range(self.num_lanes))
            and spans_full
        )
        self.total_bits = sum(widths)
        self._lane_ids = np.asarray(lane_ids, np.int32)
        self._shifts = np.asarray(shifts, np.uint32)
        self._masks = np.asarray([(1 << w) - 1 for w in widths], np.uint32)
        self._los = np.asarray(los, np.int32)
        self._num_elements = len(lane_ids)
        # per-field slices into the flat element vector
        self._field_slices = {}
        ofs = 0
        for f in self.fields:
            self._field_slices[f.name] = (ofs, ofs + f.num_elements, f.shape)
            ofs += f.num_elements
        # True iff the whole state fits in 64 bits -> fingerprints can be
        # exact (collision-free dedup).  Demoted to hashed when a state could
        # pack to the all-ones dedup sentinel (only if every lane is exactly
        # full — never the case for the corpus encodings).  force_hashed
        # exists so tests can exercise the hashed mode on small states.
        self.exact64 = (
            self.num_lanes <= 2 and not force_hashed and not self._may_hit_sentinel
        )

    # -- flat <-> struct -------------------------------------------------------

    def _flatten(self, state: dict) -> jnp.ndarray:
        parts = []
        for f in self.fields:
            v = jnp.asarray(state[f.name], jnp.int32).reshape(-1)
            parts.append(v)
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def _unflatten(self, flat: jnp.ndarray) -> dict:
        out = {}
        for f in self.fields:
            a, b, shape = self._field_slices[f.name]
            v = flat[a:b].reshape(shape) if shape else flat[a]
            out[f.name] = v
        return out

    # -- public API ------------------------------------------------------------

    def pack(self, state: dict) -> jnp.ndarray:
        """dict of int32 tensors -> uint32[num_lanes]. vmap over leading axes."""
        flat = self._flatten(state)
        biased = (flat - self._los).astype(jnp.uint32) & self._masks
        shifted = biased << self._shifts
        # widths don't overlap within a lane, so sum == bitwise-or
        lanes = jnp.zeros((self.num_lanes,), jnp.uint32)
        return lanes.at[self._lane_ids].add(shifted)

    def unpack(self, lanes: jnp.ndarray) -> dict:
        """uint32[num_lanes] -> dict of int32 tensors. vmap over leading axes."""
        vals = (lanes[self._lane_ids] >> self._shifts) & self._masks
        flat = vals.astype(jnp.int32) + self._los
        return self._unflatten(flat)

    def validate(self, state: dict) -> jnp.ndarray:
        """True iff every element is within its declared [lo, hi] range."""
        ok = jnp.bool_(True)
        for f in self.fields:
            v = jnp.asarray(state[f.name])
            ok = ok & jnp.all(v >= f.lo) & jnp.all(v <= f.hi)
        return ok
