"""Shared dedup-probe verification fixture.

One definition of "same winners as the jnp path" for every consumer that
validates a Pallas probe kernel against hashset.probe_insert: the
interpret-mode bit-identity tests (tests/test_pallas.py) and the on-chip
smoke tool (scripts/tpu_probe_smoke.py).  The fixture bakes in the
awkward cases — in-batch duplicates (winner identity matters: the lowest
-index row carries parent/action attribution for traces), rows colliding
with pre-seeded table entries, and invalid rows.
"""

from __future__ import annotations

import numpy as np

from . import hashset


def make_probe_case(seed: int = 5, cap: int = 1 << 12, m: int = 1024):
    """Build (t_hi0, t_lo0, q_hi, q_lo, valid) plus the jnp-path
    reference (ref_new, ref_n, ref_hi, ref_lo): ~25% in-batch
    duplicates, the first m/8 rows pre-seeded in the table, ~10%
    invalid rows."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    base = rng.integers(0, 2**32, size=(m, 2), dtype=np.uint32)
    dup_idx = rng.integers(0, m // 2, size=m // 4)
    base[m // 2 : m // 2 + m // 4] = base[dup_idx]
    seeded = base[: m // 8]
    valid = rng.random(m) < 0.9

    t_hi0, t_lo0 = hashset.table_from_pairs(
        seeded[:, 0], seeded[:, 1], min_cap=cap
    )
    q_hi = jnp.asarray(base[:, 0])
    q_lo = jnp.asarray(base[:, 1])
    v = jnp.asarray(valid)
    ref_hi, ref_lo, _claim, ref_new, ref_n, ref_ovf = hashset.probe_insert(
        t_hi0, t_lo0, q_hi, q_lo, v
    )
    assert not bool(ref_ovf)
    return {
        "t_hi0": t_hi0,
        "t_lo0": t_lo0,
        "q_hi": q_hi,
        "q_lo": q_lo,
        "valid": v,
        "ref_new": np.asarray(ref_new),
        "ref_n": int(ref_n),
        "ref_hi": ref_hi,
        "ref_lo": ref_lo,
    }


def live_set(h, l):
    """The set of live fingerprint pairs in a table — membership
    comparison that ignores slot layout (collision chains may legally
    place entries differently across kernel formulations)."""
    h, l = np.asarray(h), np.asarray(l)
    keep = ~((h == hashset.SENT) & (l == hashset.SENT))
    return set(zip(h[keep].tolist(), l[keep].tolist()))


def assert_same_winners(case, p_hi, p_lo, p_new, p_n):
    """Winners bit-identical to the jnp path, count equal, membership
    equal.  Raises AssertionError with context on any mismatch."""
    got = np.asarray(p_new)
    assert np.array_equal(got, case["ref_new"]), (
        "is_new winners differ from the jnp path "
        f"({int(got.sum())} vs {int(case['ref_new'].sum())} new)"
    )
    assert int(p_n) == case["ref_n"], (int(p_n), case["ref_n"])
    assert live_set(p_hi, p_lo) == live_set(case["ref_hi"], case["ref_lo"])
