"""Device-resident level helpers: in-jit multiset digest folds and the
segment-append scatter the device level-pipeline composes.

The device pipeline (engine/pipeline.py `DevicePipeline`) keeps a whole
BFS level on the accelerator: a bounded ``lax.while_loop`` runs every
chunk's expand -> compact -> fingerprint -> dedup stages without a host
round trip, so the per-chunk host work the fused pipeline still does —
fingerprint-set bookkeeping, digest folds, frontier assembly — must be
reformulated as pure traced ops.  This module holds the two primitives
that reformulation needs beyond what ops/dedup.py already provides:

- ``masked_digest`` / ``combine_digest``: the PR 9 per-level
  (count, xor, wrapping-sum) fingerprint-multiset digest computed
  entirely in-jit over (hi, lo) uint32 lanes — **x64-free** (the CI
  platform runs without jax x64), carrying the 64-bit wrapping sum as
  four 16-bit limbs in uint32 registers.  ``digest_ints`` converts the
  accumulator back to the exact python ints
  ``resilience.integrity.digest_fps`` would have produced for the same
  multiset, so the host-side :class:`LevelDigestChain` folds the
  device-computed digest bit-identically to the per-chunk host folds.
- ``append_rows`` / ``append_vec``: the dynamic-offset segment append
  that assembles the next frontier (rows, parents, action ids) inside
  the level loop — each chunk's compacted novel prefix lands at the
  running output offset; rows past the live prefix are garbage the next
  chunk overwrites (and the final host slice clips).

The HOST-backend (deferred-probe) level programs compose the same
helpers with two deltas: ``append_vec`` additionally carries the
emitted prefix's fingerprint lanes out (the once-per-level batched
host probe consumes them instead of recomputing), and the digest
helpers are NOT used — the chain's multiset is only known after the
host probe, so the host folds the survivors.  ``level_new_capacity``
sizes the level-new set identically in both modes (in host mode it
bounds the PRE-probe candidate count, which is what that set holds).

Everything here is shape-static and jit-pure; the purity lint
(`cli analyze`) sweeps this module.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: 16-bit limb block: column sums of uint16 limbs over <= 2^16 rows fit
#: uint32 exactly ((2^16-1) * 2^16 < 2^32), so digests of arbitrarily
#: wide buffers reduce block-wise with no 64-bit ALU
_BLOCK = 1 << 16


def zero_digest():
    """Neutral digest accumulator: (count, xor_hi, xor_lo, limbs[4])."""
    return (
        jnp.int32(0),
        jnp.uint32(0),
        jnp.uint32(0),
        jnp.zeros((4,), jnp.uint32),
    )


def _add_limbs(acc, add):  # kspec: traced
    """acc + add over four 16-bit limbs (uint32 registers, mod 2^64).

    ``acc`` limbs are normalized (< 2^16); ``add`` limbs may carry full
    uint32 block column sums.  Each add[i] is split into its low half
    (added to limb i) and high half (carried into limb i+1), so every
    per-limb sum stays below 2^18 — adding the raw uint32 directly
    could reach exactly 2^32 and silently drop a carry (a full 65536-row
    block of 0xFFFF limbs; caught in review, regression-tested in
    tests/test_integrity.py).  The final carry past limb 3 drops — the
    sum is 64-bit wrapping by construction, exactly
    ``np.sum(fps, dtype=uint64)``'s overflow semantics."""
    mask16 = jnp.uint32(0xFFFF)
    out = []
    carry = jnp.uint32(0)
    for i in range(4):
        t = acc[i] + (add[i] & mask16) + carry
        out.append(t & mask16)
        carry = (t >> 16) + (add[i] >> 16)
    return jnp.stack(out)


def masked_digest(hi, lo, valid):  # kspec: traced
    """(count, xor, sum) over the fingerprint pairs selected by `valid`.

    hi/lo: uint32[T] fingerprint lanes; valid: bool[T].  Returns the
    accumulator tuple ``(count i32, xor_hi u32, xor_lo u32,
    limbs u32[4])`` — fold into a running accumulator with
    :func:`combine_digest`, convert with :func:`digest_ints`."""
    z = jnp.uint32(0)
    mhi = jnp.where(valid, hi, z)
    mlo = jnp.where(valid, lo, z)
    count = jnp.sum(valid, dtype=jnp.int32)
    xor_hi = jax.lax.reduce(mhi, z, jax.lax.bitwise_xor, (0,))
    xor_lo = jax.lax.reduce(mlo, z, jax.lax.bitwise_xor, (0,))
    mask16 = jnp.uint32(0xFFFF)
    limb_cols = (mlo & mask16, mlo >> 16, mhi & mask16, mhi >> 16)
    T = hi.shape[0]
    nblk = -(-T // _BLOCK)
    pad = nblk * _BLOCK - T
    limbs = jnp.zeros((4,), jnp.uint32)
    per_block = []
    for col in limb_cols:
        if pad:
            col = jnp.concatenate([col, jnp.zeros((pad,), jnp.uint32)])
        per_block.append(
            jnp.sum(col.reshape(nblk, _BLOCK), axis=1, dtype=jnp.uint32)
        )
    for b in range(nblk):
        limbs = _add_limbs(limbs, [c[b] for c in per_block])
    return count, xor_hi, xor_lo, limbs


def combine_digest(acc, new):  # kspec: traced
    """Fold one chunk digest into the running level accumulator."""
    c0, xh0, xl0, l0 = acc
    c1, xh1, xl1, l1 = new
    return c0 + c1, xh0 ^ xh1, xl0 ^ xl1, _add_limbs(l0, l1)


def digest_ints(acc) -> tuple:
    """Device accumulator -> (count, xor, sum) python ints, bit-exact
    with ``resilience.integrity.digest_fps`` over the same multiset.
    Host-side (materializes the accumulator)."""
    import numpy as np

    count, xor_hi, xor_lo, limbs = acc
    lim = [int(v) & 0xFFFF for v in np.asarray(limbs).tolist()]
    total = lim[0] | (lim[1] << 16) | (lim[2] << 32) | (lim[3] << 48)
    xor = (int(np.asarray(xor_hi)) << 32) | int(np.asarray(xor_lo))
    return int(np.asarray(count)), xor, total & 0xFFFFFFFFFFFFFFFF


def _next_pow2(n: int) -> int:
    """Local twin of engine.bfs._next_pow2 (importing the engine here
    would cycle: engine/pipeline.py imports this module)."""
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


#: headroom multiplier over the measured per-level new-state high water
#: (matches PooledWidths.HEADROOM — one sizing philosophy everywhere)
LN_HEADROOM = 1.35

#: below this many lanes the safe (cannot-overflow) bound is taken
#: outright instead of the high-water ladder: 64Ki u64 pairs = 512KiB —
#: the per-chunk merge over it is cheap next to a gated chunk's work,
#: while an overflow re-dispatch always discards a full level's compute.
#: During a run's growth phase the high water lags the frontier by one
#: level, so a ladder here would re-dispatch nearly every level; at
#: production scale `worst` is millions of lanes and the ladder governs.
LN_SAFE_SMALL = 1 << 16


def level_new_capacity(T: int, ln_hw: int, worst: int) -> int:
    """The level-new sorted set's high-water-LADDER capacity — the ONE
    sizing policy for every device-resident level path (the single-
    device DevicePipeline and the sharded per-shard variant; they must
    not drift on overflow bounds).

    The per-chunk level-new merge costs O(LN), so LN is sized from the
    run's measured per-level new-state high water `ln_hw` (with
    LN_HEADROOM), floored at one chunk's emit width `T` (a level can
    always produce at least one chunk's worth) and capped at the safe
    bound `worst` (= chunks x emit width — the level can't produce
    more).  Small levels (`worst` <= LN_SAFE_SMALL) take the safe bound
    outright — no overflow is possible there and the ladder could only
    lose re-dispatches.  Otherwise an overflow costs exactly one
    re-dispatch at :func:`level_new_bound`; steady state costs
    nothing."""
    safe = _next_pow2(worst)
    if safe <= LN_SAFE_SMALL:
        return safe
    return min(
        _next_pow2(max(T, int(LN_HEADROOM * ln_hw) + 1)),
        safe,
    )


def level_new_bound(worst: int) -> int:
    """The safe (cannot-overflow) level-new capacity for the exact-bound
    re-dispatch: `worst` = chunks x per-chunk emit width."""
    return _next_pow2(worst)


def append_rows(buf, seg, offset):  # kspec: traced
    """Write a [T, K] segment into `buf` at row `offset` (traced value).
    The caller advances its live-prefix counter by the segment's valid
    count; rows past it are garbage the next append overwrites."""
    return jax.lax.dynamic_update_slice(buf, seg, (offset, 0))


def append_vec(buf, seg, offset):  # kspec: traced
    """1-D twin of :func:`append_rows`."""
    return jax.lax.dynamic_update_slice(buf, seg, (offset,))
