"""Pallas TPU kernel: the HBM open-addressing FPSet probe (ops/hashset).

The device-resident dedup path's dominant kernel is `probe_insert` — the
insert-or-find over the open-addressing fingerprint table.  This module
provides the Pallas formulation so a live TPU window can profile the
ACTUAL dedup kernel on hardware, not just the fingerprinting
(scripts/tpu_window.py stage; VERDICT r3 item 7).

Design — sequential grid, row-serial probing:

- TPU Pallas grids execute sequentially on a core, so the racy part of
  the jnp path (the claim-lattice scatter-min that arbitrates *parallel*
  claims to one empty slot) is unnecessary here: rows are processed in
  index order, and "first claimant wins" IS "lowest row index wins".
  The observable contract is identical to hashset.probe_insert in
  non-overflow runs: `is_new[i]` marks exactly the lowest-index row of
  each distinct fingerprint not already in the table (winner identity
  matters — it carries the parent/action attribution for traces).
  Probe-path layouts can diverge from the jnp path only in mixed
  collision chains, which never changes membership or winners, only
  slot positions (and, in pathological cases, the overflow flag — which
  merely triggers the caller's grow-and-rerun, exact either way).
- The table rides as an input/output-aliased ref read and written in
  place across grid steps; the batch is blocked into VMEM.
- Row-serial scalar probing is the correctness-first formulation (the
  per-row dependent-load chain is what a hash probe IS); a vectorized
  variant (probe rounds across the whole resident block with in-register
  duplicate arbitration) is the staged next step once hardware profiling
  shows where this one lands.

Bit-identity with the jnp path is pinned by tests/test_pallas.py in
interpret mode on CPU; KSPEC_USE_PALLAS=1 routes the engine's
device-hash backend through this kernel (engine/bfs).

Hardware status (round-5 window 3, scripts/tpu_mosaic_ladder.py +
TPU_MOSAIC_LADDER.json): this container's TPU tunnel routes every
Mosaic kernel with DATA-DEPENDENT VMEM addressing — even a single
dynamic (1,)-slice access with no loop — to a "chipless" AOT compile
helper whose libtpu init dies (subprocess exit 1), while vector /
static-index kernels compile and run on the chip.  A hash probe is
irreducibly data-dependent addressing, so these kernels cannot compile
through THIS tunnel in any formulation; the jnp probe_insert
(ops/hashset) is the production device-hash path on hardware and is
what every banked TPU bench used.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import hashset
from .hashset import SENT

# The kernel stages BOTH table lanes (uint32[cap] x 2) plus the batch block
# in VMEM (~16 MiB/core on current TPUs).  8 bytes/slot => cap 2^20 is
# 8 MiB of table, leaving headroom for the batch block, outputs and
# compiler scratch.  Beyond this the pallas_call simply fails to fit —
# callers must take the jnp probe path (HBM-resident table) instead; the
# engine gates on fits_vmem() and falls back loudly (engine/bfs), or —
# with KSPEC_PALLAS_HBM=1 — routes to probe_insert_pallas_hbm, whose
# table stays in HBM (pl.ANY + per-slot DMA) and has no such gate.
MAX_VMEM_CAP = 1 << 20


def fits_vmem(cap: int) -> bool:
    """True when a cap-slot table can be VMEM-staged by this kernel.
    KSPEC_PALLAS_VMEM_CAP overrides the limit (scripts/tpu_window.py
    shrinks it to force the HBM-resident kernel on small workloads)."""
    import os

    lim = int(os.environ.get("KSPEC_PALLAS_VMEM_CAP", MAX_VMEM_CAP))
    return cap <= lim


def _kernel(max_probes, q_hi_ref, q_lo_ref, valid_ref, _ti, _tl,
            t_hi_ref, t_lo_ref, is_new_ref):
    """One batch block: probe/insert each row serially (see module doc).

    _ti/_tl are the aliased input views of the table; all access goes
    through the output refs (same memory) so grid steps see each other's
    inserts.

    is_new_ref is int32 and TERNARY: 0 = seen / invalid, 1 = new
    (this row claimed the slot), 2 = probe-budget overflow (row still
    pending after max_probes).  Real-TPU rank-1 tiling rejects both a
    (1,)-block scalar output and bool blocks at the engine's 256-row
    alignment (first hardware windows, TPU_WINDOW.json), so the
    overflow flag rides in the one well-tiled output instead of its own
    lane, and the wrapper splits the encoding."""
    block = q_hi_ref.shape[0]
    cap = t_hi_ref.shape[0]
    mask = jnp.uint32(cap - 1)
    sent = jnp.uint32(SENT)

    def row_body(i, carry):
        qh = q_hi_ref[i]
        ql = q_lo_ref[i]
        v = valid_ref[i] != 0
        # same slotting as hashset.probe_insert (full avalanche on both
        # lanes so exact64 packs spread uniformly)
        pos0 = (hashset._fmix32(ql ^ hashset._fmix32(qh)) & mask).astype(
            jnp.int32
        )

        def probe_body(_p, carry):
            pos, pending, isnew = carry
            cur_hi = t_hi_ref[pos]
            cur_lo = t_lo_ref[pos]
            match = pending & (cur_hi == qh) & (cur_lo == ql)
            empty = pending & (cur_hi == sent) & (cur_lo == sent)
            # sequential claim: first (lowest-index) claimant wins; the
            # masked store keeps the slot unchanged for non-claimants.
            # (1,)-slice stores, not scalar stores: real-TPU lowering
            # rejects scalar stores to VMEM (hardware window 2)
            t_hi_ref[pl.ds(pos, 1)] = jnp.where(empty, qh, cur_hi)[None]
            t_lo_ref[pl.ds(pos, 1)] = jnp.where(empty, ql, cur_lo)[None]
            isnew = isnew | empty
            advance = pending & ~match & ~empty
            pos = jnp.where(advance, (pos + 1) & jnp.int32(cap - 1), pos)
            pending = pending & ~match & ~empty
            return pos, pending, isnew

        pos, pending, isnew = jax.lax.fori_loop(
            0, max_probes, probe_body, (pos0, v, jnp.bool_(False))
        )
        is_new_ref[pl.ds(i, 1)] = jnp.where(
            pending, jnp.int32(2), jnp.where(isnew, jnp.int32(1), jnp.int32(0))
        )[None]
        return carry

    jax.lax.fori_loop(0, block, row_body, 0)


def _kernel_grouped(max_probes, group, q_hi_ref, q_lo_ref, valid_ref, _ti,
                    _tl, t_hi_ref, t_lo_ref, is_new_ref):
    """Interleaved probe: G independent row chains in flight per round.

    TPU Pallas has no vector gather over VMEM (dynamic indexing is scalar
    or contiguous-slice — pallas guide "Dynamic Indexing"), so a hash
    probe is irreducibly a dependent-load chain PER ROW.  What CAN be
    parallelized is memory-level parallelism ACROSS rows: each round
    issues G independent scalar loads (no cross-dependences, so the
    scalar unit pipelines them) and then resolves the G rows in
    row-index order entirely in registers.

    In-register arbitration keeps the sequential-claim contract: row g's
    loaded value is patched with any slot written by rows h<g in the SAME
    round (ascending h, so the latest write wins), which makes the commit
    order strictly row-index order.  Same-fp rows share one probe chain,
    so the lowest-index row claims and the rest observe its write as a
    match — `is_new` winners are identical to the row-serial kernel and
    the jnp path.  Mixed collision chains may land at different slot
    POSITIONS than the serial kernel (same caveat as the module header:
    membership and winners never differ; pathological near-full tables
    can differ in the overflow flag, which only triggers the caller's
    grow-and-rerun).
    """
    block = q_hi_ref.shape[0]
    cap = t_hi_ref.shape[0]
    mask = jnp.uint32(cap - 1)
    sent = jnp.uint32(SENT)

    def group_body(gi, carry):
        base = gi * group
        qh = [q_hi_ref[base + g] for g in range(group)]
        ql = [q_lo_ref[base + g] for g in range(group)]
        pos0 = [
            (hashset._fmix32(ql[g] ^ hashset._fmix32(qh[g])) & mask).astype(
                jnp.int32
            )
            for g in range(group)
        ]
        pend0 = [valid_ref[base + g] != 0 for g in range(group)]

        def probe_round(_p, carry):
            pos, pending, isnew = carry
            # phase 1: G independent loads (the MLP win — no
            # cross-dependences inside one round)
            cur_hi = [t_hi_ref[pos[g]] for g in range(group)]
            cur_lo = [t_lo_ref[pos[g]] for g in range(group)]
            # phase 2: resolve in row-index order, patching each row's
            # view with same-round writes by earlier rows
            npos, npend, nnew = list(pos), list(pending), list(isnew)
            writes = []  # (slot, hi, lo) committed this round, ascending
            for g in range(group):
                ch, cl = cur_hi[g], cur_lo[g]
                for ws, wh, wl in writes:
                    hit = pos[g] == ws
                    ch = jnp.where(hit, wh, ch)
                    cl = jnp.where(hit, wl, cl)
                match = pending[g] & (ch == qh[g]) & (cl == ql[g])
                empty = pending[g] & (ch == sent) & (cl == sent)
                sh = jnp.where(empty, qh[g], ch)
                sl = jnp.where(empty, ql[g], cl)
                t_hi_ref[pl.ds(pos[g], 1)] = sh[None]
                t_lo_ref[pl.ds(pos[g], 1)] = sl[None]
                writes.append((pos[g], sh, sl))
                nnew[g] = isnew[g] | empty
                advance = pending[g] & ~match & ~empty
                npos[g] = jnp.where(
                    advance, (pos[g] + 1) & jnp.int32(cap - 1), pos[g]
                )
                npend[g] = advance
            return tuple(npos), tuple(npend), tuple(nnew)

        pos, pending, isnew = jax.lax.fori_loop(
            0,
            max_probes,
            probe_round,
            (
                tuple(pos0),
                tuple(pend0),
                tuple(jnp.bool_(False) for _ in range(group)),
            ),
        )
        for g in range(group):
            # ternary encoding (see _kernel): 2 = still pending after
            # max_probes rounds = probe-budget overflow
            is_new_ref[pl.ds(base + g, 1)] = jnp.where(
                pending[g],
                jnp.int32(2),
                jnp.where(isnew[g], jnp.int32(1), jnp.int32(0)),
            )[None]
        return carry

    jax.lax.fori_loop(0, block // group, group_body, 0)


def _kernel_hbm(max_probes, q_hi_ref, q_lo_ref, valid_ref, _ti, _tl,
                t_hi_any, t_lo_any, is_new_ref,
                s_rhi, s_rlo, s_whi, s_wlo, sem):
    """HBM-resident probe: the table never enters VMEM (round-5 item —
    lifts the MAX_VMEM_CAP gate for real workloads, where
    cap = pow2(4*states) blows the VMEM-staged kernel).

    The table lanes ride in `pl.ANY` memory space (HBM on hardware);
    every probe is an explicit single-slot DMA into a VMEM scratch, and
    every commit a single-slot DMA back (unconditional write-back of
    either the claim or the unchanged value — the sequential grid makes
    the read-modify-write race-free, same argument as the row-serial
    kernel).  The hi/lo lanes' DMAs are started together so the two
    loads overlap.  Winners/membership are bit-identical to the VMEM
    kernels and the jnp path (same probe order); per-element DMA is the
    correctness-first formulation — a block-granular double-buffered
    variant is the staged next step once a hardware window profiles the
    descriptor overhead."""
    block = q_hi_ref.shape[0]
    cap = t_hi_any.shape[0]
    mask = jnp.uint32(cap - 1)
    sent = jnp.uint32(SENT)

    def row_body(i, carry):
        qh = q_hi_ref[i]
        ql = q_lo_ref[i]
        v = valid_ref[i] != 0
        pos0 = (hashset._fmix32(ql ^ hashset._fmix32(qh)) & mask).astype(
            jnp.int32
        )

        def probe_body(_p, carry):
            pos, pending, isnew = carry
            r_hi = pltpu.make_async_copy(
                t_hi_any.at[pl.ds(pos, 1)], s_rhi, sem.at[0]
            )
            r_lo = pltpu.make_async_copy(
                t_lo_any.at[pl.ds(pos, 1)], s_rlo, sem.at[1]
            )
            r_hi.start()
            r_lo.start()
            r_hi.wait()
            r_lo.wait()
            cur_hi = s_rhi[0]
            cur_lo = s_rlo[0]
            match = pending & (cur_hi == qh) & (cur_lo == ql)
            empty = pending & (cur_hi == sent) & (cur_lo == sent)
            s_whi[:] = jnp.where(empty, qh, cur_hi)[None]
            s_wlo[:] = jnp.where(empty, ql, cur_lo)[None]
            w_hi = pltpu.make_async_copy(
                s_whi, t_hi_any.at[pl.ds(pos, 1)], sem.at[2]
            )
            w_lo = pltpu.make_async_copy(
                s_wlo, t_lo_any.at[pl.ds(pos, 1)], sem.at[3]
            )
            w_hi.start()
            w_lo.start()
            w_hi.wait()
            w_lo.wait()
            isnew = isnew | empty
            advance = pending & ~match & ~empty
            pos = jnp.where(advance, (pos + 1) & jnp.int32(cap - 1), pos)
            return pos, advance, isnew

        pos, pending, isnew = jax.lax.fori_loop(
            0, max_probes, probe_body, (pos0, v, jnp.bool_(False))
        )
        is_new_ref[pl.ds(i, 1)] = jnp.where(
            pending, jnp.int32(2), jnp.where(isnew, jnp.int32(1), jnp.int32(0))
        )[None]
        return carry

    jax.lax.fori_loop(0, block, row_body, 0)


@functools.partial(
    jax.jit,
    static_argnames=("max_probes", "block_rows", "interpret"),
)
def probe_insert_pallas_hbm(
    t_hi,
    t_lo,
    q_hi,
    q_lo,
    valid,
    max_probes: int = 32,
    block_rows: int = 4096,
    interpret: bool = False,
):
    """HBM-resident insert-or-find (no table-size VMEM gate); same
    contract and return shape as probe_insert_pallas."""
    import math

    cap = t_hi.shape[0]
    m = q_hi.shape[0]
    block = math.gcd(m, block_rows)
    grid = (m // block,)
    # real-TPU rank-1 tiling rejects a (1,)-block scalar output and bool
    # blocks at the engine's 256-row alignment (hardware windows 1-2,
    # TPU_WINDOW.json) — so flags cross the pallas_call boundary as ONE
    # ternary int32 lane (0 = seen, 1 = new, 2 = probe overflow) and the
    # wrapper splits the encoding.
    t_hi2, t_lo2, is_new3 = pl.pallas_call(
        functools.partial(_kernel_hbm, max_probes),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((block,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((cap,), jnp.uint32),
            jax.ShapeDtypeStruct((cap,), jnp.uint32),
            jax.ShapeDtypeStruct((m,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1,), jnp.uint32),
            pltpu.VMEM((1,), jnp.uint32),
            pltpu.VMEM((1,), jnp.uint32),
            pltpu.VMEM((1,), jnp.uint32),
            pltpu.SemaphoreType.DMA((4,)),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
    )(q_hi, q_lo, jnp.asarray(valid, jnp.int32), t_hi, t_lo)
    is_new = is_new3 == 1
    return (
        t_hi2,
        t_lo2,
        is_new,
        jnp.sum(is_new, dtype=jnp.int32),
        jnp.any(is_new3 == 2),
    )


@functools.partial(
    jax.jit,
    static_argnames=("max_probes", "block_rows", "interpret", "group"),
)
def probe_insert_pallas(
    t_hi,
    t_lo,
    q_hi,
    q_lo,
    valid,
    max_probes: int = 32,
    block_rows: int = 4096,
    interpret: bool = False,
    group: int = 1,
):
    """Pallas insert-or-find; same contract as hashset.probe_insert minus
    the claim lattice (sequential probing needs no parallel arbitration).

    Returns (t_hi', t_lo', is_new[M], n_new, overflow).  M must be a
    multiple of block_rows or smaller than it (the engine's buffers are
    powers of two).

    group > 1 selects the interleaved kernel (_kernel_grouped): `group`
    independent row chains probe per round, so the scalar unit pipelines
    their loads instead of serializing on one row's dependent-load chain;
    is_new winners and table membership are identical to group=1 (the
    in-register arbitration keeps commit order = row-index order).
    """
    import math

    cap = t_hi.shape[0]
    m = q_hi.shape[0]
    # largest divisor of m up to block_rows (engine buffers are 256-row
    # aligned, so blocks stay >= 256)
    block = math.gcd(m, block_rows)
    grid = (m // block,)
    if group > 1 and block % group == 0:
        kern = functools.partial(_kernel_grouped, max_probes, group)
    else:
        kern = functools.partial(_kernel, max_probes)
    # real-TPU rank-1 tiling rejects a (1,)-block scalar output and bool
    # blocks at the engine's 256-row alignment (hardware windows 1-2,
    # TPU_WINDOW.json) — so flags cross the pallas_call boundary as ONE
    # ternary int32 lane (0 = seen, 1 = new, 2 = probe overflow) and the
    # wrapper splits the encoding.
    t_hi2, t_lo2, is_new3 = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((cap,), lambda i: (0,)),
            pl.BlockSpec((cap,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((cap,), lambda i: (0,)),
            pl.BlockSpec((cap,), lambda i: (0,)),
            pl.BlockSpec((block,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((cap,), jnp.uint32),
            jax.ShapeDtypeStruct((cap,), jnp.uint32),
            jax.ShapeDtypeStruct((m,), jnp.int32),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
    )(q_hi, q_lo, jnp.asarray(valid, jnp.int32), t_hi, t_lo)
    is_new = is_new3 == 1
    return (
        t_hi2,
        t_lo2,
        is_new,
        jnp.sum(is_new, dtype=jnp.int32),
        jnp.any(is_new3 == 2),
    )
