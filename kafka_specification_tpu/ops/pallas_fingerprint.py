"""Pallas TPU kernel: fused murmur3 fingerprinting of packed state rows.

The per-candidate hot path of a BFS level hashes M ~ 10^6-10^7 rows of K
uint32 lanes twice (hi/lo seeds).  XLA already fuses the jnp implementation
(ops/fingerprint.py) well; this Pallas version exists to (a) keep both hash
streams and the sentinel masking in one VMEM-resident pass over the
candidate matrix, and (b) serve as the template for further Pallas work on
the dedup pipeline.  It is opt-in (`use_pallas=True` / KSPEC_USE_PALLAS=1)
and bit-identical to the jnp path — the test suite runs it in interpret
mode on CPU and compares exactly.

Grid: 1-D over row blocks of `block_rows`; each program hashes its block's
K lanes with both seeds and applies the invalid->sentinel mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fingerprint import hash_pair
from . import dedup


def _kernel(lanes_ref, valid_ref, hi_ref, lo_ref):
    # one authoritative hash implementation: the kernel body is plain jnp
    # over the VMEM-resident block, so it reuses ops.fingerprint directly
    # (including the sentinel-collision remap)
    lanes = lanes_ref[...]  # [block, K] uint32
    valid = valid_ref[...]  # [block] bool
    sent = jnp.uint32(dedup.SENT)
    hi, lo = hash_pair(lanes)
    hi_ref[...] = jnp.where(valid, hi, sent)
    lo_ref[...] = jnp.where(valid, lo, sent)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def fingerprint_pallas(lanes, valid, block_rows: int = 1024, interpret: bool = False):
    """uint32[M, K] x bool[M] -> (hi, lo) uint32[M] with invalid -> sentinel.

    M must be a multiple of block_rows (the engine's buffers are powers of
    two).  interpret=True runs the kernel in Pallas interpret mode (CPU CI).
    """
    m, k = lanes.shape
    assert m % block_rows == 0, (m, block_rows)
    grid = (m // block_rows,)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, k), lambda i: (i, 0)),
            pl.BlockSpec((block_rows,), lambda i: (i,)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows,), lambda i: (i,)),
            pl.BlockSpec((block_rows,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m,), jnp.uint32),
            jax.ShapeDtypeStruct((m,), jnp.uint32),
        ],
        interpret=interpret,
    )(lanes, valid)
