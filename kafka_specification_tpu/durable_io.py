"""The one recordable interposition layer for durable filesystem effects.

Every durable publish in this repo flows through a handful of idioms —
the two blessed atomic helpers (``storage/atomic.py``,
``obs/atomicio.py``), the O_APPEND journal emitters
(``resilience/heartbeat.py``, ``obs/fleettrace.py``), and a small set of
bare ``os.rename``/``os.replace``/``os.unlink`` protocol steps in the
queue/router/checkpoint/spill layers.  This module gives all of them one
shared vocabulary of primitives plus an optional *recorder* so the
crash-consistency harness (``resilience/crashcheck``) can capture the
exact op-trace a scenario issues and then enumerate every legal
post-crash filesystem state at every prefix of that trace.

With no recorder installed (the production default) every wrapper is a
direct pass-through to ``os`` — one ``is None`` check of overhead, zero
behavior change.  Recording never alters the effects either: ops are
logged *after* they succeed, and the recorder only ever reads files
back, never writes.

Op vocabulary (what the crash model reasons about):

``write``      whole-file content landed (tmp or in-place); ``fsynced``
               says whether the *data* is durable independent of any
               later rename
``append``     one O_APPEND record; appended data is never fsync'd, so
               the tail is always torn-able
``rename``     directory-entry op; durable only once the destination
               directory has been fsync'd (``fsync_dir``)
``unlink``     directory-entry op, same durability rule
``fsync_dir``  flushes every pending directory-entry op under that dir
``ack``        not a filesystem op — a scenario-level acknowledgement
               marker ("the client was told X"); invariants conditional
               on an ack apply only to crash points after it

Leaf contract: stdlib-only, zero intra-package imports.  Both blessed
atomic helpers import this module, so it must never pull numpy, jax, or
the native FpSet extension (the reason ``obs/atomicio.py`` exists as a
separate twin of ``storage/atomic.py`` in the first place).
"""

from __future__ import annotations

import os
import threading

__all__ = [
    "OpRecorder", "install", "recording",
    "replace", "rename", "unlink", "fsync_dir",
    "write_text", "append_text",
    "note_write", "note_append", "ack",
    "sweep_tmp", "set_fault_hook",
]

_LOCK = threading.Lock()
_RECORDER = None  # None in production: every wrapper is a pass-through
_FAULT_HOOK = None  # None in production: doing wrappers never inject

#: grace age for sweeping orphan tmps out of MULTI-writer directories
#: (queue state dirs, router routes, sweep manifests): a live writer's
#: in-flight tmp is milliseconds old, so only a tmp at least this stale
#: can be a mid-write death's orphan.  Single-owner structures sweep
#: with min_age_s=0 at open, exactly as before.
TMP_SWEEP_GRACE_S = float(os.environ.get("KSPEC_TMP_SWEEP_GRACE_S", "60"))


class OpRecorder:
    """Collects the op-trace of every durable effect under ``root``.

    Paths are stored root-relative with ``/`` separators; ops touching
    only paths outside the root are dropped (scratch files, unrelated
    tmpdirs).  ``ops`` is a list of plain dicts — the crash model and
    the machine-readable finding repro both consume it as-is."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.ops = []

    def rel(self, path: str):
        p = os.path.abspath(path)
        if p == self.root:
            return "."
        prefix = self.root + os.sep
        if not p.startswith(prefix):
            return None
        return p[len(prefix):].replace(os.sep, "/")

    def record(self, op: str, **fields) -> None:
        entry = {"op": op}
        for k, v in fields.items():
            if k in ("path", "src", "dst"):
                v = self.rel(v)
                if v is None:
                    return  # outside the recorded root: not ours
            entry[k] = v
        self.ops.append(entry)

    def ack(self, label: str, **fields) -> None:
        """Scenario-level acknowledgement marker (see module docstring)."""
        self.ops.append({"op": "ack", "label": label, **fields})


def install(recorder):
    """Install (or with ``None`` remove) the process-global recorder.
    Returns the previous recorder so callers can restore it."""
    global _RECORDER
    with _LOCK:
        prev = _RECORDER
        _RECORDER = recorder
    return prev


def recording() -> bool:
    return _RECORDER is not None


def _note(op: str, **fields) -> None:
    r = _RECORDER
    if r is not None:
        r.record(op, **fields)


# --- pure recording hooks (no filesystem effect of their own) -------------


def note_write(path: str, fsynced: bool) -> None:
    """Record that ``path`` now holds the bytes on disk (the caller just
    wrote and closed it).  Reads the file back ONLY when recording."""
    r = _RECORDER
    if r is None:
        return
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError:
        return
    r.record("write", path=path, data=data, fsynced=bool(fsynced))


def note_append(path: str, data) -> None:
    """Record one O_APPEND emit of ``data`` (bytes or str) to ``path``."""
    r = _RECORDER
    if r is None:
        return
    if isinstance(data, str):
        data = data.encode("utf-8", "replace")
    r.record("append", path=path, data=data)


def ack(label: str, **fields) -> None:
    """Scenario acknowledgement marker — no-op unless recording."""
    r = _RECORDER
    if r is not None:
        r.ack(label, **fields)


# --- fault injection seam (simfleet's flaky-fs model) ---------------------


def set_fault_hook(hook):
    """Install (``None`` removes) a callable ``hook(op, path) -> None``
    consulted at the START of every doing wrapper, before the effect.
    Raising ``OSError`` from the hook makes the op fail cleanly (nothing
    happened on disk) — the flaky-filesystem model the deterministic
    fleet simulation (``resilience/simfleet``) drives, exercising every
    ``retry_transient`` envelope in virtual time.  Returns the previous
    hook.  Production default ``None``: one ``is None`` check per op."""
    global _FAULT_HOOK
    with _LOCK:
        prev = _FAULT_HOOK
        _FAULT_HOOK = hook
    return prev


def _fault(op: str, path: str) -> None:
    h = _FAULT_HOOK
    if h is not None:
        h(op, path)


# --- doing wrappers (perform the effect, then record it) ------------------


def replace(src: str, dst: str) -> None:
    _fault("rename", dst)
    os.replace(src, dst)
    _note("rename", src=src, dst=dst)


def rename(src: str, dst: str) -> None:
    _fault("rename", dst)
    os.rename(src, dst)
    _note("rename", src=src, dst=dst)


def unlink(path: str) -> None:
    _fault("unlink", path)
    os.unlink(path)
    _note("unlink", path=path)


def fsync_dir(path: str) -> None:
    """Best-effort fsync of a directory entry (some filesystems refuse
    O_RDONLY dir fsync; the data-file fsync already happened either
    way).  Recorded even when the fsync itself is refused: the caller
    *issued* the barrier, which is what the crash model checks for."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        _note("fsync_dir", path=path or ".")
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
    _note("fsync_dir", path=path or ".")


def write_text(path: str, text: str, fsync: bool = False) -> None:
    """In-place (non-atomic) whole-file text write, recorded.  For
    sidecars whose torn state is tolerated by every reader (claim
    leases, tenant admission markers) — anything a reader must never
    see torn goes through an atomic helper instead."""
    _fault("write", path)
    with open(path, "w") as fh:
        fh.write(text)
        fh.flush()
        if fsync:
            os.fsync(fh.fileno())
    note_write(path, fsynced=fsync)


def append_text(path: str, text: str) -> None:
    """One buffered O_APPEND text emit, recorded."""
    _fault("append", path)
    with open(path, "a") as fh:
        fh.write(text)
    note_append(path, text)


# --- the shared startup janitor ------------------------------------------


def sweep_tmp(directory: str, min_age_s: float = 0.0) -> list:
    """Startup janitor: remove stale ``.tmp`` siblings (``x.tmp``,
    ``x.<nonce>.tmp``, ``x.tmp.npz`` checkpoint tmps) left by a
    mid-write death.  Safe by construction — no manifest ever references
    a tmp name.  ``min_age_s > 0`` (pass :data:`TMP_SWEEP_GRACE_S`)
    spares young tmps for the multi-writer directories where a sibling
    may legitimately be mid-promote right now.  Returns the removed
    paths.  This is the canonical copy; ``storage.atomic.sweep_tmp``
    re-exports it."""
    removed = []
    if not os.path.isdir(directory):
        return removed
    now = None
    for name in os.listdir(directory):
        if not (name.endswith(".tmp") or ".tmp." in name):
            continue
        p = os.path.join(directory, name)
        if not os.path.isfile(p):
            continue
        if min_age_s > 0.0:
            if now is None:
                import time

                now = time.time()
            try:
                if now - os.path.getmtime(p) < min_age_s:
                    continue  # possibly a live writer's in-flight tmp
            except OSError:
                continue  # promoted or collected under us: not an orphan
        try:
            os.unlink(p)
            removed.append(p)
        except OSError:
            pass
    return removed
