from .interp import OracleAction, OracleModel, OracleResult, oracle_bfs

__all__ = ["OracleAction", "OracleModel", "OracleResult", "oracle_bfs"]
