"""Slow, obviously-correct reference interpreter ("the oracle").

Plays the role stock TLC would play for golden outputs (TLC is a Java tool
and is not available in this environment): each TLA+ module of the reference
corpus is transcribed 1:1 into Python set semantics (states as canonical
immutable values, actions as successor generators), and an explicit BFS
produces distinct-state counts, per-level counts, diameters and first
violations.  The JAX kernels are validated against this interpreter by exact
state-set comparison per BFS level (tests/), which is how we keep the tensor
kernels *provably* equivalent to the TLA+ semantics (SURVEY.md §7 step 2).

The interpreter deliberately shares no code with the kernel path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional, Sequence


@dataclass(frozen=True)
class OracleAction:
    name: str
    # state -> iterable of successor states (already canonical/immutable)
    successors: Callable[[object], Iterable[object]]


@dataclass
class OracleModel:
    name: str
    init_states: Callable[[], Sequence[object]]
    actions: Sequence[OracleAction]
    invariants: Sequence[tuple[str, Callable[[object], bool]]]
    constraint: Optional[Callable[[object], bool]] = None
    # same vocabulary as Model.meta (drives TLA-style trace rendering)
    meta: dict = field(default_factory=dict)


@dataclass
class OracleResult:
    levels: list[int]
    level_sets: list[set]
    total: int
    diameter: int
    violation: Optional[tuple[str, int, object]]  # (invariant, depth, state)
    trace: list = field(default_factory=list)  # [(action_name, state), ...]

    @property
    def ok(self) -> bool:
        return self.violation is None


def oracle_bfs(
    model: OracleModel,
    max_depth: Optional[int] = None,
    max_states: Optional[int] = None,
    stop_on_violation: bool = True,
    keep_level_sets: bool = True,
    check_deadlock: bool = False,
) -> OracleResult:
    """check_deadlock: report a state with no successors as a violation of
    the pseudo-invariant "Deadlock" (TLC's CHECK_DEADLOCK TRUE).  Note: an
    oracle model whose generators bake constraint bounds into the guards
    (AsyncIsr) treats constraint-pruned successors as absent here."""
    inits = list(dict.fromkeys(model.init_states()))
    visited = set(inits)
    parent = {s: (None, "<init>") for s in inits}
    frontier = inits
    levels = [len(inits)]
    level_sets = [set(inits)] if keep_level_sets else []
    violation = None
    depth = 0

    def check(states, d):
        for name, pred in model.invariants:
            for s in states:
                if not pred(s):
                    return (name, d, s)
        return None

    violation = check(frontier, 0)
    while frontier and violation is None:
        if max_depth is not None and depth >= max_depth:
            break
        if max_states is not None and len(visited) >= max_states:
            break
        nxt = []
        for s in frontier:
            any_succ = False
            for a in model.actions:
                for t in a.successors(s):
                    any_succ = True
                    if model.constraint is not None and not model.constraint(t):
                        continue
                    if t not in visited:
                        visited.add(t)
                        parent[t] = (s, a.name)
                        nxt.append(t)
            if check_deadlock and not any_succ and violation is None:
                violation = ("Deadlock", depth, s)
        if violation is not None and check_deadlock and violation[0] == "Deadlock":
            frontier = []
            break
        depth += 1
        if nxt:
            levels.append(len(nxt))
            if keep_level_sets:
                level_sets.append(set(nxt))
        if stop_on_violation:
            violation = check(nxt, depth)
        frontier = nxt

    trace = []
    if violation is not None:
        s = violation[2]
        while s is not None:
            p, aname = parent[s]
            trace.append((aname, s))
            s = p
        trace.reverse()

    return OracleResult(
        levels=levels,
        level_sets=level_sets,
        total=len(visited),
        diameter=len(levels) - 1,
        violation=violation,
        trace=trace,
    )
