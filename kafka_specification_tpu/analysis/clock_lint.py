"""Raw-clock discipline lint: clock-migrated control-plane modules must
take time from the injectable shim.

The deterministic fleet simulation (``resilience/simfleet``) can only
own what flows through ``utils/clock.py``: a raw ``time.time()`` /
``time.sleep()`` / ``time.monotonic()`` in a migrated module is a
timing decision the virtual clock never sees — the simulated schedule
silently reads the REAL wall clock there, and the whole
same-seed-same-run guarantee dissolves.  This lint pins the boundary:
in the modules listed in :data:`CLOCK_MIGRATED`, the three raw idioms
may appear only inside ``utils/clock.py`` itself (where the real calls
live).

Pure *formatting* of an already-taken stamp (``time.strftime``,
``time.gmtime``) and profiling reads (``time.perf_counter``) are not
timing decisions and are not flagged.

A site that genuinely must read real time regardless of any installed
virtual clock carries a reasoned suppression on its own line or up to
two lines above (the ``durable-io`` lint's exact idiom)::

    # kspec: allow(raw-clock) <why this must be the real clock>

A bare tag with no reason is itself a finding.  Wired into
``cli analyze`` as HIGH ``raw-clock`` findings and pinned at zero by a
tier-1 test, with a seeded-mutant test proving the lint actually fires.
"""

from __future__ import annotations

import os
import re
from typing import Optional

# the shim itself: the only migrated file allowed the raw calls
_SHIM = "kafka_specification_tpu/utils/clock.py"

#: the clock-migrated set — every module whose timing decisions the
#: simulation kernel owns.  Grows as modules migrate; a module listed
#: here may never regress to the raw idioms.
CLOCK_MIGRATED = (
    _SHIM,
    "kafka_specification_tpu/service/queue.py",
    "kafka_specification_tpu/service/router.py",
    "kafka_specification_tpu/service/daemon.py",
    "kafka_specification_tpu/service/fleet.py",
    "kafka_specification_tpu/service/state_cache.py",
    "kafka_specification_tpu/service/scheduler.py",
    "kafka_specification_tpu/resilience/heartbeat.py",
    "kafka_specification_tpu/resilience/retry.py",
    "kafka_specification_tpu/resilience/supervisor.py",
    "kafka_specification_tpu/obs/fleettrace.py",
    "kafka_specification_tpu/resilience/simfleet/simclock.py",
    "kafka_specification_tpu/resilience/simfleet/kernel.py",
    "kafka_specification_tpu/resilience/simfleet/oracles.py",
    "kafka_specification_tpu/resilience/simfleet/search.py",
)

_DOCSTRING_RE = re.compile(r'""".*?"""|\'\'\'.*?\'\'\'', re.S)

_RAW_CLOCK_RE = re.compile(
    r"\btime\.(time|sleep|monotonic)\s*\("
    r"|\bfrom\s+time\s+import\s+[^\n]*\b(time|sleep|monotonic)\b"
)

_ALLOW_RE = re.compile(r"#\s*kspec:\s*allow\(raw-clock\)\s*(.*)")


def _allowed(lines: list, lineno: int):
    """(suppressed, reason-missing) for a 1-based finding line: the tag
    counts on the line itself or either of the two lines above."""
    for ln in (lineno, lineno - 1, lineno - 2):
        if 1 <= ln <= len(lines):
            m = _ALLOW_RE.search(lines[ln - 1])
            if m:
                return True, not m.group(1).strip()
    return False, False


def lint_raw_clock(package_root: Optional[str] = None) -> list:
    """Static clock-boundary lint over :data:`CLOCK_MIGRATED`.  Returns
    ``{path, line, problem}`` findings (empty = clean); wired into
    ``cli analyze`` and pinned by a tier-1 test so no timing decision
    can drift back outside the simulation's reach."""
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))
    repo = os.path.dirname(package_root)
    pkg_name = os.path.basename(package_root)
    findings = []
    for listed in CLOCK_MIGRATED:
        if listed == _SHIM:
            continue
        # the listed paths are canonical-repo-relative; re-anchor them
        # under the given root so seeded-mutant tests can lint a copy
        rel_in_pkg = listed.split("/", 1)[1]
        path = os.path.join(package_root, *rel_in_pkg.split("/"))
        rel = f"{pkg_name}/{rel_in_pkg}"
        try:
            with open(path) as fh:
                src = fh.read()
        except OSError:
            continue  # a trimmed package copy: absent modules are clean
        # docstrings quote the raw idiom as documentation; only real
        # code sites count (comments still count: the allow-tag
        # machinery below is how a comment legitimizes a site)
        scrubbed = _DOCSTRING_RE.sub(
            lambda m: "\n" * m.group(0).count("\n"), src
        )
        lines = src.splitlines()
        for m in _RAW_CLOCK_RE.finditer(scrubbed):
            lineno = scrubbed[: m.start()].count("\n") + 1
            code = lines[lineno - 1]
            if code.lstrip().startswith("#"):
                continue  # comment-only mentions are not sites
            suppressed, bare = _allowed(lines, lineno)
            if suppressed and not bare:
                continue
            findings.append({
                "path": rel,
                "line": lineno,
                "problem": (
                    "kspec: allow(raw-clock) tag carries no reason — "
                    "state why this site must read the real clock"
                ) if suppressed else (
                    "raw time.time/sleep/monotonic in a clock-migrated "
                    "module — the simfleet virtual clock never sees "
                    "this timing decision; route it through "
                    "utils/clock.py (now/sleep/monotonic)"
                ),
            })
    return findings
