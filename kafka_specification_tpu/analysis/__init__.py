"""`kspec analyze` — static analysis of the specs and the engine.

Three passes close the verdict-trust gap from the build side (PR 9's
digest chains close it from the runtime side):

1. **Encoding soundness** (analysis/encoding.py): interval abstract
   interpretation of every action kernel over the declared tensor
   schema proves each written field stays within its packed range —
   the general form of the hand-written AsyncIsr "N <= 4" cliff check,
   applied to every model and config at build time.  An unsound
   (config, schema) pair refuses to explore with a machine-readable
   interval counterexample instead of returning a wrong verdict.
2. **Action/guard lint** (same module): vacuous guards, frame-condition
   violations against declared write sets, read-of-unwritten /
   dead-field detection.
3. **Concurrency ownership** (analysis/ownership.py): the engine's
   thread contract (docs/engine.md § Async execution) declared as
   machine-readable ``THREAD_CONTRACT`` annotations on overlap.py,
   storage/tiered.py and resilience/checkpoints.py, verified by an AST
   pass; ``KSPEC_TSAN=1`` arms a runtime sanitizer that asserts the
   same ownership on every attribute write (test-only).

Front doors: ``cli analyze [--json]`` (jax-free; exits non-zero on any
HIGH finding; emits the schema-versioned ``kspec-analysis/1`` record)
and the build gates in ``utils/cfg.build_model`` / ``engine.bfs.check``
/ ``parallel.sharded.check_sharded`` (KSPEC_ANALYZE=0 disables).

This package must stay importable without jax: heavy passes live in
submodules imported lazily, and :func:`install_jax_stub` lets the model
modules (which bind ``jnp`` at import) load on a box with no working
accelerator stack at all.
"""

from __future__ import annotations

import os
import sys
import types
from dataclasses import dataclass, field as dc_field
from typing import Optional

#: the machine-readable findings record version (mirrors kspec-verdict/1)
ANALYSIS_SCHEMA = "kspec-analysis/1"

SEVERITIES = ("HIGH", "MEDIUM", "LOW", "INFO")

ANALYZE_ENV = "KSPEC_ANALYZE"


@dataclass(frozen=True)
class Finding:
    """One analysis finding, machine-readable.

    kind: spec-width | encoding-overflow | frame-violation |
          vacuous-action |
          read-of-unwritten-field | dead-field | analysis-skip |
          ownership-breach | unlocked-shared-write |
          unannotated-attribute | stale-annotation | worker-unsafe-write |
          host-materialization | set-iteration-order
    """

    kind: str
    severity: str
    target: str
    message: str
    data: dict = dc_field(default_factory=dict)
    suppressed: Optional[str] = None  # justification when downgraded

    def record(self) -> dict:
        out = {"kind": self.kind, "severity": self.severity,
               "target": self.target, "message": self.message,
               "data": self.data}
        if self.suppressed:
            out["suppressed"] = self.suppressed
        return out


def analysis_record(findings, targets=()) -> dict:
    """The stable ``kspec-analysis/1`` findings record (`cli analyze
    --json`); one schema for CI, the tier-1 gate and operators."""
    counts = {s: 0 for s in SEVERITIES}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    return {
        "schema": ANALYSIS_SCHEMA,
        "targets": list(targets),
        "findings": [f.record() for f in findings],
        "counts": counts,
        "ok": counts.get("HIGH", 0) == 0,
    }


def analysis_enabled() -> bool:
    """The build-gate kill switch (documented escape hatch)."""
    return os.environ.get(ANALYZE_ENV, "1").strip().lower() not in (
        "0", "off", "false", "no"
    )


# --------------------------------------------------------------------------
# jax-free model imports (`cli analyze` on a box with no accelerator stack)
# --------------------------------------------------------------------------


def install_jax_stub() -> bool:
    """Make ``import jax.numpy as jnp`` succeed WITHOUT importing jax.

    The model modules bind ``jnp`` at import time but only *use* it
    inside kernels — which the abstract interpreter runs with ``jnp``
    rebound to the interval namespace.  The stub raises on any attribute
    access, so a code path that genuinely needs jax fails loudly instead
    of silently degrading.  Installed only when jax is absent (or
    poisoned with a None sys.modules sentinel); a process that already
    imported the real jax keeps it.  Returns True when the stub was
    installed."""
    if sys.modules.get("jax") is not None and "jax" in sys.modules:
        return False

    class _StubModule(types.ModuleType):
        def __getattr__(self, name):
            if name.startswith("__"):
                raise AttributeError(name)
            raise RuntimeError(
                f"jax.{name} accessed under the kspec-analyze jax stub — "
                f"the static-analysis path is jax-free by contract "
                f"(docs/analysis.md)"
            )

    jax = _StubModule("jax")
    jnp = _StubModule("jax.numpy")
    jax.numpy = jnp
    sys.modules["jax"] = jax
    sys.modules["jax.numpy"] = jnp
    return True


# --------------------------------------------------------------------------
# the engine build gate
# --------------------------------------------------------------------------

#: process-wide memo of verified model shapes — re-building the same
#: (module, config), which tests do hundreds of times, re-verifies
#: nothing.  The key is the full structural identity, NOT just the name:
#: emitted names drop constants, and a same-named model with different
#: field bounds or action structure must not ride a sibling's pass.
_VERIFIED_MODELS: set = set()


def _model_memo_key(model):
    try:
        return (
            model.name,
            tuple((f.name, f.shape, f.lo, f.hi)
                  for f in model.spec.fields),
            # kernel CODE identity matters: two same-shaped models with
            # different kernel bodies must not share a verification
            # (code objects are shared across rebuilds of the same
            # factory, so the memo still hits where it should)
            tuple((a.name, a.n_choices, getattr(a, "writes", None),
                   getattr(a.kernel, "__code__", None))
                  for a in model.actions),
        )
    except Exception:  # duck-typed test doubles: no memo, just verify
        return None


def require_encoding_sound(model) -> None:
    """Refuse to explore an encoding-unsound model (the check/check_sharded
    and build_model gate).  Raises analysis.encoding.EncodingUnsound (a
    ValueError) carrying the interval counterexample; KSPEC_ANALYZE=0
    skips.  Memoized on the model's structural identity (name + field
    bounds + action inventory), so a rebuilt same-config model costs
    nothing."""
    if not analysis_enabled():
        return
    key = _model_memo_key(model)
    if key is not None and key in _VERIFIED_MODELS:
        return
    from .encoding import verify_model_encoding

    verify_model_encoding(model)
    if key is not None:
        _VERIFIED_MODELS.add(key)


# --------------------------------------------------------------------------
# full-repo analysis (the `cli analyze` driver)
# --------------------------------------------------------------------------

#: the engine modules the ownership/purity passes cover (repo-relative)
OWNERSHIP_MODULES = (
    "kafka_specification_tpu/overlap.py",
    "kafka_specification_tpu/storage/tiered.py",
    "kafka_specification_tpu/resilience/checkpoints.py",
)
PURITY_MODULES = (
    "kafka_specification_tpu/engine/pipeline.py",
    "kafka_specification_tpu/parallel/sharded.py",
    # the device-resident level pipeline's in-jit helpers: a host-side
    # np.*/.item() call inside the while_loop body must fail CI
    "kafka_specification_tpu/ops/devlevel.py",
)


def field_hulls(model, strict: bool = False) -> dict:
    """Stable per-field reachable-value hull export (lazy import; see
    analysis/encoding.py:field_hulls for the soundness contract)."""
    from .encoding import field_hulls as _fh

    return _fh(model, strict=strict)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def analyze_engine_sources(root: Optional[str] = None) -> list:
    """Ownership-contract + purity/order lint over the engine sources."""
    from .ownership import check_module_contract, lint_purity

    root = root or repo_root()
    findings = []
    for rel in OWNERSHIP_MODULES:
        findings += check_module_contract(os.path.join(root, rel), rel)
    for rel in PURITY_MODULES:
        findings += lint_purity(os.path.join(root, rel), rel)
    return findings
