"""Concurrency-ownership checker + engine purity/order lint + KSPEC_TSAN.

PR 10 made the engine multi-threaded (AsyncWorker background merges,
async checkpoint writes, the two-slot staged chunk pipeline); the
ownership rules lived only in docs/engine.md prose.  This module makes
them machine-checked three ways:

1. **Annotation vocabulary**: each participating module declares a
   module-level ``THREAD_CONTRACT`` dict::

       THREAD_CONTRACT = {
           "schema": "kspec-ownership/1",
           "classes": {
               "AsyncWorker": {
                   "lock": "_cv",                  # guard for shared state
                   "shared_locked": [...],         # mutate only under lock
                   "engine_only": [...],           # submitting thread only
                   "immutable_after_init": [...],  # set once in __init__
                   "worker_methods": [...],        # run on the worker
                   "worker_safe": [...],           # any thread, no self-mutation
               },
           },
       }

   Nested functions handed to ``*.submit(...)`` (or wrapped in
   ``AsyncJob(...)``) are worker context too — the checker discovers
   them syntactically, plus every method transitively self-called from
   worker context.

2. **AST pass** (:func:`check_module_contract`): flags attribute
   mutations (assignments AND mutating container calls like
   ``self._q.append``) that break the contract — engine-only state
   mutated from worker context, shared state mutated outside a
   ``with self.<lock>:`` block, immutable state rebound after
   ``__init__``, and *unannotated* attributes mutated anywhere outside
   ``__init__`` (the "nobody decided who owns this" class).  Inline
   suppression with justification: ``# kspec: allow(<kind>) <reason>``
   on the flagged line.

3. **Runtime sanitizer** (``KSPEC_TSAN=1``, test-only): modules call
   :func:`bind_contract` at import; when armed, annotated classes get a
   checking ``__setattr__`` that asserts the same ownership on every
   write — engine-only attrs must not be written from a registered
   worker thread, shared attrs only with the lock held, immutables only
   once.  AsyncWorker registers its thread via
   :func:`register_worker_thread`, so the overlap fault-matrix tests
   double as a race harness.

The purity/order lint (:func:`lint_purity`) covers the other
self-application class from the issue: functions annotated
``# kspec: traced`` (the jit-traced stage helpers) must not
host-materialize traced values (``np.*``, ``int()``/``float()``,
``.item()``, ``.tolist()``, ``jax.device_get``), and no engine module
may iterate a ``set``/``frozenset`` directly in a ``for`` (PYTHONHASHSEED-
dependent order; wrap in ``sorted(...)``).

Everything here is stdlib-only (jax-free, numpy-free).
"""

from __future__ import annotations

import ast
import os
import re
import threading
from typing import Optional

from . import Finding

OWNERSHIP_SCHEMA = "kspec-ownership/1"
TSAN_ENV = "KSPEC_TSAN"

_ALLOW_RE = re.compile(r"#\s*kspec:\s*allow\(([\w-]+)\)\s*(.*)")
_TRACED_RE = re.compile(r"#\s*kspec:\s*traced\b")

#: container methods that mutate their receiver (the deque/list/dict/set
#: surface the engine actually uses)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "clear", "pop", "popleft", "popitem", "update",
    "setdefault", "sort", "reverse",
}


class OwnershipViolation(AssertionError):
    """KSPEC_TSAN runtime ownership assertion failure."""


# --------------------------------------------------------------------------
# runtime sanitizer
# --------------------------------------------------------------------------

_WORKER_THREADS: set = set()
_WT_LOCK = threading.Lock()


def tsan_enabled() -> bool:
    return os.environ.get(TSAN_ENV, "").strip().lower() in (
        "1", "on", "true", "yes"
    )


def register_worker_thread(thread: threading.Thread) -> None:
    """Called by overlap.AsyncWorker when its thread starts (no-op cost
    when TSAN is off beyond one set insert)."""
    with _WT_LOCK:
        _WORKER_THREADS.add(thread.ident or id(thread))


def unregister_worker_thread(thread: threading.Thread) -> None:
    with _WT_LOCK:
        _WORKER_THREADS.discard(thread.ident or id(thread))


def on_worker_thread() -> bool:
    ident = threading.get_ident()
    with _WT_LOCK:
        return ident in _WORKER_THREADS


def _checking_setattr(cls, contract: dict):
    engine_only = set(contract.get("engine_only", ()))
    shared = set(contract.get("shared_locked", ()))
    immutable = set(contract.get("immutable_after_init", ()))
    lock_name = contract.get("lock")
    orig = cls.__setattr__

    def __setattr__(self, name, value):
        if id(self) in _IN_INIT:
            # construction precedes publication: __init__ writes are
            # single-threaded by contract (the static checker enforces
            # that nothing ELSE runs before the constructor returns)
            orig(self, name, value)
            return
        if name in engine_only and on_worker_thread():
            raise OwnershipViolation(
                f"{cls.__name__}.{name} is engine-thread-only but was "
                f"written from worker thread "
                f"{threading.current_thread().name!r} (THREAD_CONTRACT; "
                f"docs/analysis.md)"
            )
        if name in immutable and hasattr(self, name):
            raise OwnershipViolation(
                f"{cls.__name__}.{name} is immutable-after-init but was "
                f"rebound (THREAD_CONTRACT)"
            )
        if name in shared and lock_name is not None:
            lock = getattr(self, lock_name, None)
            owned = getattr(lock, "_is_owned", None)
            if lock is not None and owned is not None and not owned():
                raise OwnershipViolation(
                    f"{cls.__name__}.{name} is shared state but was "
                    f"written without holding {lock_name} "
                    f"(THREAD_CONTRACT)"
                )
        orig(self, name, value)

    return __setattr__


#: objects currently inside their (sanitized) constructor
_IN_INIT: set = set()

#: classes registered via bind_contract, with their contracts
_BOUND: list = []
#: armed classes -> their original (__setattr__, __init__)
_ARMED: dict = {}


def _checking_init(cls):
    orig_init = cls.__init__

    def __init__(self, *a, **k):
        _IN_INIT.add(id(self))
        try:
            orig_init(self, *a, **k)
        finally:
            _IN_INIT.discard(id(self))

    return __init__


def bind_contract(module_globals: dict, contract: dict) -> None:
    """Register a module's THREAD_CONTRACT classes for the runtime
    sanitizer; arm immediately when KSPEC_TSAN=1 (zero overhead
    otherwise — the static checker reads the contract straight from the
    source either way)."""
    for cls_name, c in contract.get("classes", {}).items():
        cls = module_globals.get(cls_name)
        if cls is not None:
            _BOUND.append((cls, c))
    if tsan_enabled():
        arm_all()


def arm_all() -> int:
    """Install the checking __setattr__/__init__ on every registered
    class (tests arm/disarm around a TSAN scenario; KSPEC_TSAN=1 arms
    at import).  Returns the number of classes armed."""
    n = 0
    for cls, c in _BOUND:
        if cls in _ARMED:
            continue
        _ARMED[cls] = (cls.__setattr__, cls.__init__)
        cls.__setattr__ = _checking_setattr(cls, c)
        cls.__init__ = _checking_init(cls)
        n += 1
    return n


def disarm_all() -> None:
    """Restore the original __setattr__/__init__ on every armed class."""
    for cls, (s, i) in _ARMED.items():
        cls.__setattr__ = s
        cls.__init__ = i
    _ARMED.clear()


# --------------------------------------------------------------------------
# static contract checker
# --------------------------------------------------------------------------


def _literal_contract(tree: ast.Module) -> Optional[dict]:
    """Extract the module-level THREAD_CONTRACT literal, or None."""
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "THREAD_CONTRACT"):
            try:
                return ast.literal_eval(node.value)
            except ValueError:
                return None
    return None


def _allow_reasons(source: str) -> dict:
    """lineno -> (kind, reason) for `# kspec: allow(kind) reason` lines."""
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _ALLOW_RE.search(line)
        if m:
            out[i] = (m.group(1), m.group(2).strip() or "allowed")
    return out


def _allow_match(allows: dict, lineno: int, kinds) -> bool:
    """THE suppression-window rule, shared by the ownership and purity
    passes: an allow() comment matches on the flagged line or up to
    three lines above (black-formatted code rarely has room on the
    statement line itself)."""
    for ln in range(lineno, max(0, lineno - 4), -1):
        a = allows.get(ln)
        if a is not None and a[0] in kinds:
            return True
    return False


def _self_root_attr(node) -> Optional[str]:
    """For an attribute/subscript chain rooted at `self`, the FIRST
    attribute after self (`self.deleter.pending` -> "deleter") — a
    mutation anywhere down the chain reaches state owned through that
    root attribute.  None when the chain is not self-rooted."""
    attr = None
    cur = node
    while True:
        if isinstance(cur, ast.Attribute):
            attr = cur.attr
            cur = cur.value
        elif isinstance(cur, ast.Subscript):
            cur = cur.value
        elif isinstance(cur, ast.Name):
            return attr if cur.id == "self" else None
        else:
            return None


def _self_attr_writes(fn: ast.AST, exclude=()):
    """Yield (attr, lineno, via_call) for self-attribute mutations inside
    one function body.  Nested function defs are descended into EXCEPT
    the ids in `exclude` (worker-submitted closures, which get their own
    worker-context classification) — an un-submitted nested callback
    inherits its enclosing method's context, so its mutations are never
    invisible to the checker."""
    excluded = set(exclude)

    class V(ast.NodeVisitor):
        def __init__(self):
            self.out = []

        def visit_FunctionDef(self, node):
            if node is fn or id(node) not in excluded:
                self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def _target(self, t):
            # self.x = / self.x[...] = / self.a.b = / (a, self.x) = ...
            # — any self-rooted chain mutates state reached through its
            # root attribute
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._target(e)
                return
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                root = _self_root_attr(t)
                if root is not None:
                    self.out.append((root, t.lineno, False))

        def visit_Assign(self, node):
            for t in node.targets:
                self._target(t)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._target(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            if node.value is not None:
                self._target(node.target)
            self.generic_visit(node)

        def visit_Delete(self, node):
            for t in node.targets:
                self._target(t)
            self.generic_visit(node)

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                # self.<chain>.append(...) — any depth, incl. subscripts
                root = _self_root_attr(f.value)
                if root is not None:
                    self.out.append((root, node.lineno, True))
            self.generic_visit(node)

    v = V()
    v.visit(fn)
    return v.out


def _lock_spans(fn: ast.AST, lock_name: str):
    """Line ranges covered by `with self.<lock_name>` blocks in fn."""
    spans = []

    class V(ast.NodeVisitor):
        def visit_With(self, node):
            for item in node.items:
                e = item.context_expr
                if (isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self"
                        and e.attr == lock_name):
                    last = node.body[-1]
                    spans.append((node.lineno,
                                  getattr(last, "end_lineno",
                                          last.lineno)))
            self.generic_visit(node)

    V().visit(fn)
    return spans


def _self_calls(fn: ast.AST) -> set:
    """Names of methods this function calls as self.<m>(...)."""
    out = set()

    class V(ast.NodeVisitor):
        def visit_Call(self, node):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"):
                out.add(f.attr)
            self.generic_visit(node)

    V().visit(fn)
    return out


def _submitted_nested(fn: ast.AST) -> list:
    """Nested FunctionDefs inside `fn` whose NAME is passed to a
    `*.submit(...)` call or an `AsyncJob(...)` constructor — they run on
    the worker thread."""
    nested = {n.name: n for n in ast.walk(fn)
              if isinstance(n, ast.FunctionDef) and n is not fn}
    if not nested:
        return []
    hits = []

    class V(ast.NodeVisitor):
        def visit_Call(self, node):
            f = node.func
            is_submit = isinstance(f, ast.Attribute) and f.attr == "submit"
            is_job = isinstance(f, ast.Name) and f.id == "AsyncJob"
            if is_submit or is_job:
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id in nested:
                        hits.append(nested[a.id])
            self.generic_visit(node)

    V().visit(fn)
    return hits


def check_module_contract(path: str, rel: str) -> list:
    """Verify one module's THREAD_CONTRACT annotations; returns findings.

    A module without a THREAD_CONTRACT yields a single MEDIUM finding
    when it is in the declared ownership scope (the caller only passes
    modules that must carry one)."""
    with open(path) as fh:
        source = fh.read()
    tree = ast.parse(source, filename=path)
    allows = _allow_reasons(source)
    contract = _literal_contract(tree)
    findings: list = []
    if contract is None:
        return [Finding(
            kind="unannotated-attribute", severity="MEDIUM",
            target=rel,
            message=f"{rel} has threaded classes but no THREAD_CONTRACT",
            data={"module": rel},
        )]

    classes = {n.name: n for n in tree.body if isinstance(n, ast.ClassDef)}
    for cls_name, c in contract.get("classes", {}).items():
        node = classes.get(cls_name)
        if node is None:
            findings.append(Finding(
                kind="stale-annotation", severity="LOW",
                target=f"{rel}:{cls_name}",
                message=f"THREAD_CONTRACT names missing class {cls_name}",
                data={"class": cls_name},
            ))
            continue
        findings += _check_class(node, c, rel, allows)

    # classes with threaded surface but no contract entry: a class that
    # references a worker/submit and is not annotated
    annotated = set(contract.get("classes", {}))
    for cls_name, node in classes.items():
        if cls_name in annotated:
            continue
        src = ast.get_source_segment(source, node) or ""
        if ".submit(" in src or "AsyncJob(" in src:
            findings.append(Finding(
                kind="unannotated-attribute", severity="MEDIUM",
                target=f"{rel}:{cls_name}",
                message=(
                    f"class {cls_name} interacts with a worker but has "
                    f"no THREAD_CONTRACT entry"
                ),
                data={"class": cls_name},
            ))
    return findings


def _check_class(node: ast.ClassDef, c: dict, rel: str,
                 allows: dict) -> list:
    findings: list = []
    engine_only = set(c.get("engine_only", ()))
    shared = set(c.get("shared_locked", ()))
    immutable = set(c.get("immutable_after_init", ()))
    worker_safe = set(c.get("worker_safe", ()))
    lock_name = c.get("lock")
    known = engine_only | shared | immutable
    methods = {m.name: m for m in node.body
               if isinstance(m, ast.FunctionDef)}

    # context classification: worker = declared worker methods + nested
    # submitted functions + transitive self-calls from worker context
    worker_fns: list = []
    worker_names = set(c.get("worker_methods", ()))
    for name in worker_names:
        if name in methods:
            worker_fns.append(methods[name])
    submitted: list = []
    for m in methods.values():
        submitted.extend(_submitted_nested(m))
    worker_fns.extend(submitted)
    # submitted closures are walked in worker context; every OTHER
    # nested function inherits its enclosing method's context
    submitted_ids = {id(n) for n in submitted}
    # close worker context over self.<m>() calls
    frontier = list(worker_fns)
    while frontier:
        fn = frontier.pop()
        for callee in _self_calls(fn):
            if callee in methods and callee not in worker_names:
                worker_names.add(callee)
                worker_fns.append(methods[callee])
                frontier.append(methods[callee])

    worker_ids = {id(f) for f in worker_fns}
    seen_attrs: set = set()

    def engine_ctx_fns():
        for name, m in methods.items():
            if name not in worker_names:
                yield name, m

    def _suppressed(lineno, kind):
        # allow(ownership) is the category-wide form
        return _allow_match(allows, lineno, (kind, "ownership"))

    # worker-context mutations
    for fn in worker_fns:
        in_worker_safe = getattr(fn, "name", "") in worker_safe
        spans = _lock_spans(fn, lock_name) if lock_name else []
        for attr, lineno, via_call in _self_attr_writes(
                fn, exclude=submitted_ids):
            seen_attrs.add(attr)
            if attr in shared:
                if _suppressed(lineno, "unlocked-shared-write"):
                    continue
                if not any(a <= lineno <= b for a, b in spans):
                    findings.append(Finding(
                        kind="unlocked-shared-write", severity="HIGH",
                        target=f"{rel}:{lineno}",
                        message=(
                            f"{node.name}.{attr} is shared_locked but "
                            f"written without `with self.{lock_name}` "
                            f"(worker context, {getattr(fn, 'name', '?')})"
                        ),
                        data={"class": node.name, "attr": attr,
                              "line": lineno},
                    ))
                continue
            kind = ("ownership-breach" if attr in engine_only
                    or attr in immutable else "unannotated-attribute")
            if _suppressed(lineno, kind):
                continue
            # unannotated mutation is HIGH in WORKER context (nobody
            # decided who owns it, and a thread other than the engine is
            # touching it) vs MEDIUM from the engine side below
            findings.append(Finding(
                kind=kind, severity="HIGH",
                target=f"{rel}:{lineno}",
                message=(
                    f"{node.name}.{attr} mutated from worker context "
                    f"({getattr(fn, 'name', '<nested>')}) but is "
                    + ("engine-thread-only/immutable"
                       if kind == "ownership-breach"
                       else "not annotated in THREAD_CONTRACT")
                ),
                data={"class": node.name, "attr": attr, "line": lineno,
                      "context": "worker"},
            ))
        if in_worker_safe:
            ws_writes = [
                w for w in _self_attr_writes(fn, exclude=submitted_ids)
                if not _suppressed(w[1], "worker-unsafe-write")
            ]
            if ws_writes:
                findings.append(Finding(
                    kind="worker-unsafe-write", severity="HIGH",
                    target=f"{rel}:{fn.lineno}",
                    message=(
                        f"{node.name}.{fn.name} is declared worker_safe "
                        f"but mutates self"
                    ),
                    data={"class": node.name, "method": fn.name,
                          "attrs": sorted({w[0] for w in ws_writes})},
                ))

    # engine-context mutations
    for name, fn in engine_ctx_fns():
        spans = _lock_spans(fn, lock_name) if lock_name else []
        for attr, lineno, via_call in _self_attr_writes(
                fn, exclude=submitted_ids):
            seen_attrs.add(attr)
            if name == "__init__":
                continue  # construction precedes publication
            if attr in shared:
                if _suppressed(lineno, "unlocked-shared-write"):
                    continue
                if not any(a <= lineno <= b for a, b in spans):
                    findings.append(Finding(
                        kind="unlocked-shared-write", severity="HIGH",
                        target=f"{rel}:{lineno}",
                        message=(
                            f"{node.name}.{attr} is shared_locked but "
                            f"written without `with self.{lock_name}` "
                            f"({name})"
                        ),
                        data={"class": node.name, "attr": attr,
                              "line": lineno},
                    ))
            elif attr in immutable:
                if _suppressed(lineno, "ownership-breach"):
                    continue
                findings.append(Finding(
                    kind="ownership-breach", severity="HIGH",
                    target=f"{rel}:{lineno}",
                    message=(
                        f"{node.name}.{attr} is immutable-after-init but "
                        f"rebound in {name}"
                    ),
                    data={"class": node.name, "attr": attr,
                          "line": lineno},
                ))
            elif attr not in engine_only:
                if _suppressed(lineno, "unannotated-attribute"):
                    continue
                findings.append(Finding(
                    kind="unannotated-attribute", severity="MEDIUM",
                    target=f"{rel}:{lineno}",
                    message=(
                        f"{node.name}.{attr} mutated outside __init__ "
                        f"({name}) but not annotated in THREAD_CONTRACT"
                    ),
                    data={"class": node.name, "attr": attr,
                          "line": lineno, "context": "engine"},
                ))

    # worker_safe methods that mutate self (engine-classified ones too —
    # the declaration is "callable from any thread")
    for name in worker_safe:
        fn = methods.get(name)
        if fn is None or id(fn) in worker_ids:
            continue
        writes = [w for w in _self_attr_writes(fn, exclude=submitted_ids)
                  if not _suppressed(w[1], "worker-unsafe-write")]
        if writes:
            findings.append(Finding(
                kind="worker-unsafe-write", severity="HIGH",
                target=f"{rel}:{fn.lineno}",
                message=(
                    f"{node.name}.{name} is declared worker_safe (any "
                    f"thread) but mutates self.{writes[0][0]}"
                ),
                data={"class": node.name, "method": name,
                      "attrs": sorted({w[0] for w in writes})},
            ))

    # stale annotations: contracted attrs never touched in this class
    for attr in sorted(known):
        if attr not in seen_attrs:
            # immutables are typically only set in __init__ (which we
            # did record); anything truly unseen is stale
            findings.append(Finding(
                kind="stale-annotation", severity="LOW",
                target=f"{rel}:{node.name}",
                message=(
                    f"THREAD_CONTRACT annotates {node.name}.{attr} but "
                    f"no method ever writes it"
                ),
                data={"class": node.name, "attr": attr},
            ))
    return findings


# --------------------------------------------------------------------------
# purity / iteration-order lint (self-application over the engine)
# --------------------------------------------------------------------------

#: host-materialization surface inside traced code
_HOST_CALLS = {"int", "float", "bool"}
_HOST_ATTRS = {"item", "tolist", "block_until_ready"}


def _traced_functions(tree: ast.Module, source: str):
    """FunctionDefs whose def line (or the line above) carries
    `# kspec: traced`."""
    lines = source.splitlines()
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        for ln in (node.lineno, node.lineno - 1):
            if 1 <= ln <= len(lines) and _TRACED_RE.search(lines[ln - 1]):
                out.append(node)
                break
    return out


def lint_purity(path: str, rel: str) -> list:
    """Host-materialization lint over `# kspec: traced` functions plus
    the module-wide set-iteration-order check."""
    with open(path) as fh:
        source = fh.read()
    tree = ast.parse(source, filename=path)
    allows = _allow_reasons(source)
    findings: list = []

    def allowed(lineno, kind):
        # allow(purity) is the category-wide form
        return _allow_match(allows, lineno, (kind, "purity"))

    for fn in _traced_functions(tree, source):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            flagged = None
            if isinstance(f, ast.Name) and f.id in _HOST_CALLS:
                flagged = f"{f.id}(...)"
            elif isinstance(f, ast.Attribute):
                if isinstance(f.value, ast.Name) and f.value.id == "np":
                    flagged = f"np.{f.attr}"
                elif f.attr in _HOST_ATTRS:
                    flagged = f".{f.attr}()"
                elif (f.attr == "device_get"
                      and isinstance(f.value, ast.Name)
                      and f.value.id == "jax"):
                    flagged = "jax.device_get"
            if flagged and not allowed(node.lineno, "host-materialization"):
                findings.append(Finding(
                    kind="host-materialization", severity="MEDIUM",
                    target=f"{rel}:{node.lineno}",
                    message=(
                        f"traced function {fn.name!r} calls {flagged} — "
                        f"a host materialization inside a jit-traced "
                        f"stage helper forces the device pipeline "
                        f"(annotate `# kspec: allow(host-materialization)"
                        f" <why>` if the value is static)"
                    ),
                    data={"function": fn.name, "call": flagged,
                          "line": node.lineno},
                ))

    # set-iteration-order: `for x in {…}` / `for x in set(...)` — order
    # depends on PYTHONHASHSEED for str elements; engine determinism
    # (warm cache-key replay, digest chains) must not
    for node in ast.walk(tree):
        if not isinstance(node, (ast.For, ast.comprehension)):
            continue
        it = node.iter
        bad = None
        if isinstance(it, (ast.Set, ast.SetComp)):
            bad = "a set literal"
        elif (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id in ("set", "frozenset")):
            bad = f"{it.func.id}(...)"
        if bad and not allowed(it.lineno, "set-iteration-order"):
            findings.append(Finding(
                kind="set-iteration-order", severity="MEDIUM",
                target=f"{rel}:{it.lineno}",
                message=(
                    f"iteration over {bad} — set order is hash-seed "
                    f"dependent; wrap in sorted(...) or annotate "
                    f"`# kspec: allow(set-iteration-order) <why>`"
                ),
                data={"line": it.lineno},
            ))
    return findings
