"""Durable-write discipline lint: every durable filesystem effect must
flow through the recordable shim.

The crashcheck harness (``resilience/crashcheck``) can only model-check
what it can see: a raw ``os.rename``/``os.replace`` or an unregistered
``O_APPEND`` journal writer is a durable effect the op-recorder never
records, so its crash states are never enumerated and its recovery is
never exercised.  This lint pins the interposition boundary:

- **entry ops**: ``os.rename(`` / ``os.replace(`` may appear only inside
  ``durable_io.py`` itself (the shim is where the real syscall lives);
  everyone else goes through ``durable_io.rename/replace`` or the
  blessed atomic helpers (``storage/atomic.py``, ``obs/atomicio.py``),
  which already route there.
- **append journals**: ``os.O_APPEND`` opens and ``open(..., "a")`` may
  appear only in the registered emitters (``obs/tracer.py``,
  ``obs/fleettrace.py`` — both call ``durable_io.note_append`` after the
  write) or route through ``durable_io.append_text``.

A site that is genuinely not durable state (ephemeral IPC markers,
scratch files) carries a reasoned suppression on its own line or the
line above::

    # kspec: allow(durable-io) <why this is not durable state>

A bare tag with no reason is itself a finding.  Wired into
``cli analyze`` as HIGH ``durable-io`` findings and pinned at zero by a
tier-1 test, with a seeded-mutant test proving the lint actually fires.
"""

from __future__ import annotations

import os
import re
from typing import Optional

# the shim itself: the only file allowed to issue the raw entry syscalls
_SHIM = "kafka_specification_tpu/durable_io.py"

#: files whose O_APPEND writers are registered with the shim (they call
#: ``durable_io.note_append`` after each raw append write)
_REGISTERED_EMITTERS = {
    _SHIM,
    "kafka_specification_tpu/obs/tracer.py",
    "kafka_specification_tpu/obs/fleettrace.py",
}

_DOCSTRING_RE = re.compile(r'""".*?"""|\'\'\'.*?\'\'\'', re.S)

_ENTRY_OP_RE = re.compile(r"\bos\.(rename|replace)\s*\(")
_APPEND_RE = re.compile(
    r"\bos\.O_APPEND\b|\bopen\s*\([^)\n]*,\s*[\"']a[bt+]?[\"']"
)

_ALLOW_RE = re.compile(r"#\s*kspec:\s*allow\(durable-io\)\s*(.*)")


def _allowed(lines: list, lineno: int):
    """(suppressed, reason-missing) for a 1-based finding line: the tag
    counts on the line itself or either of the two lines above (the
    reasoned form usually wraps)."""
    for ln in (lineno, lineno - 1, lineno - 2):
        if 1 <= ln <= len(lines):
            m = _ALLOW_RE.search(lines[ln - 1])
            if m:
                return True, not m.group(1).strip()
    return False, False


def lint_durable_io(package_root: Optional[str] = None) -> list:
    """Static interposition-boundary lint.  Returns
    ``{path, line, problem}`` findings (empty = clean); wired into
    ``cli analyze`` and pinned by a tier-1 test so no durable write can
    drift outside what the crashcheck harness records."""
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))
    repo = os.path.dirname(package_root)
    findings = []
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, repo).replace(os.sep, "/")
            try:
                with open(path) as fh:
                    src = fh.read()
            except OSError:
                continue
            # docstrings quote the raw idiom as documentation; only real
            # code sites count (comments still count: the allow-tag
            # machinery below is how a comment legitimizes a site)
            scrubbed = _DOCSTRING_RE.sub(
                lambda m: "\n" * m.group(0).count("\n"), src
            )
            lines = src.splitlines()
            checks = []
            if rel != _SHIM:
                checks.append((
                    _ENTRY_OP_RE,
                    "raw os.rename/os.replace outside durable_io — the "
                    "crashcheck recorder never sees this entry op; use "
                    "durable_io.replace/rename or a blessed atomic "
                    "helper",
                ))
            if rel not in _REGISTERED_EMITTERS:
                checks.append((
                    _APPEND_RE,
                    "append-mode writer outside the registered journal "
                    "emitters — crashcheck cannot enumerate its torn "
                    "tails; use durable_io.append_text or register the "
                    "emitter",
                ))
            for pattern, problem in checks:
                for m in pattern.finditer(scrubbed):
                    # comment-only mentions of the idiom are not sites
                    lineno = scrubbed[: m.start()].count("\n") + 1
                    code = lines[lineno - 1]
                    if code.lstrip().startswith("#"):
                        continue
                    suppressed, bare = _allowed(lines, lineno)
                    if suppressed and not bare:
                        continue
                    findings.append({
                        "path": rel,
                        "line": lineno,
                        "problem": (
                            "kspec: allow(durable-io) tag carries no "
                            "reason — state why this site is not "
                            "durable state"
                        ) if suppressed else problem,
                    })
    return findings
