"""Encoding-soundness and action/guard lint over built models.

Two layers, one Finding vocabulary (docs/analysis.md):

**Spec-width pass** (``spec_fits_errors``): every declared field range
must fit the packed representation the engine actually uses —
``ops/packing.StateSpec`` flattens states through an **int32 element
dtype** before biasing into <=32-bit lanes, so a field with
``hi > 2^31 - 1`` (or ``lo < -2^31``) silently wraps long before the
lane packer would complain.  This is the general form of the AsyncIsr
"N <= 4" encoding cliff: at N = 5 the per-version request bitset
declares ``hi = 2^32 - 1``.  Pure arithmetic over the Field table —
runs in microseconds at every model construction
(``models.base.Model.__post_init__``).

**Action passes** (``analyze_model``): interval abstract interpretation
of every (action, choice) pair through the *shipped* kernel code
(analysis/interval.py), producing:

- ``encoding-overflow`` (HIGH): a possibly-enabled successor writes a
  field element whose interval escapes the declared [lo, hi] — the
  packer would truncate it and the checker would explore (and digest,
  and checkpoint) a state that never existed.  The finding carries the
  machine-readable counterexample (action, choice, field, computed
  interval, declared interval).
- ``frame-violation`` (HIGH): the kernel wrote a field outside the
  action's declared write set (``Action.writes``, an UPPER bound on the
  fields whose tensor value may change), or declared a write for a name
  that is not a spec field at all.
- ``vacuous-action`` (MEDIUM): every choice of an action is statically
  disabled under the CONSTANTS-derived bounds — dead spec code, or a
  mistranscribed guard.
- ``read-of-unwritten-field`` / ``dead-field`` (LOW): a field no action
  ever writes is constant forever; if action guards/updates still read
  it, the likely cause is a forgotten update transcription.
- ``analysis-skip`` (INFO): the kernel used a construct outside the
  abstract domain; the action is honestly skipped, never guessed at.

Suppression: ``model.meta["analysis_suppress"]`` is an iterable of
``{"kind": ..., "target": <substring>, "reason": ...}``; matching
findings are downgraded to INFO with the justification attached.

Everything here is jax-free.
"""

from __future__ import annotations

import numpy as np

from . import Finding
from .interval import (
    AnalysisUnsupported,
    IVal,
    analyze_action_choice,
    definitely_disabled,
)

#: the packed element dtype's representable range (StateSpec._flatten
#: casts through int32; ops/packing.py)
INT32_MIN = -(1 << 31)
INT32_MAX = (1 << 31) - 1
#: lane width (uint32 lanes; elements never straddle one)
LANE_BITS = 32


class EncodingUnsound(ValueError):
    """A (config, schema) pair the engine cannot soundly encode.

    Subclasses ValueError so every pre-existing entry point that rejected
    the AsyncIsr N=5 cliff with a ValueError keeps its error class; the
    machine-readable findings ride on ``.findings``."""

    def __init__(self, message: str, findings=()):
        super().__init__(message)
        self.findings = list(findings)


def spec_fits_errors(fields, context: str = "") -> list:
    """Spec-width findings for a Field table (empty list == sound)."""
    out = []
    prefix = f"{context}: " if context else ""
    for f in fields:
        span_bits = max(1, int(f.hi - f.lo).bit_length())
        if f.lo < INT32_MIN or f.hi > INT32_MAX:
            out.append(Finding(
                kind="spec-width",
                severity="HIGH",
                target=f"field:{f.name}",
                message=(
                    f"{prefix}field {f.name!r} declares [{f.lo}, {f.hi}] "
                    f"but the packed element dtype is int32 "
                    f"[{INT32_MIN}, {INT32_MAX}]: values would silently "
                    f"wrap before packing"
                ),
                data={"field": f.name, "declared": [f.lo, f.hi],
                      "dtype_range": [INT32_MIN, INT32_MAX],
                      "needed_bits": span_bits},
            ))
        # the 32-bit LANE bound needs no separate branch: a range inside
        # the int32 element dtype spans <= 2^32 values = <= LANE_BITS
        # bits by construction (and StateSpec's own `assert w <= 32`
        # backstops any future dtype change)
    return out


def check_spec_fields(fields, context: str = "") -> None:
    """Raise :class:`EncodingUnsound` when the field table cannot be
    packed soundly — the one spec-level entry point (Model build, the
    AsyncIsr delegating check, `cli analyze`)."""
    errs = spec_fits_errors(fields, context)
    if errs:
        raise EncodingUnsound(
            "; ".join(e.message for e in errs), findings=errs
        )


# --------------------------------------------------------------------------
# interval pass over the actions
# --------------------------------------------------------------------------


def _overflow_elements(nv: IVal, field):
    """Elements of a written field whose interval escapes the declared
    range -> (worst_lo, worst_hi, n_bad) or None."""
    bad = (nv.lo < field.lo) | (nv.hi > field.hi)
    if not bool(np.any(bad)):
        return None
    return int(np.min(nv.lo)), int(np.max(nv.hi)), int(np.sum(bad))


def analyze_actions(model) -> list:
    """The three action passes (overflow / frame / vacuous + dead-field)
    over one built model.  Returns raw findings (no suppression)."""
    fields = model.spec.fields
    by_name = {f.name: f for f in fields}
    findings: list = []
    written_any: set = set()
    read_any: set = set()
    # a skipped action's writes are UNKNOWN: its declared write set (if
    # any) still counts as "written somewhere", and with no declaration
    # the whole dead-field pass would be guessing — honesty rules
    writes_unknown = False

    for a in model.actions:
        changed: set = set()
        n_disabled = 0
        n_skipped = 0
        for c in range(a.n_choices):
            try:
                r = analyze_action_choice(a.kernel, fields, c)
            except AnalysisUnsupported as e:
                n_skipped += 1
                if n_skipped == 1:  # one skip record per action
                    findings.append(Finding(
                        kind="analysis-skip",
                        severity="INFO",
                        target=f"action:{a.name}",
                        message=(
                            f"action {a.name!r} uses a construct outside "
                            f"the interval domain ({e}) — not analyzed"
                        ),
                        data={"action": a.name, "reason": str(e)},
                    ))
                continue
            enabled = r["enabled"]
            read_any |= set(enabled.deps)
            if definitely_disabled(enabled):
                n_disabled += 1
                continue  # statically disabled: nothing can commit
            for f in fields:
                nv = r["next"].get(f.name)
                if nv is None or nv is r["base"][f.name]:
                    continue
                nv = IVal.coerce(nv)
                changed.add(f.name)
                read_any |= set(nv.deps)
                ovf = _overflow_elements(nv, f)
                if ovf is not None:
                    lo, hi, n_bad = ovf
                    findings.append(Finding(
                        kind="encoding-overflow",
                        severity="HIGH",
                        target=f"action:{a.name}",
                        message=(
                            f"action {a.name!r} (choice {c}) writes "
                            f"field {f.name!r} with interval [{lo}, {hi}]"
                            f" outside its declared [{f.lo}, {f.hi}] — "
                            f"the bit packer would silently truncate it"
                        ),
                        data={"action": a.name, "choice": c,
                              "field": f.name, "interval": [lo, hi],
                              "declared": [f.lo, f.hi],
                              "bad_elements": n_bad},
                    ))
        written_any |= changed
        if n_skipped:
            if a.writes is not None:
                written_any |= set(a.writes)
            else:
                writes_unknown = True
        if a.n_choices and n_disabled == a.n_choices:
            findings.append(Finding(
                kind="vacuous-action",
                severity="MEDIUM",
                target=f"action:{a.name}",
                message=(
                    f"action {a.name!r} is statically disabled for every "
                    f"choice under the declared bounds — dead spec code "
                    f"or a mistranscribed guard"
                ),
                data={"action": a.name, "choices": a.n_choices},
            ))
        writes = getattr(a, "writes", None)
        if writes is not None:
            # observed changes from the ANALYZED choices can only
            # understate violations, so partial skips don't gate this
            # (and the unknown-name check needs no abstract run at all)
            extra = sorted(changed - set(writes))
            if extra:
                findings.append(Finding(
                    kind="frame-violation",
                    severity="HIGH",
                    target=f"action:{a.name}",
                    message=(
                        f"action {a.name!r} writes {extra} outside its "
                        f"declared write set {sorted(writes)}"
                    ),
                    data={"action": a.name, "extra_writes": extra,
                          "declared_writes": sorted(writes)},
                ))
            # note: declared write sets are UPPER bounds — an action may
            # pass a field through unchanged (ControllerElectLeader
            # re-publishes the same quorum ISR object), so declared-but-
            # unchanged is NOT a finding; only changed-but-undeclared is.
            unknown = sorted(n for n in writes if n not in by_name)
            if unknown:
                findings.append(Finding(
                    kind="frame-violation",
                    severity="HIGH",
                    target=f"action:{a.name}",
                    message=(
                        f"action {a.name!r} declares writes {unknown} "
                        f"that are not fields of the spec"
                    ),
                    data={"action": a.name, "unknown_writes": unknown},
                ))

    # dead / read-of-unwritten fields (whole-model facts); with any
    # skipped action's writes unknown, the pass would be guessing — skip
    for f in (fields if not writes_unknown else ()):
        if f.name in written_any:
            continue
        if f.name in read_any:
            findings.append(Finding(
                kind="read-of-unwritten-field",
                severity="LOW",
                target=f"field:{f.name}",
                message=(
                    f"field {f.name!r} feeds action guards/updates but "
                    f"no action ever writes it — it is constant at its "
                    f"init value (forgotten update transcription?)"
                ),
                data={"field": f.name},
            ))
        else:
            findings.append(Finding(
                kind="dead-field",
                severity="LOW",
                target=f"field:{f.name}",
                message=(
                    f"field {f.name!r} is neither written nor read by "
                    f"any action — encoding bits wasted on a constant "
                    f"(invariants may still read it)"
                ),
                data={"field": f.name},
            ))
    return findings


def field_hulls(model, strict: bool = False) -> dict:
    """Per-field reachable-value interval hulls: {name: (lo, hi)}.

    The hull of field ``f`` joins (1) the model's concrete init values
    and (2) every possibly-enabled (action, choice) write interval the
    encoding pass already computes (analysis/interval.py) — a SOUND
    over-approximation of every value the checker can ever pack, and the
    stable export the device-resident pipeline sizes its in-jit pack
    stage from (docs/engine.md): a hull inside the declared ``[lo, hi]``
    proves the pack stage cannot truncate even though no host-side
    validation runs between the while-loop's chunks.

    Honesty contract: a kernel outside the abstract domain (the emitted
    models' evaluator closures) makes its writes unknowable — with
    ``strict=True`` that raises :class:`AnalysisUnsupported` (the device
    pipeline's fallback trigger); otherwise the affected fields widen to
    their DECLARED ranges (still sound *if* the encoding gate holds,
    stated as such, never a guessed tight hull).  Hulls are NOT clipped
    to the declared ranges: with the build gate disabled
    (KSPEC_ANALYZE=0) a write can escape them, and a consumer comparing
    hull vs declared is exactly how that escape is caught.

    Memoized on the model object (abstract runs cost milliseconds but
    engines construct pipelines per check() call) — strict and
    non-strict results cache separately (a strict failure is cached as
    the exception to re-raise).
    """
    if strict:
        cached = getattr(model, "_field_hulls_strict", None)
        if isinstance(cached, AnalysisUnsupported):
            raise cached
        if cached is not None:
            return dict(cached)
    else:
        cached = getattr(model, "_field_hulls", None)
        if cached is not None:
            return dict(cached)
    fields = model.spec.fields
    by_name = {f.name: f for f in fields}
    hulls: dict = {}

    def widen(name, lo, hi):
        cur = hulls.get(name)
        hulls[name] = (
            (min(cur[0], lo), max(cur[1], hi)) if cur else (lo, hi)
        )

    # (1) init values: unwritten fields stay at them forever
    try:
        inits = model.init_states()
    except Exception as e:  # noqa: BLE001 — exotic init builders
        if strict:
            exc = AnalysisUnsupported(f"init states not enumerable: {e}")
            try:  # same cached-exception contract as the action path
                model._field_hulls_strict = exc
            except AttributeError:
                pass
            raise exc
        inits = None
    if inits is None:
        for f in fields:
            widen(f.name, f.lo, f.hi)
    else:
        for s in inits:
            for f in fields:
                v = np.asarray(s[f.name])
                widen(f.name, int(np.min(v)), int(np.max(v)))

    # (2) every possibly-enabled write interval
    for a in model.actions:
        skipped = False
        for c in range(a.n_choices):
            try:
                r = analyze_action_choice(a.kernel, fields, c)
            except AnalysisUnsupported:
                skipped = True
                break
            if definitely_disabled(r["enabled"]):
                continue
            for f in fields:
                nv = r["next"].get(f.name)
                if nv is None or nv is r["base"][f.name]:
                    continue
                nv = IVal.coerce(nv)
                widen(f.name, int(np.min(nv.lo)), int(np.max(nv.hi)))
        if skipped:
            if strict:
                exc = AnalysisUnsupported(
                    f"action {a.name!r} outside the interval domain — "
                    f"no proven hull"
                )
                try:
                    model._field_hulls_strict = exc
                except AttributeError:
                    pass
                raise exc
            # unknown writes: widen the declared write set (or, with no
            # declaration, every field) to its declared range
            names = (
                a.writes if getattr(a, "writes", None) is not None
                else by_name
            )
            for n in names:
                f = by_name.get(n)
                if f is not None:
                    widen(f.name, f.lo, f.hi)
    try:
        if strict:
            model._field_hulls_strict = dict(hulls)
        else:
            model._field_hulls = dict(hulls)
    except AttributeError:
        pass
    return hulls


def hull_pack_widths(hulls: dict) -> dict:
    """{field: bits} a pack stage would need for the hull spans — the
    quantity tests pin against ``ops/packing.Field.width`` (a sound
    hull can never need MORE bits than the declared range provides)."""
    import math

    return {
        name: max(1, math.ceil(math.log2(hi - lo + 1)))
        for name, (lo, hi) in hulls.items()
    }


def apply_suppressions(findings, model) -> list:
    """Downgrade findings matching ``meta['analysis_suppress']`` entries
    to INFO, carrying the justification (docs/analysis.md)."""
    rules = []
    meta = getattr(model, "meta", None) or {}
    for r in meta.get("analysis_suppress", ()):
        rules.append((r.get("kind"), r.get("target", ""),
                      r.get("reason", "suppressed")))
    if not rules:
        return list(findings)
    out = []
    for f in findings:
        for kind, target, reason in rules:
            if (kind is None or kind == f.kind) and target in f.target:
                f = Finding(kind=f.kind, severity="INFO", target=f.target,
                            message=f.message, data=f.data,
                            suppressed=reason)
                break
        out.append(f)
    return out


def analyze_model(model) -> list:
    """Spec-width + action passes + suppressions for one built model."""
    findings = spec_fits_errors(model.spec.fields, context=model.name)
    findings += analyze_actions(model)
    return apply_suppressions(findings, model)


def verify_model_encoding(model) -> list:
    """The build-time gate's core: raise :class:`EncodingUnsound` on any
    unsuppressed HIGH encoding finding (spec-width, encoding-overflow,
    frame-violation); return the full finding list otherwise."""
    findings = analyze_model(model)
    fatal = [f for f in findings
             if f.severity == "HIGH"
             and f.kind in ("spec-width", "encoding-overflow",
                            "frame-violation")]
    if fatal:
        head = fatal[0]
        raise EncodingUnsound(
            f"model {model.name!r} is encoding-unsound "
            f"({len(fatal)} HIGH finding(s)); first: {head.message}  "
            f"[refusing to explore: the verdict would be untrustworthy; "
            f"KSPEC_ANALYZE=0 overrides at your own risk]",
            findings=fatal,
        )
    return findings
