"""Interval abstract interpretation of the models' successor kernels.

The encoding-soundness pass (analysis/encoding.py) must prove, for every
shipped model and every CONSTANTS valuation, that every field an action
writes stays within the field's declared [lo, hi] — the range the bit
packer (ops/packing.StateSpec) silently truncates to.  The proof runs the
*actual shipped kernel code*: each action kernel is executed once per
choice with the state fields bound to interval values (lo/hi hulls over
the declared field ranges) and the module-level ``jnp`` name temporarily
rebound to the abstract namespace below — so there is no second
transcription of the update semantics that could drift from the kernels
the engine runs (the alpha-normalize capture bug class this subsystem
exists to close).

Domain: non-relational intervals over arbitrary-precision Python ints
(numpy ``object`` arrays carry the element lattice so field shapes and
broadcasting come for free; Python ints mean a 2^32-bit bitset bound can
never overflow the *analyzer*).  Two refinements keep the shipped
kernels precise enough to verify clean:

- **guard refinement**: scalar comparisons whose operand is a direct
  field read (``s["end"][r] < L``) record a constraint on the enabled
  value they flow into through ``&``; each (action, choice) is evaluated
  twice — once to collect the guard's constraints, once against the
  state refined by them.  This is sound because the engine only commits
  successors whose guard held.  Disjunctions (``|``) and negations drop
  constraints (weaker, still sound).
- **per-element arrays**: indexed reads/updates with concrete indices
  (choice-derived) are strong; abstract indices join over the index
  hull, clipped to the axis like XLA's gather/scatter clamp/drop rule.

Everything here is jax-free: the abstract ``jnp`` is this module's, and
``cli analyze`` imports the model modules under the stub installed by
:func:`..analysis.install_jax_stub`.
"""

from __future__ import annotations

import sys
from typing import Optional

import numpy as np


class AnalysisUnsupported(Exception):
    """The kernel used a construct the abstract domain does not model.
    Callers skip the action (recorded as an INFO finding) rather than
    guessing — an imprecise skip is visible, a wrong hull is not."""


def _obj(x) -> np.ndarray:
    """Coerce to an object-dtype ndarray of Python ints."""
    a = np.asarray(x, dtype=object)
    if a.shape == ():
        a = a.reshape(())
    return a


def _aint(x):
    """Normalize a numpy scalar / bool to a Python int."""
    if isinstance(x, bool) or isinstance(x, np.bool_):
        return int(x)
    if isinstance(x, np.generic):
        return int(x)
    return x


class IVal:
    """An interval-valued tensor: elementwise [lo, hi] (inclusive), with
    optional provenance for guard refinement and field-dependency taint.

    - ``origin``: (field, idx_tuple) when this value IS a direct (chain
      of concrete-index) read of a state field — the only values guard
      refinement may constrain.
    - ``deps``: frozenset of field names whose values flowed into this
      one (read-set accounting for the action lint).
    - ``constraints``: guard facts of the form (field, idx, "le"|"ge",
      bound) collected from scalar comparisons; survive only ``&``.
    """

    __slots__ = ("lo", "hi", "origin", "deps", "constraints", "is_bool")

    def __init__(self, lo, hi, origin=None, deps=frozenset(),
                 constraints=(), is_bool=False):
        self.lo = _obj(lo)
        self.hi = _obj(hi)
        if self.lo.shape != self.hi.shape:
            lo_b, hi_b = np.broadcast_arrays(self.lo, self.hi)
            self.lo, self.hi = lo_b.copy(), hi_b.copy()
        self.origin = origin
        self.deps = deps
        self.constraints = tuple(constraints)
        self.is_bool = bool(is_bool)

    # -- constructors ------------------------------------------------------
    @classmethod
    def const(cls, v):
        v = _aint(v)
        return cls(v, v)

    @classmethod
    def coerce(cls, v) -> "IVal":
        if isinstance(v, IVal):
            return v
        if isinstance(v, (bool, np.bool_)):
            return cls(int(v), int(v), is_bool=True)
        if isinstance(v, (int, np.integer)):
            return cls.const(v)
        if isinstance(v, (list, tuple, np.ndarray)):
            a = _obj([_aint(x) for x in np.asarray(v).reshape(-1)])
            a = a.reshape(np.asarray(v).shape)
            return cls(a, a.copy())
        raise AnalysisUnsupported(f"cannot abstract {type(v).__name__}")

    # -- shape plumbing ----------------------------------------------------
    @property
    def shape(self):
        return self.lo.shape

    @property
    def ndim(self):
        return self.lo.ndim

    def is_concrete(self) -> bool:
        return bool(np.all(self.lo == self.hi))

    def concrete_scalar(self) -> Optional[int]:
        if self.shape == () and self.lo.item() == self.hi.item():
            return int(self.lo.item())
        return None

    def _bin_deps(self, other) -> frozenset:
        o = other.deps if isinstance(other, IVal) else frozenset()
        return self.deps | o

    def __repr__(self):
        if self.shape == ():
            return f"IVal[{self.lo.item()}, {self.hi.item()}]"
        return f"IVal(shape={self.shape})"

    # -- arithmetic --------------------------------------------------------
    def __add__(self, other):
        o = IVal.coerce(other)
        return IVal(self.lo + o.lo, self.hi + o.hi,
                    deps=self._bin_deps(o))

    __radd__ = __add__

    def __sub__(self, other):
        o = IVal.coerce(other)
        return IVal(self.lo - o.hi, self.hi - o.lo,
                    deps=self._bin_deps(o))

    def __rsub__(self, other):
        return IVal.coerce(other).__sub__(self)

    def __mul__(self, other):
        o = IVal.coerce(other)
        cands = [self.lo * o.lo, self.lo * o.hi,
                 self.hi * o.lo, self.hi * o.hi]
        return IVal(np.minimum.reduce(cands), np.maximum.reduce(cands),
                    deps=self._bin_deps(o))

    __rmul__ = __mul__

    def __neg__(self):
        return IVal(-self.hi, -self.lo, deps=self.deps)

    def _div_corners(self, o, op):
        if not bool(np.all(o.lo > 0)):
            raise AnalysisUnsupported("division by non-positive interval")
        cands = [op(self.lo, o.lo), op(self.lo, o.hi),
                 op(self.hi, o.lo), op(self.hi, o.hi)]
        return IVal(np.minimum.reduce(cands), np.maximum.reduce(cands),
                    deps=self._bin_deps(o))

    def __floordiv__(self, other):
        return self._div_corners(IVal.coerce(other),
                                 lambda a, b: a // b)

    def __mod__(self, other):
        o = IVal.coerce(other)
        n = o.concrete_scalar()
        if n is None or n <= 0:
            raise AnalysisUnsupported("modulo by non-constant")
        same_block = (self.lo // n) == (self.hi // n)
        lo = np.where(same_block, self.lo % n, 0)
        hi = np.where(same_block, self.hi % n, n - 1)
        if bool(np.any(self.lo < 0)):
            lo = np.minimum(lo, self.lo)  # conservative for negatives
        return IVal(lo, hi, deps=self._bin_deps(o))

    # -- shifts (monotone in both operands; 4-corner hull) ----------------
    def _shift(self, other, op):
        o = IVal.coerce(other)
        if bool(np.any(o.lo < 0)):
            raise AnalysisUnsupported("negative shift amount")
        if bool(np.any(o.hi > 1 << 20)):
            raise AnalysisUnsupported("shift amount too large to bound")
        cands = [op(self.lo, o.lo), op(self.lo, o.hi),
                 op(self.hi, o.lo), op(self.hi, o.hi)]
        return IVal(np.minimum.reduce(cands), np.maximum.reduce(cands),
                    deps=self._bin_deps(o))

    def __lshift__(self, other):
        return self._shift(other, lambda a, b: a << b)

    def __rlshift__(self, other):
        return IVal.coerce(other)._shift(self, lambda a, b: a << b)

    def __rshift__(self, other):
        return self._shift(other, lambda a, b: a >> b)

    def __rrshift__(self, other):
        return IVal.coerce(other)._shift(self, lambda a, b: a >> b)

    # -- bitwise hulls -----------------------------------------------------
    @staticmethod
    def _mask_hull(a_hi, b_hi):
        """All-ones hull >= a|b for nonneg operands (elementwise)."""
        def bits(x):
            return int(x).bit_length()
        vb = np.frompyfunc(
            lambda x, y: (1 << max(bits(max(x, 0)), bits(max(y, 0)))) - 1,
            2, 1,
        )
        return vb(a_hi, b_hi)

    def _is_boolish(self) -> bool:
        return bool(np.all(self.lo >= 0)) and bool(np.all(self.hi <= 1))

    def __and__(self, other):
        o = IVal.coerce(other)
        deps = self._bin_deps(o)
        # guard conjunction: `enabled = c1 & c2 & ...` — the ONLY operator
        # that propagates refinement constraints (if a & b is true, both
        # conjuncts held); sound for {0,1}-valued operands only
        cons = (self.constraints + o.constraints
                if self._is_boolish() and o._is_boolish() else ())
        if self._is_boolish() and o._is_boolish():
            # logical conjunction on {0,1}: products keep definiteness
            return IVal(self.lo * o.lo, self.hi * o.hi,
                        deps=deps, constraints=cons,
                        is_bool=self.is_bool and o.is_bool)
        a_nn = bool(np.all(self.lo >= 0))
        b_nn = bool(np.all(o.lo >= 0))
        if a_nn and b_nn:
            shape = np.broadcast(self.lo, o.lo).shape
            return IVal(np.zeros(shape, object),
                        np.minimum(self.hi + 0 * o.hi, o.hi + 0 * self.hi),
                        deps=deps, constraints=cons)
        if b_nn:  # a & b with b >= 0 is in [0, b.hi]
            z = 0 * self.hi
            return IVal(z + 0 * o.lo, o.hi + z, deps=deps)
        if a_nn:
            z = 0 * o.hi
            return IVal(z + 0 * self.lo, self.hi + z, deps=deps)
        # both may be negative: bound by the wider two's-complement width
        m = self._mask_hull(np.maximum(np.abs(self.lo), np.abs(self.hi)),
                            np.maximum(np.abs(o.lo), np.abs(o.hi)))
        return IVal(-(m + 1), np.maximum(self.hi + 0 * o.hi,
                                         o.hi + 0 * self.hi), deps=deps)

    __rand__ = __and__

    def __or__(self, other):
        o = IVal.coerce(other)
        deps = self._bin_deps(o)
        if self._is_boolish() and o._is_boolish():
            # logical disjunction on {0,1} (constraints drop: a true
            # disjunction pins neither side)
            return IVal(np.maximum(self.lo + 0 * o.lo, o.lo + 0 * self.lo),
                        np.maximum(self.hi + 0 * o.hi, o.hi + 0 * self.hi),
                        deps=deps, is_bool=self.is_bool and o.is_bool)
        lo = np.minimum(self.lo + 0 * o.lo, o.lo + 0 * self.lo)
        # a | b < 0 iff either operand < 0; definitely-negative => hi = -1
        both_nn_possible = (self.hi >= 0) & (o.hi >= 0)
        hull = self._mask_hull(self.hi, o.hi)
        hi = np.where(both_nn_possible, hull, -1)
        return IVal(lo, hi, deps=deps)

    __ror__ = __or__

    def __xor__(self, other):
        o = IVal.coerce(other)
        m = self._mask_hull(np.maximum(np.abs(self.lo), np.abs(self.hi)),
                            np.maximum(np.abs(o.lo), np.abs(o.hi)))
        return IVal(-(m + 1), m, deps=self._bin_deps(o))

    __rxor__ = __xor__

    def __invert__(self):
        if self.is_bool:
            # jnp logical-not on bool arrays (constraints drop: they
            # describe the un-negated fact)
            return IVal(1 - self.hi, 1 - self.lo, deps=self.deps,
                        is_bool=True)
        return IVal(-self.hi - 1, -self.lo - 1, deps=self.deps)

    # -- comparisons -> abstract booleans in {0, 1} -----------------------
    def _cmp(self, other, defi_true, defi_false, facts):
        o = IVal.coerce(other)
        t = defi_true(self, o)
        f = defi_false(self, o)
        lo = np.where(t, 1, 0)
        hi = np.where(f, 0, 1)
        cons = []
        if self.shape == () and o.shape == ():
            for side, mirror, val in facts:
                src = self if side == "a" else o
                if src.origin is not None:
                    cons.append((src.origin[0], src.origin[1], mirror,
                                 int(val(self, o))))
        return IVal(lo, hi, deps=self._bin_deps(o), constraints=cons,
                    is_bool=True)

    def __lt__(self, other):
        return self._cmp(
            other,
            lambda a, b: a.hi < b.lo,
            lambda a, b: a.lo >= b.hi,
            facts=[("a", "le", lambda a, b: b.hi.item() - 1),
                   ("b", "ge", lambda a, b: a.lo.item() + 1)],
        )

    def __le__(self, other):
        return self._cmp(
            other,
            lambda a, b: a.hi <= b.lo,
            lambda a, b: a.lo > b.hi,
            facts=[("a", "le", lambda a, b: b.hi.item()),
                   ("b", "ge", lambda a, b: a.lo.item())],
        )

    def __gt__(self, other):
        return self._cmp(
            other,
            lambda a, b: a.lo > b.hi,
            lambda a, b: a.hi <= b.lo,
            facts=[("a", "ge", lambda a, b: b.lo.item() + 1),
                   ("b", "le", lambda a, b: a.hi.item() - 1)],
        )

    def __ge__(self, other):
        return self._cmp(
            other,
            lambda a, b: a.lo >= b.hi,
            lambda a, b: a.hi < b.lo,
            facts=[("a", "ge", lambda a, b: b.lo.item()),
                   ("b", "le", lambda a, b: a.hi.item())],
        )

    def __eq__(self, other):  # noqa: D105 — abstract, not identity
        return self._cmp(
            other,
            lambda a, b: (a.lo == a.hi) & (b.lo == b.hi) & (a.lo == b.lo),
            lambda a, b: (a.hi < b.lo) | (a.lo > b.hi),
            facts=[("a", "le", lambda a, b: b.hi.item()),
                   ("a", "ge", lambda a, b: b.lo.item()),
                   ("b", "le", lambda a, b: a.hi.item()),
                   ("b", "ge", lambda a, b: a.lo.item())],
        )

    def __ne__(self, other):  # noqa: D105
        return self._cmp(
            other,
            lambda a, b: (a.hi < b.lo) | (a.lo > b.hi),
            lambda a, b: (a.lo == a.hi) & (b.lo == b.hi) & (a.lo == b.lo),
            facts=[],
        )

    __hash__ = None  # abstract == is not an equivalence

    def __bool__(self):
        c = self.concrete_scalar()
        if c is None:
            raise AnalysisUnsupported(
                "data-dependent Python branch on an abstract value"
            )
        return bool(c)

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, idx):
        if not isinstance(idx, tuple):
            idx = (idx,)
        lo, hi = self.lo, self.hi
        origin = self.origin
        deps = self.deps
        axis = 0
        for part in idx:
            part = _aint(part) if isinstance(part, np.generic) else part
            if isinstance(part, IVal):
                c = part.concrete_scalar()
                deps = deps | part.deps
                if c is not None:
                    part = c
                else:
                    # abstract gather: join over the index hull, clipped
                    # to the axis (XLA gather clamps out-of-bounds)
                    n = lo.shape[axis]
                    a = max(0, min(int(part.lo.item()), n - 1))
                    b = max(0, min(int(part.hi.item()), n - 1))
                    sl = [slice(None)] * lo.ndim
                    sl[axis] = slice(a, b + 1)
                    lo = np.minimum.reduce(lo[tuple(sl)], axis=axis)
                    hi = np.maximum.reduce(hi[tuple(sl)], axis=axis)
                    origin = None
                    continue
            if isinstance(part, (bool, np.bool_)):
                raise AnalysisUnsupported("boolean mask indexing")
            if isinstance(part, int):
                n = lo.shape[axis]
                p = max(-n, min(part, n - 1))  # numpy/jnp clamp semantics
                lo = np.take(lo, p, axis=axis)
                hi = np.take(hi, p, axis=axis)
                if origin is not None:
                    origin = (origin[0], origin[1] + (p,))
                continue
            if isinstance(part, slice):
                sl = [slice(None)] * lo.ndim
                sl[axis] = part
                lo = lo[tuple(sl)]
                hi = hi[tuple(sl)]
                origin = None
                axis += 1
                continue
            raise AnalysisUnsupported(f"index kind {type(part).__name__}")
        lo, hi = _obj(lo), _obj(hi)  # np.take on object arrays may
        # return the bare element
        if origin is not None and lo.ndim != 0:
            origin = None  # refinement constrains fully-indexed scalars only
        return IVal(lo.copy() if isinstance(lo, np.ndarray) else lo,
                    hi.copy() if isinstance(hi, np.ndarray) else hi,
                    origin=origin, deps=deps, is_bool=self.is_bool)

    # -- functional updates (.at[idx].set(v)) ------------------------------
    @property
    def at(self):
        return _At(self)

    def join(self, other: "IVal") -> "IVal":
        o = IVal.coerce(other)
        return IVal(np.minimum(self.lo + 0 * o.lo, o.lo + 0 * self.lo),
                    np.maximum(self.hi + 0 * o.hi, o.hi + 0 * self.hi),
                    deps=self._bin_deps(o))


class _At:
    def __init__(self, base: IVal):
        self.base = base

    def __getitem__(self, idx):
        return _AtIndexed(self.base, idx)


class _AtIndexed:
    def __init__(self, base: IVal, idx):
        self.base = base
        self.idx = idx if isinstance(idx, tuple) else (idx,)

    def set(self, v):
        base = self.base
        v = IVal.coerce(v)
        lo = base.lo.copy()
        hi = base.hi.copy()
        deps = base.deps | v.deps
        # resolve leading concrete indices into a target sub-view
        concrete: list = []
        rest = list(self.idx)
        abstract = None
        for part in rest:
            part = _aint(part) if isinstance(part, np.generic) else part
            if isinstance(part, IVal):
                c = part.concrete_scalar()
                deps = deps | part.deps
                if c is not None:
                    concrete.append(c)
                    continue
                abstract = part
                break
            elif isinstance(part, int):
                concrete.append(part)
            else:
                raise AnalysisUnsupported(
                    f".at index kind {type(part).__name__}"
                )
        n_abs = len(self.idx) - len(concrete)
        if abstract is None:
            # strong update at a fully/partially concrete position
            pos = tuple(concrete)
            for d, p in enumerate(pos):
                n = base.lo.shape[d]
                if not (-n <= p < n):
                    return IVal(lo, hi, deps=deps)  # XLA scatter drop
            tgt_shape = lo[pos].shape if isinstance(lo[pos], np.ndarray) \
                else ()
            lo[pos] = np.broadcast_to(v.lo, tgt_shape) if tgt_shape \
                else v.lo.item() if v.lo.shape == () else v.lo
            hi[pos] = np.broadcast_to(v.hi, tgt_shape) if tgt_shape \
                else v.hi.item() if v.hi.shape == () else v.hi
            return IVal(lo, hi, deps=deps)
        if n_abs != 1 or v.shape != ():
            raise AnalysisUnsupported(
                "abstract scatter supports one abstract axis and a "
                "scalar value"
            )
        # weak update: every position the abstract index may hit joins
        # with the written value (out-of-range portions drop, like XLA)
        axis = len(concrete)
        n = base.lo.shape[axis]
        a = max(0, min(int(abstract.lo.item()), n - 1))
        b = max(0, min(int(abstract.hi.item()), n - 1))
        if int(abstract.hi.item()) < 0 or int(abstract.lo.item()) > n - 1:
            return IVal(lo, hi, deps=deps)  # entirely out of range: drop
        for p in range(a, b + 1):
            pos = tuple(concrete) + (p,)
            lo[pos] = min(lo[pos], v.lo.item())
            hi[pos] = max(hi[pos], v.hi.item())
        return IVal(lo, hi, deps=deps)


# --------------------------------------------------------------------------
# abstract jnp namespace
# --------------------------------------------------------------------------


def _defi(x: IVal):
    """(definitely-true mask, definitely-false mask) under jnp TRUTHINESS
    — any nonzero value is true, so definitely-true means 0 is outside
    the interval (lo > 0 or hi < 0) and definitely-false means the
    interval IS {0}.  Comparison results are {0,1}-valued so this
    degenerates to the boolean rule there, but a kernel branching on a
    raw integer (`jnp.where(x - 5, a, b)`) must not have its negative
    range read as false."""
    return ((x.lo >= 1) | (x.hi <= -1)), ((x.lo == 0) & (x.hi == 0))


class AbstractJnp:
    """Duck-typed stand-in for the ``jnp`` module name inside kernels.

    Covers exactly the operation set the shipped model kernels use
    (jnp.where/minimum/maximum/clip/all/any/min/max/arange/int32/
    broadcast_to); anything else raises AnalysisUnsupported so the
    caller records an honest skip instead of a wrong hull.
    """

    int32 = staticmethod(lambda x=0: IVal.coerce(x))
    int64 = staticmethod(lambda x=0: IVal.coerce(x))

    @staticmethod
    def arange(n, dtype=None):
        return IVal.coerce(list(range(int(n))))

    @staticmethod
    def asarray(x, dtype=None):
        return IVal.coerce(x)

    @staticmethod
    def array(x, dtype=None):
        return IVal.coerce(x)

    @staticmethod
    def bool_(x):
        return IVal.coerce(int(bool(x)) if isinstance(x, bool) else x)

    @staticmethod
    def minimum(a, b):
        a, b = IVal.coerce(a), IVal.coerce(b)
        return IVal(np.minimum(a.lo + 0 * b.lo, b.lo + 0 * a.lo),
                    np.minimum(a.hi + 0 * b.hi, b.hi + 0 * a.hi),
                    deps=a.deps | b.deps)

    @staticmethod
    def maximum(a, b):
        a, b = IVal.coerce(a), IVal.coerce(b)
        return IVal(np.maximum(a.lo + 0 * b.lo, b.lo + 0 * a.lo),
                    np.maximum(a.hi + 0 * b.hi, b.hi + 0 * a.hi),
                    deps=a.deps | b.deps)

    @classmethod
    def clip(cls, x, lo, hi):
        return cls.maximum(cls.minimum(IVal.coerce(x), hi), lo)

    @staticmethod
    def where(cond, a, b):
        if isinstance(cond, (bool, np.bool_)):
            return IVal.coerce(a if cond else b)
        cond = IVal.coerce(cond)
        a, b = IVal.coerce(a), IVal.coerce(b)
        t, f = _defi(cond)
        shape = np.broadcast(cond.lo, a.lo, b.lo).shape
        t = np.broadcast_to(t, shape)
        f = np.broadcast_to(f, shape)
        alo = np.broadcast_to(a.lo, shape)
        ahi = np.broadcast_to(a.hi, shape)
        blo = np.broadcast_to(b.lo, shape)
        bhi = np.broadcast_to(b.hi, shape)
        lo = np.where(t, alo, np.where(f, blo, np.minimum(alo, blo)))
        hi = np.where(t, ahi, np.where(f, bhi, np.maximum(ahi, bhi)))
        return IVal(lo, hi, deps=cond.deps | a.deps | b.deps,
                    is_bool=a.is_bool and b.is_bool)

    @staticmethod
    def all(x, axis=None):
        x = IVal.coerce(x)
        if axis is not None:
            raise AnalysisUnsupported("axis reductions")
        t, f = _defi(x)
        lo = 1 if bool(np.all(t)) else 0
        hi = 0 if bool(np.any(f)) else 1
        return IVal(lo, hi, deps=x.deps, is_bool=True)

    @staticmethod
    def any(x, axis=None):
        x = IVal.coerce(x)
        if axis is not None:
            raise AnalysisUnsupported("axis reductions")
        t, f = _defi(x)
        lo = 1 if bool(np.any(t)) else 0
        hi = 0 if bool(np.all(f)) else 1
        return IVal(lo, hi, deps=x.deps, is_bool=True)

    @staticmethod
    def min(x, axis=None):
        x = IVal.coerce(x)
        if axis is not None:
            raise AnalysisUnsupported("axis reductions")
        return IVal(np.min(x.lo), np.min(x.hi), deps=x.deps)

    @staticmethod
    def max(x, axis=None):
        x = IVal.coerce(x)
        if axis is not None:
            raise AnalysisUnsupported("axis reductions")
        return IVal(np.max(x.lo), np.max(x.hi), deps=x.deps)

    @staticmethod
    def sum(x, axis=None, dtype=None):
        x = IVal.coerce(x)
        if axis is not None:
            raise AnalysisUnsupported("axis reductions")
        return IVal(np.sum(x.lo), np.sum(x.hi), deps=x.deps)

    @staticmethod
    def broadcast_to(x, shape):
        x = IVal.coerce(x)
        return IVal(np.broadcast_to(x.lo, shape).copy(),
                    np.broadcast_to(x.hi, shape).copy(), deps=x.deps)

    def __getattr__(self, name):
        raise AnalysisUnsupported(f"jnp.{name} is not modeled")


ABSTRACT_JNP = AbstractJnp()


# --------------------------------------------------------------------------
# abstract state + kernel execution
# --------------------------------------------------------------------------


def field_hull(field) -> IVal:
    """The declared-range hull of one packing Field, origin-tagged."""
    shape = field.shape or ()
    lo = np.full(shape, field.lo, dtype=object) if shape else \
        _obj(field.lo)
    hi = np.full(shape, field.hi, dtype=object) if shape else \
        _obj(field.hi)
    return IVal(lo, hi, origin=(field.name, ()),
                deps=frozenset([field.name]))


def state_hull(fields) -> dict:
    """Abstract state: every field at its declared-range hull."""
    return {f.name: field_hull(f) for f in fields}


def refine_state(state: dict, constraints):
    """Apply guard constraints (field, idx, 'le'|'ge', bound) to a fresh
    copy of the abstract state.  -> (refined_state, empty: bool); empty
    means some constraint contradicts the domain — the guard is
    statically unsatisfiable under the declared bounds."""
    out = {k: IVal(v.lo.copy(), v.hi.copy(), origin=v.origin,
                   deps=v.deps, is_bool=v.is_bool)
           for k, v in state.items()}
    empty = False
    for (field, idx, kind, bound) in constraints:
        if field not in out:
            continue
        v = out[field]
        lo, hi = v.lo, v.hi
        key = idx if idx else ()
        try:
            if kind == "le":
                hi[key] = min(hi[key], bound)
            else:
                lo[key] = max(lo[key], bound)
            if lo[key] > hi[key]:
                empty = True
        except IndexError:
            continue
    return out, empty


class _PatchedJnp:
    """Context manager: rebind the module-global ``jnp`` of every loaded
    model module (and the kernel's own defining module) to the abstract
    namespace for the duration of an abstract run.

    Kernel closures resolve ``jnp`` through their defining module's
    globals, so this is what makes the *shipped* kernel code run over
    the interval domain with zero transcription.  Single-threaded by
    contract: abstract runs happen at model-build/analyze time, never
    concurrently with an engine executing the same kernels.
    """

    def __init__(self, extra_globals=()):
        self._saved = []
        self._extra = list(extra_globals)

    def __enter__(self):
        seen = set()
        targets = []
        for name, mod in list(sys.modules.items()):
            if (mod is not None
                    and name.startswith("kafka_specification_tpu.models")
                    and hasattr(mod, "jnp")):
                targets.append(mod.__dict__)
        targets.extend(self._extra)
        for g in targets:
            gid = id(g)
            if gid in seen or "jnp" not in g:
                continue
            seen.add(gid)
            self._saved.append((g, g["jnp"]))
            g["jnp"] = ABSTRACT_JNP
        return self

    def __exit__(self, *exc):
        for g, old in self._saved:
            g["jnp"] = old
        return False


def run_kernel_abstract(kernel, state: dict, choice: int):
    """One abstract execution of an action kernel: returns
    (enabled: IVal, next_state: dict[str, IVal]).  The caller owns
    refinement and result interpretation."""
    extra = [kernel.__globals__] if hasattr(kernel, "__globals__") else []
    with _PatchedJnp(extra_globals=extra):
        try:
            enabled, nxt = kernel(dict(state), choice)
        except AnalysisUnsupported:
            raise
        except Exception as e:  # noqa: BLE001 — kernel outside the domain
            # e.g. the emitted models' symbolic-evaluator closures, which
            # drive jnp through machinery this domain does not model: an
            # honest skip (INFO finding), never a guessed hull
            raise AnalysisUnsupported(
                f"kernel not abstractly executable "
                f"({type(e).__name__}: {e})"
            ) from e
    if not isinstance(enabled, IVal):
        enabled = IVal.coerce(int(bool(enabled)) if
                              isinstance(enabled, (bool, np.bool_))
                              else enabled)
    return enabled, nxt


def definitely_disabled(enabled: IVal) -> bool:
    """jnp truthiness: a guard is statically false iff its interval is
    exactly {0} (a negative hull is NONZERO, i.e. possibly enabled)."""
    e = IVal.coerce(enabled)
    return e.shape == () and e.lo.item() == 0 and e.hi.item() == 0


def analyze_action_choice(kernel, fields, choice: int):
    """The two-pass (collect guards, re-run refined) abstract execution
    of one (action, choice) pair.

    -> dict with:
       enabled: IVal (refined run's guard value)
       next:    {field: IVal} (refined run's next state)
       base:    {field: IVal} (the hull state the run started from —
                identity anchor for written-field detection)
    """
    base = state_hull(fields)
    enabled0, nxt0 = run_kernel_abstract(kernel, base, choice)
    cons = enabled0.constraints
    if not cons or definitely_disabled(enabled0):
        return {"enabled": IVal.coerce(enabled0), "next": nxt0,
                "base": base}
    refined, empty = refine_state(base, cons)
    if empty:
        # the guard's own conjuncts contradict the declared bounds:
        # statically unsatisfiable — report definitely-disabled and keep
        # the unrefined next (the successor is unreachable)
        return {"enabled": IVal(0, 0, is_bool=True), "next": nxt0,
                "base": base}
    # the refined state's IVals are fresh objects; written-field
    # detection compares identities against THIS state dict
    enabled, nxt = run_kernel_abstract(kernel, refined, choice)
    return {"enabled": IVal.coerce(enabled), "next": nxt,
            "base": refined}
