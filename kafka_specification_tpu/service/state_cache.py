"""Persistent state-space cache: checked explorations as keyed artifacts.

The kernel cache (service/kernel_cache.py) made *compilation* a keyed
O(1) artifact, following the compiler-first cache design of
arXiv:2603.09555 (PAPERS.md).  This module extends the same pattern to
the *explored state space itself*: the spilled-sorted-run + digest-chain
machinery (PRs 2, 9) already makes the visited set a portable,
verifiable object, so a completed check can publish it — and a repeat
check of the unchanged config becomes a **chain-verified cache hit** in
O(verify) instead of O(explore), while a config-delta check (a deeper
``max_depth`` over the same schema) **seeds its frontier from the cached
boundary** instead of re-exploring from Init.

Trust-but-verify is the whole contract.  A cache entry is never believed,
it is *re-proven* at lookup time:

- the entry record carries a self-digest (sha256 over its canonical
  JSON) — bit rot in the metadata is caught before anything is trusted;
- the visited set is a ``KRUN1`` sorted-run file (storage/runs.py) whose
  content CRC is verified on open, exactly like a spill run;
- the per-level digest chain must re-verify (hash-chain linkage + level
  counts, resilience.integrity.chain_array_errors) and its cumulative
  (count, xor, sum) multiset digest must equal the digest of the stored
  visited set — a CRC-consistent corruption (flipped before the CRC was
  computed) is still caught, the same property checkpoint chains have;
- the boundary frontier's fingerprint multiset must digest to the
  chain's entry at the boundary depth (the same check the engine runs at
  every level boundary on the seeded frontier, so a corrupt boundary is
  caught twice: here and in-engine).

ANY failure — verification, version skew, unreadable files, a publish
ENOSPC — degrades to a cold run with a typed ``cache-fallback`` event.
The cache can cost a re-exploration; it can never cost a wrong verdict.

Key schema (``kspec-state-cache/1``).  An entry is keyed by everything
that shapes the *verdict*: module, kernel source (emitted/hand),
canonical CONSTANTS, the ORDERED invariant selection (first-violation
order is semantic), constraints, the deadlock flag, and the
``max_depth``/``max_states`` bounds.  Engine knobs (pipeline, backend,
chunk size, overlap) deliberately do NOT key: the bit-identity contracts
pin the verdict invariant across all of them.  Bounds split the key in
two levels on disk::

    <root>/<base16>/                          base = everything but bounds
        d<depth>-s<states>/entry.json         one entry per bounds pair
        d<depth>-s<states>/visited-<u>.run    sorted u64 fingerprints
                                              (KRUN1)
        d<depth>-s<states>/boundary-<u>.npy   deepest level's packed rows

so a delta lookup (same base, larger depth bound) is a directory scan of
the base, not of the whole cache.  The cache root defaults to
``<svc>/state-cache`` but may be any shared directory
(``--state-cache-dir`` / ``$KSPEC_STATE_CACHE_DIR``): entries are
content-addressed and re-proven on every read, so N hosts can share ONE
namespace — a hit published by host A serves chain-verified from host B
with no coordination beyond the filesystem (cache FEDERATION,
docs/service.md).  Data filenames carry a per-publisher nonce ``<u>``
and travel inside the entry record; concurrent same-key publishes each
write their own data files and race only the atomic ``entry.json``
promote — last promote wins, both candidates were chain-valid, and the
loser's orphaned files are garbage-collected (grace-aged) by later
publishes.  A reader mid-race sees the OLD entry, the NEW entry, or a
verification failure that degrades to a typed cold run — never a torn
read.

Publication happens after a completed SOLO run (the daemon's singleton
path): the per-level packed rows the trace store already holds are
fingerprinted host-side (integrity.fingerprint_rows — the bit-exact
numpy twin of the engine kernel), folded into a fresh LevelDigestChain
(bit-identical to the engine's own chain by construction), and written
files-first / entry-last under tmp-write + atomic promote — a torn
publish leaves data files without an entry, which is invisible, never a
half-trusted artifact.  Violating runs publish a verdict-only entry (no
artifact: their exploration stopped at the violation, so there is no
boundary to seed from — but the verdict itself is deterministic and
cache-hittable).

Fault sites (resilience.faults): ``flip@cache:N`` corrupts the Nth
published artifact after its promote (the next lookup must reject it);
``enospc@cache:N`` raises at the Nth publish's entry-promote point (the
publish aborts cleanly; the job's verdict is untouched).

Must stay jax-free: lookup/verify run in the daemon but also in tests
and offline tooling on boxes with no accelerator stack.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..resilience import integrity as _integ
from ..resilience.faults import FaultPlan, corrupt_file
from .. import durable_io as _dio
from ..utils import clock as _clk
from ..storage.atomic import atomic_write
from ..storage.runs import RunCorrupt, SortedRun, write_run

CACHE_SCHEMA = "kspec-state-cache/1"

#: artifact-size gate: runs past this many distinct states publish a
#: verdict-only entry (the verdict is still O(verify)-hittable; only the
#: boundary-seeding artifact is skipped).  Env twin for operators.
DEFAULT_MAX_ARTIFACT_STATES = int(
    os.environ.get("KSPEC_STATE_CACHE_MAX_STATES", str(2_000_000))
)

#: per-process publish ordinal (flip@cache:N / enospc@cache:N fault
#: grammar counts publishes the way crash@merge counts merges)
_publish_ordinal = {"n": 0}


@dataclass(frozen=True)
class CacheKey:
    """Everything that shapes a verdict (see module docstring)."""

    module: str
    emitted: bool
    constants: tuple  # canonical ((name, value-or-tuple), ...) pairs
    invariants: tuple  # ORDERED — first-violation order is semantic
    constraints: tuple
    check_deadlock: bool
    max_depth: Optional[int] = None
    max_states: Optional[int] = None

    def base_dict(self) -> dict:
        return {
            "module": self.module,
            "emitted": bool(self.emitted),
            "constants": [[k, list(v) if isinstance(v, tuple) else v]
                          for k, v in self.constants],
            "invariants": list(self.invariants),
            "constraints": list(self.constraints),
            "check_deadlock": bool(self.check_deadlock),
        }

    def base_digest(self) -> str:
        payload = json.dumps(self.base_dict(), sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    def bounds_name(self) -> str:
        return bounds_name(self.max_depth, self.max_states)


def bounds_name(max_depth, max_states) -> str:
    return (
        f"d{'N' if max_depth is None else int(max_depth)}"
        f"-s{'N' if max_states is None else int(max_states)}"
    )


def canonical_constants(constants: dict) -> tuple:
    """Same canonical form as kernel_cache.canonical_constants (kept
    local so this module stays importable without the model builders)."""
    return tuple(
        (k, tuple(v) if isinstance(v, list) else v)
        for k, v in sorted(constants.items())
    )


def key_for_job(spec: dict, cfg, emitted: bool, invariants: tuple) -> CacheKey:
    """The cache key a queued job resolves to (the daemon's entry point).
    `invariants` must be the job's RESOLVED, ordered invariant names
    (kernel_cache.job_invariants) — exactly what a solo check builds."""
    return CacheKey(
        module=spec["module"],
        emitted=bool(emitted),
        constants=canonical_constants(cfg.constants),
        invariants=tuple(invariants),
        constraints=tuple(cfg.constraints),
        check_deadlock=bool(cfg.check_deadlock),
        max_depth=spec.get("max_depth"),
        max_states=spec.get("max_states"),
    )


@dataclass
class CacheHit:
    """Chain-verified exact (or exhausted-superset) hit: return the
    cached verdict, run nothing."""

    verdict: dict
    entry: dict
    reason: str = "exact"  # exact | exhausted-superset


@dataclass
class CacheSeed:
    """Config-delta hit: seed the engine from the cached boundary.
    `seed` plugs straight into engine.bfs.check(seed=...)."""

    seed: dict
    from_depth: int
    entry: dict


class VerifyFailed(Exception):
    """An entry failed its trust-but-verify pass (reason in args[0])."""


@dataclass
class StateSpaceCache:
    root: str
    fault_plan: Optional[FaultPlan] = None
    event: Optional[object] = None  # callable(kind, **fields)
    max_artifact_states: int = DEFAULT_MAX_ARTIFACT_STATES
    stats: dict = field(default_factory=lambda: {
        "hits": 0, "seeds": 0, "misses": 0, "publishes": 0, "fallbacks": 0,
    })

    # --- events -----------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        if self.event is not None:
            try:
                self.event(kind, **fields)
            except Exception:  # noqa: BLE001 — telemetry must not fail jobs
                pass

    def _fallback(self, key: CacheKey, reason: str, **fields) -> None:
        """THE typed degradation event: every path that abandons the
        cache (verify failure, version skew, read error, publish ENOSPC)
        funnels here, so operators see one event kind with a reason."""
        self.stats["fallbacks"] += 1
        self._event(
            "cache-fallback",
            reason=reason,
            module=key.module,
            base=key.base_digest(),
            bounds=key.bounds_name(),
            **fields,
        )

    # --- paths ------------------------------------------------------------
    def _entry_dir(self, key: CacheKey, bounds: Optional[str] = None) -> str:
        return os.path.join(
            self.root, key.base_digest(), bounds or key.bounds_name()
        )

    # --- lookup -----------------------------------------------------------
    def lookup(self, key: CacheKey):
        """-> CacheHit | CacheSeed | None.  Never raises: any failure is
        a cache-fallback event + None (the caller runs cold)."""
        try:
            entry = self._load_verified(key, key.bounds_name(),
                                        want_key=key)
        except VerifyFailed as e:
            self._fallback(key, str(e.args[0]))
            return None
        if entry is not None:
            self.stats["hits"] += 1
            self._event(
                "state-cache-hit",
                module=key.module,
                base=key.base_digest(),
                bounds=key.bounds_name(),
            )
            return CacheHit(verdict=dict(entry["verdict"]), entry=entry)
        delta = self._delta_lookup(key)
        if delta is None:
            self.stats["misses"] += 1
        return delta

    def _delta_lookup(self, key: CacheKey):
        """Same base key, smaller depth bound, clean run: seed from the
        cached boundary (or return the verdict outright when the cached
        run already exhausted the space — a larger bound cannot change
        an exhausted verdict)."""
        if key.max_states is not None:
            return None  # state-count bounds do not delta cleanly
        base_dir = os.path.join(self.root, key.base_digest())
        try:
            names = sorted(os.listdir(base_dir))
        except OSError:
            return None
        best = None  # (cached_max_depth, bounds_name)
        for name in names:
            if not name.endswith("-sN") or name == key.bounds_name():
                continue
            if not name.startswith("d") or name[1:2] == "N":
                continue
            try:
                cached_depth = int(name[1:].split("-")[0])
            except ValueError:
                continue
            if key.max_depth is not None and cached_depth >= key.max_depth:
                continue
            if best is None or cached_depth > best[0]:
                best = (cached_depth, name)
        if best is None:
            return None
        try:
            entry = self._load_verified(key, best[1], want_key=None)
        except VerifyFailed as e:
            self._fallback(key, str(e.args[0]), delta_base=best[1])
            return None
        if entry is None:
            return None
        v = entry["verdict"]
        if v.get("exit_code") != 0 or v.get("violation") is not None:
            return None  # only clean explorations seed
        if not entry.get("bound_limited"):
            # the cached run exhausted the state space below its bound:
            # any larger bound yields the identical verdict
            self.stats["hits"] += 1
            self._event(
                "state-cache-hit",
                module=key.module,
                base=key.base_digest(),
                bounds=key.bounds_name(),
                via=best[1],
                exhausted=True,
            )
            return CacheHit(
                verdict=dict(v), entry=entry, reason="exhausted-superset"
            )
        if entry.get("artifact") is None:
            return None  # verdict-only entry (size-gated): nothing to seed
        seed = self._seed_from_entry(entry)
        self.stats["seeds"] += 1
        self._event(
            "state-cache-seed",
            module=key.module,
            base=key.base_digest(),
            bounds=key.bounds_name(),
            from_depth=best[0],
        )
        return CacheSeed(seed=seed, from_depth=best[0], entry=entry)

    # --- verification -----------------------------------------------------
    def _load_verified(self, key: CacheKey, bounds: str,
                       want_key: Optional[CacheKey]) -> Optional[dict]:
        """Load + trust-but-verify one entry; None = absent, VerifyFailed
        = present but not trustworthy (the caller emits the fallback)."""
        d = self._entry_dir(key, bounds)
        path = os.path.join(d, "entry.json")
        try:
            with open(path) as fh:
                entry = json.load(fh)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            raise VerifyFailed(f"entry-unreadable: {e}")
        if entry.get("schema") != CACHE_SCHEMA:
            raise VerifyFailed(
                f"version-skew: entry schema {entry.get('schema')!r} != "
                f"{CACHE_SCHEMA}"
            )
        if entry_self_digest(entry) != entry.get("self_digest"):
            raise VerifyFailed("entry-corrupt: self-digest mismatch")
        if want_key is not None and entry.get("key") != want_key.base_dict():
            raise VerifyFailed("entry-corrupt: key mismatch (collision?)")
        art = entry.get("artifact")
        if art is not None:
            # the verified arrays ride the entry so a seed consumer
            # never re-reads + re-CRCs the files it just proved
            # (_seed_from_entry pops them; exact hits just drop them)
            entry["_verified"] = self._verify_artifact(d, entry, art)
        _integ.count_check()
        return entry

    def _verify_artifact(self, d: str, entry: dict, art: dict) -> tuple:
        """The chain-verified part: visited-run CRC, chain linkage +
        counts, cumulative multiset digest, boundary digest.
        -> (visited_fps uint64, boundary uint32 rows), both verified."""
        levels = entry["verdict"]["levels"]
        chain_arr = np.asarray(art["chain"], np.uint64)
        errs = _integ.chain_array_errors(chain_arr, levels=levels)
        if errs:
            raise VerifyFailed(f"artifact-corrupt: {errs[0]}")
        try:
            run = SortedRun(d, art["visited"], verify=True)
        except RunCorrupt as e:
            raise VerifyFailed(f"artifact-corrupt: {e}")
        chain = _integ.LevelDigestChain.from_array(chain_arr)
        if _integ.digest_fps(np.asarray(run.arr)) != chain.cumulative():
            raise VerifyFailed(
                "artifact-corrupt: visited-set digest does not match the "
                "chain's cumulative (CRC-consistent corruption)"
            )
        boundary = self._read_boundary(d, art)
        depth = len(levels) - 1
        c, x, s = _integ.digest_fps(
            _integ.fingerprint_rows(boundary, bool(entry["exact64"]))
        )
        if (c, x, s) != tuple(chain.entries[depth][:3]):
            raise VerifyFailed(
                "artifact-corrupt: boundary frontier digest does not "
                f"match the chain entry at depth {depth}"
            )
        return np.asarray(run.arr, np.uint64).copy(), boundary

    def _read_boundary(self, d: str, art: dict) -> np.ndarray:
        import zlib

        path = os.path.join(d, art["boundary"]["name"])
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError as e:
            raise VerifyFailed(f"artifact-corrupt: boundary unreadable: {e}")
        if zlib.crc32(raw) != int(art["boundary"]["crc32"]):
            raise VerifyFailed("artifact-corrupt: boundary CRC mismatch")
        import io

        arr = np.load(io.BytesIO(raw), allow_pickle=False)
        return np.ascontiguousarray(arr, np.uint32)

    def _seed_from_entry(self, entry: dict) -> dict:
        """verified entry -> the engine's seed dict, reusing the arrays
        the verification pass already read + proved (no second I/O or
        CRC on the serving hot path)."""
        visited, boundary = entry.pop("_verified")
        levels = [int(v) for v in entry["verdict"]["levels"]]
        return {
            "visited_fps": visited,
            "frontier": boundary,
            "levels": levels,
            "total": int(entry["verdict"]["distinct_states"]),
            "depth": len(levels) - 1,
            "digest_chain": np.asarray(
                entry["artifact"]["chain"], np.uint64
            ),
        }

    # --- publication ------------------------------------------------------
    def publish(self, key: CacheKey, verdict: dict, *,
                exact64: bool, lanes: int,
                level_rows: Optional[list] = None,
                diameter: Optional[int] = None) -> bool:
        """Publish one completed solo run.  `verdict` is the semantic
        kspec-verdict/1 subset (model/distinct_states/diameter/levels/
        violation/exit_code).  `level_rows` — per-level packed uint32
        rows (the trace store's rows column) — enables the seedable
        artifact; None (or a violating/oversized run) publishes a
        verdict-only entry.  Returns True iff an entry was promoted;
        every failure is a cache-fallback event, never an exception."""
        plan = self.fault_plan or FaultPlan("")
        _publish_ordinal["n"] += 1
        ordinal = _publish_ordinal["n"]
        clean = (
            verdict.get("exit_code") == 0
            and verdict.get("violation") is None
        )
        levels = verdict.get("levels") or []
        with_artifact = (
            clean
            and level_rows is not None
            and len(level_rows) == len(levels)
            and int(verdict.get("distinct_states") or 0)
            <= self.max_artifact_states
            and key.max_states is None
        )
        d = self._entry_dir(key)
        entry = {
            "schema": CACHE_SCHEMA,
            "created_unix": round(_clk.now(), 3),
            "key": key.base_dict(),
            "max_depth": key.max_depth,
            "max_states": key.max_states,
            "lanes": int(lanes),
            "exact64": bool(exact64),
            # bound-limited = the run stopped AT its depth bound with a
            # live frontier (diameter == max_depth); an exhausted run's
            # verdict covers every larger bound outright
            "bound_limited": bool(
                key.max_depth is not None
                and diameter is not None
                and int(diameter) == int(key.max_depth)
            ),
            "verdict": {
                k: verdict.get(k)
                for k in ("model", "distinct_states", "diameter", "levels",
                          "violation", "exit_code", "states_per_sec",
                          "seconds")
            },
            "artifact": None,
        }
        try:
            os.makedirs(d, exist_ok=True)
            art_files = []
            # per-publisher nonce: names this publisher's data files AND
            # privatises the entry-promote tmp, so two hosts racing the
            # same key never touch each other's in-flight bytes — the
            # promote itself (os.replace) is the only shared step, and
            # it is atomic: last promote wins
            nonce = f"{os.getpid():x}-{os.urandom(4).hex()}"
            if with_artifact:
                chain = _integ.LevelDigestChain()
                all_fps = []
                for depth, rows in enumerate(level_rows):
                    fps = _integ.fingerprint_rows(
                        np.ascontiguousarray(rows, np.uint32), exact64
                    )
                    chain.fold(fps)
                    chain.seal(depth, int(levels[depth]))
                    all_fps.append(fps)
                visited = np.sort(np.concatenate(all_fps))
                # per-publisher data filenames (the names travel in the
                # entry record, so lookup never assumes them): two hosts
                # racing the same key each write their OWN data files and
                # only the entry.json promote decides the winner — with a
                # shared fixed name, a reader could open A's entry over
                # B's half-written run, a torn read no verifier owes a
                # defense against
                run_path = os.path.join(d, f"visited-{nonce}.run")
                run_meta = write_run(run_path, visited)
                art_files.append(run_path)
                boundary = np.ascontiguousarray(level_rows[-1], np.uint32)
                b_path = os.path.join(d, f"boundary-{nonce}.npy")
                b_crc = _write_npy(b_path, boundary)
                art_files.append(b_path)
                entry["artifact"] = {
                    "visited": run_meta,
                    "boundary": {"name": os.path.basename(b_path),
                                 "crc32": b_crc,
                                 "rows": int(boundary.shape[0])},
                    "chain": [[int(v) for v in row]
                              for row in chain.to_array().tolist()],
                }
            entry["self_digest"] = entry_self_digest(entry)
            payload = json.dumps(entry, sort_keys=True).encode()
            atomic_write(
                os.path.join(d, "entry.json"),
                lambda fh: fh.write(payload),
                # the publish commit point: enospc@cache:N fires here,
                # after the data files but before the entry promote —
                # exactly what a real full disk does mid-publish (data
                # without an entry is invisible; nothing half-trusted)
                before_replace=lambda: plan.enospc("cache", ordinal),
                tmp_nonce=nonce,
            )
        except OSError as e:
            self._fallback(key, f"publish-error: {e}", ordinal=ordinal)
            return False
        except _integ.IntegrityError as e:
            # fold/seal count disagreement: the run's own accounting and
            # its rows diverged — do not publish a lying artifact
            self._fallback(key, f"publish-integrity: {e}", ordinal=ordinal)
            return False
        self.stats["publishes"] += 1
        self._event(
            "state-cache-publish",
            module=key.module,
            base=key.base_digest(),
            bounds=key.bounds_name(),
            artifact=entry["artifact"] is not None,
            states=verdict.get("distinct_states"),
        )
        # a lost promote race leaves this publisher's data files orphaned
        # in the entry dir: collect whatever the CURRENT entry does not
        # reference (grace-aged, so a racing publisher mid-write is never
        # collected before its own promote)
        self.collect_garbage(key)
        # flip@cache:N — the silent-corruption rehearsal: bytes flip in
        # the promoted artifact; the NEXT lookup's verification must
        # reject it (cache-fallback + cold run, never a wrong verdict)
        if plan.flip("cache", ordinal):
            target = (
                art_files[0]
                if art_files
                else os.path.join(d, "entry.json")
            )
            try:
                corrupt_file(target, n_bytes=16)
            except OSError:
                pass
        return True

    def collect_garbage(self, key: CacheKey,
                        grace_s: Optional[float] = None) -> list:
        """Remove data files in `key`'s entry dir that the CURRENT
        promoted entry does not reference — the loser's artifacts after a
        concurrent same-key publish race (both candidates were chain-
        valid; last entry-promote won; the loser's uniquely-named run/
        boundary files are invisible to every reader and now dead
        weight).  Files younger than the grace window (default
        KSPEC_STATE_CACHE_GC_GRACE_S, 120s) are kept: they may belong to
        a publisher whose promote hasn't landed yet.  Returns the
        basenames removed; never raises."""
        if grace_s is None:
            try:
                grace_s = float(
                    os.environ.get("KSPEC_STATE_CACHE_GC_GRACE_S", "120")
                )
            except ValueError:
                grace_s = 120.0
        d = self._entry_dir(key)
        referenced = {"entry.json"}
        try:
            with open(os.path.join(d, "entry.json")) as fh:
                entry = json.load(fh)
            art = entry.get("artifact") or {}
            if art.get("visited"):
                referenced.add(art["visited"]["name"])
            if art.get("boundary"):
                referenced.add(art["boundary"]["name"])
        except FileNotFoundError:
            # no entry was EVER promoted here: every data file is either
            # an in-flight publisher's (protected by the grace window
            # below) or a crashed first-publisher's orphan that no
            # future entry will ever reference (publishes mint fresh
            # nonce'd names) — the crashcheck `cache` scenario found
            # these accumulating forever when this case collected
            # nothing
            pass
        except (OSError, ValueError, KeyError, TypeError):
            # unreadable/torn entry: the atomic promote makes this
            # unreachable by crash, so treat it as transient (EIO, a
            # concurrent replace) — nothing is provably garbage
            return []
        # a referenced run's bloom sidecar is part of the artifact
        referenced |= {name + ".bloom" for name in tuple(referenced)}
        removed = []
        now = _clk.now()
        try:
            names = os.listdir(d)
        except OSError:
            return []
        for name in names:
            collectable = (
                name.endswith(".run") or name.endswith(".npy")
                # a loser's rebuilt-on-verify bloom sidecar dies with
                # its run
                or name.endswith(".bloom")
                # startup-janitor parity (crashcheck `cache` scenario):
                # a publisher killed mid-atomic-write leaves a nonce'd
                # entry tmp that atomic_write's cleanup-on-raise never
                # saw — once it outlives the same grace window that
                # protects an in-flight promote, it is provably a
                # mid-write death's orphan (no manifest references tmp
                # names)
                or name.endswith(".tmp") or ".tmp." in name
            )
            if name in referenced or not collectable:
                continue
            path = os.path.join(d, name)
            try:
                if now - os.path.getmtime(path) < grace_s:
                    continue
                _dio.unlink(path)
                removed.append(name)
            except OSError:
                continue
        return removed

    def iter_entries(self):
        """Corpus view of this cache (see :func:`iter_corpus`)."""
        return iter_corpus(self.root)


def iter_corpus(root: str):
    """Yield every readable, self-consistent entry record under a cache
    root — the STANDING CORPUS view (sweep/cost.py trains on it; `cli
    sweep` reports over it).  Light validation only: schema + self-digest
    (the cheap metadata checks); artifact chain verification is lookup's
    job, not a corpus scan's.  Bad entries are skipped, never fatal —
    this walks a live cache that concurrent daemons are promoting into.
    Each yielded dict gains ``_base``/``_bounds`` (its directory
    coordinates) for callers that need the on-disk address."""
    try:
        bases = sorted(os.listdir(root))
    except OSError:
        return
    for base in bases:
        base_dir = os.path.join(root, base)
        try:
            bounds_dirs = sorted(os.listdir(base_dir))
        except (OSError, NotADirectoryError):
            continue
        for bounds in bounds_dirs:
            path = os.path.join(base_dir, bounds, "entry.json")
            try:
                with open(path) as fh:
                    entry = json.load(fh)
            except (OSError, ValueError):
                continue
            if entry.get("schema") != CACHE_SCHEMA:
                continue
            if entry_self_digest(entry) != entry.get("self_digest"):
                continue
            entry["_base"] = base
            entry["_bounds"] = bounds
            yield entry


def entry_self_digest(entry: dict) -> str:
    """sha256 over the entry's canonical JSON minus the digest field —
    the metadata's own bit-rot detector."""
    body = {k: v for k, v in entry.items() if k != "self_digest"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()
    ).hexdigest()


def _write_npy(path: str, arr: np.ndarray) -> int:
    import io
    import zlib

    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    raw = buf.getvalue()
    atomic_write(path, lambda fh: fh.write(raw))
    return zlib.crc32(raw)
