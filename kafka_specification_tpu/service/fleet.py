"""Fault-tolerant serving-daemon fleet: ``cli serve-fleet --daemons N``.

PR 9 proved two daemons can drain one queue directory; this module gives
that shape a LIFECYCLE.  One jax-free parent launches N ``cli serve``
children over one service directory and keeps the fleet serving through
every failure mode the taxonomy names:

- **death** (any nonzero exit, or an unexpected clean exit): restart the
  slot with bounded jittered backoff (resilience.supervisor's policy);
  the restarted daemon's startup janitor requeues its predecessor's
  leased claims, so in-flight jobs survive the bounce;
- **wedge** (per-daemon heartbeat frozen past ``--stall-timeout``): kill
  the process tree (SIGTERM -> SIGKILL) and restart it; meanwhile a
  healthy sibling's periodic janitor takes the wedged daemon's claims
  over at lease expiry — the job does not wait for the restart;
- **rc 75** (typed RESOURCE_EXHAUSTED — the daemon itself ran out of
  service-dir disk): halt that slot with a resource-verdict event, never
  hot-loop a restart into the full disk (the existing supervisor
  contract, resilience.supervisor.classify_exit);
- **rc 76** (typed INTEGRITY_VIOLATION): restart, budget-bounded — the
  daemon's state is the queue + cache, both verified on read.

Autoscaling: queue depth drives the live-daemon count between ``--min``
and ``--max``.  Scale-up spawns a new instance when pending jobs exceed
``--scale-up-pending`` per live daemon; scale-down retires the
highest-numbered instance after ``--scale-down-idle`` seconds of empty
queue via a **graceful drain**: the parent touches
``service/drain/<i>``, the daemon finishes its claimed jobs, takes no
new ones, and exits 0 (service/daemon.py watches the marker).

Identity: each child runs with ``KSPEC_DAEMON_INSTANCE=i`` — it writes
``service/heartbeat-<i>.jsonl`` / ``metrics-<i>.prom`` (per-daemon
liveness and scrape files), stamps ``instance`` into shared events, and
becomes the target of the ``crash@daemon<i>:N`` / ``stall@daemon<i>``
fault sites (resilience.faults), which is how the whole lifecycle is
deterministically drillable from tier-1 tests.

Must stay jax-free: the parent never touches an accelerator (children
are full ``cli serve`` processes and do their own platform hygiene).
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Optional

from ..resilience.heartbeat import append_jsonl, heartbeat_record
from ..utils import clock as _clk
from ..resilience.supervisor import (
    SupervisorConfig,
    _hb_size,
    classify_exit,
)
from .queue import JobQueue


@dataclass
class FleetServeConfig:
    service_dir: str
    daemons: int = 2  # initial fleet size
    min_daemons: int = 1
    max_daemons: Optional[int] = None  # default: max(daemons, min)
    poll_s: float = 0.5
    stall_timeout: float = 120.0  # per-daemon heartbeat freeze -> kill
    max_restarts: int = 8  # per slot
    backoff_base: float = 1.0
    backoff_cap: float = 60.0
    jitter: float = 0.25
    term_grace: float = 10.0
    # autoscaling
    scale_interval_s: float = 5.0
    scale_up_pending: int = 4  # pending jobs per live daemon
    scale_down_idle_s: float = 60.0
    # child construction
    serve_args: tuple = ()  # extra argv appended to each `cli serve`
    # cache federation (docs/service.md): point every daemon of this
    # fleet at a SHARED state-cache root (entries are content-addressed
    # and re-proven per read, so N hosts federate over one namespace);
    # None keeps the per-service-dir default <svc>/state-cache
    state_cache_dir: Optional[str] = None
    # host identity for the routed fleet (service/router.py): exported
    # to every daemon as KSPEC_HOST_INSTANCE, scoping the kill@host<i> /
    # partition@host<i> / skew@host<i> chaos faults to this host
    host_instance: Optional[int] = None
    env: Optional[dict] = None
    command: Optional[object] = None  # callable(instance)->argv override
    events: Optional[str] = None  # default <svc>/service/fleet-events.jsonl
    log_dir: Optional[str] = None  # default <svc>/service/logs
    rng: random.Random = field(default_factory=random.Random, repr=False)

    backoff = SupervisorConfig.backoff

    def __post_init__(self):
        if self.max_daemons is None:
            self.max_daemons = max(self.daemons, self.min_daemons)
        self.daemons = max(self.min_daemons,
                           min(self.daemons, self.max_daemons))


class _Slot:
    """One daemon instance's lifecycle state."""

    def __init__(self, instance: int):
        self.instance = instance
        self.proc: Optional[subprocess.Popen] = None
        self.log_fh = None
        self.hb_size = 0
        self.last_progress = 0.0
        self.restarts_used = 0
        self.spawn_count = 0
        self.state = "down"  # down | up | draining | halted
        self.respawn_at: Optional[float] = None  # backoff deadline


class FleetManager:
    """The blocking fleet loop (``serve_fleet`` is the entry point).
    Single-threaded by design: every child interaction is a poll."""

    def __init__(self, cfg: FleetServeConfig):
        self.cfg = cfg
        self.queue = JobQueue(cfg.service_dir)
        svc = self.queue.service_dir
        os.makedirs(svc, exist_ok=True)
        self.drain_dir = os.path.join(svc, "drain")
        os.makedirs(self.drain_dir, exist_ok=True)
        self.events_path = cfg.events or os.path.join(
            svc, "fleet-events.jsonl"
        )
        self.log_dir = cfg.log_dir or os.path.join(svc, "logs")
        self.slots: list = []
        self._stop = False
        self._next_instance = 0
        self._idle_since: Optional[float] = None
        self._last_scale = 0.0

    # --- events -----------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        try:
            append_jsonl(
                self.events_path, heartbeat_record("fleet", event=kind,
                                                   **fields)
            )
        except OSError:
            pass  # telemetry must never take the fleet down

    # --- child management -------------------------------------------------
    def _hb_path(self, instance: int) -> str:
        return os.path.join(
            self.queue.service_dir, f"heartbeat-{instance}.jsonl"
        )

    def _drain_marker(self, instance: int) -> str:
        return os.path.join(self.drain_dir, str(instance))

    def _command(self, instance: int) -> list:
        if self.cfg.command is not None:
            return list(self.cfg.command(instance))
        argv = [
            sys.executable, "-m", "kafka_specification_tpu.utils.cli",
            "serve", self.queue.dir,
        ] + list(self.cfg.serve_args)
        if self.cfg.state_cache_dir:
            argv += ["--state-cache-dir", self.cfg.state_cache_dir]
        return argv

    def _spawn(self, slot: _Slot) -> None:
        env = dict(self.cfg.env if self.cfg.env is not None else os.environ)
        env["KSPEC_DAEMON_INSTANCE"] = str(slot.instance)
        if self.cfg.host_instance is not None:
            env["KSPEC_HOST_INSTANCE"] = str(self.cfg.host_instance)
        if self.cfg.state_cache_dir:
            # command-override children (tests' stub daemons) get the
            # federation root too, even though _command wasn't consulted
            env["KSPEC_STATE_CACHE_DIR"] = self.cfg.state_cache_dir
        os.makedirs(self.log_dir, exist_ok=True)
        slot.spawn_count += 1
        if slot.log_fh is not None:
            slot.log_fh.close()
        slot.log_fh = open(
            os.path.join(
                self.log_dir,
                f"daemon{slot.instance}-spawn{slot.spawn_count:02d}.log",
            ),
            "wb",
        )
        # stale drain marker from a previous life must not instantly
        # retire the fresh daemon
        try:
            os.unlink(self._drain_marker(slot.instance))
        except OSError:
            pass
        slot.proc = subprocess.Popen(
            self._command(slot.instance),
            stdout=slot.log_fh,
            stderr=subprocess.STDOUT,
            env=env,
            start_new_session=True,  # stall-kill takes the whole tree
        )
        slot.state = "up"
        slot.respawn_at = None
        slot.hb_size = _hb_size(self._hb_path(slot.instance))
        slot.last_progress = _clk.monotonic()
        self._event(
            "daemon-start",
            instance=slot.instance,
            pid=slot.proc.pid,
            spawn=slot.spawn_count,
        )

    def _signal_tree(self, slot: _Slot, sig) -> None:
        if slot.proc is None:
            return
        try:
            os.killpg(slot.proc.pid, sig)
        except (OSError, ProcessLookupError):
            try:
                slot.proc.send_signal(sig)
            except (OSError, ProcessLookupError):
                pass

    def _kill(self, slot: _Slot) -> None:
        self._signal_tree(slot, signal.SIGTERM)
        deadline = _clk.monotonic() + self.cfg.term_grace
        while slot.proc.poll() is None and _clk.monotonic() < deadline:
            _clk.sleep(0.05)
        if slot.proc.poll() is None:
            self._signal_tree(slot, signal.SIGKILL)
            slot.proc.wait()

    def _schedule_restart(self, slot: _Slot, why: str, rc) -> None:
        """Bounded jittered backoff, or halt the slot at budget
        exhaustion.  A fleet with every slot halted gives up."""
        if slot.restarts_used >= self.cfg.max_restarts:
            slot.state = "halted"
            self._event(
                "daemon-give-up", instance=slot.instance, why=why, rc=rc,
                restarts=slot.restarts_used,
            )
            return
        slot.restarts_used += 1
        delay = self.cfg.backoff(slot.restarts_used)
        slot.state = "down"
        slot.respawn_at = _clk.monotonic() + delay
        self._event(
            "daemon-restart", instance=slot.instance, why=why, rc=rc,
            backoff_s=round(delay, 2), restarts=slot.restarts_used,
        )

    # --- per-iteration checks ---------------------------------------------
    def _reap_and_watch(self) -> None:
        now = _clk.monotonic()
        for slot in list(self.slots):  # a drained slot removes itself
            if slot.state == "down":
                if slot.respawn_at is not None and now >= slot.respawn_at:
                    self._spawn(slot)
                continue
            if slot.state == "halted" or slot.proc is None:
                continue
            rc = slot.proc.poll()
            if rc is not None:
                self._classify_exit(slot, rc)
                continue
            # wedge detection: per-daemon heartbeat growth (an idle
            # daemon still ticks every few seconds, so frozen == wedged,
            # never merely busy — service/daemon.py's contract)
            size = _hb_size(self._hb_path(slot.instance))
            if size != slot.hb_size:
                slot.hb_size = size
                slot.last_progress = now
            elif now - slot.last_progress > self.cfg.stall_timeout:
                self._event(
                    "daemon-stall", instance=slot.instance,
                    pid=slot.proc.pid,
                    stall_timeout=self.cfg.stall_timeout,
                )
                self._kill(slot)
                self._schedule_restart(slot, "stall", None)

    def _classify_exit(self, slot: _Slot, rc: int) -> None:
        """The daemon-death taxonomy (resilience.supervisor.classify_exit):
        death -> bounded restart; rc-75 -> halt with a verdict (never
        restart into a full disk); rc-76 -> bounded restart; a clean
        exit is terminal only when WE asked for it (drain)."""
        kind = classify_exit(rc)
        self._event(
            "daemon-exit", instance=slot.instance, rc=rc, classified=kind,
            draining=slot.state == "draining",
        )
        if slot.state == "draining" and kind == "ok":
            # graceful retirement completed (scale-down)
            try:
                os.unlink(self._drain_marker(slot.instance))
            except OSError:
                pass
            slot.state = "halted"
            self._event("fleet-scale-down", instance=slot.instance)
            if slot.log_fh is not None:
                slot.log_fh.close()
                slot.log_fh = None
            self.slots.remove(slot)
            return
        if kind == "resource":
            # the daemon ITSELF ran out of service-dir disk: restarting
            # would hot-loop into the same full disk — halt the slot
            # with the actionable verdict, keep the siblings serving
            slot.state = "halted"
            self._event(
                "daemon-resource-exhausted", instance=slot.instance, rc=rc,
            )
            print(
                f"[fleet] daemon {slot.instance} exited RESOURCE_EXHAUSTED"
                f" (rc={rc}); NOT restarting it into a full service dir — "
                "free space, then restart the fleet",
                file=sys.stderr,
            )
            return
        if kind == "integrity":
            self._event(
                "daemon-integrity-violation", instance=slot.instance, rc=rc,
            )
        # crashes, integrity exits and unexpected clean exits all
        # restart (bounded): the queue is the durable state, the
        # restarted daemon's janitor requeues its predecessor's claims
        self._schedule_restart(slot, kind, rc)

    def _autoscale(self) -> None:
        now = _clk.monotonic()
        if now - self._last_scale < self.cfg.scale_interval_s:
            return
        self._last_scale = now
        try:
            pending = self.queue.pending_count()
            claimed = self.queue.claimed_count()
        except OSError:
            return
        live = [s for s in self.slots if s.state in ("up", "down")]
        # scale UP: queue depth per live daemon over the threshold
        if (
            pending > self.cfg.scale_up_pending * max(1, len(live))
            and len(live) < self.cfg.max_daemons
        ):
            slot = _Slot(self._next_instance)
            self._next_instance += 1
            self.slots.append(slot)
            self._event(
                "fleet-scale-up", instance=slot.instance, pending=pending,
                live=len(live),
            )
            self._spawn(slot)
            self._idle_since = None
            return
        # scale DOWN: drained queue for long enough -> graceful retire
        if pending == 0 and claimed == 0:
            if self._idle_since is None:
                self._idle_since = now
            elif (
                now - self._idle_since >= self.cfg.scale_down_idle_s
                and len(live) > self.cfg.min_daemons
            ):
                victim = max(
                    (s for s in live if s.state == "up"),
                    key=lambda s: s.instance,
                    default=None,
                )
                if victim is not None:
                    victim.state = "draining"
                    with open(self._drain_marker(victim.instance), "w"):
                        pass
                    self._event(
                        "fleet-drain", instance=victim.instance,
                        idle_s=round(now - self._idle_since, 1),
                    )
                    self._idle_since = now  # one retirement per window
        else:
            self._idle_since = None

    # --- lifecycle --------------------------------------------------------
    def request_stop(self, *_a) -> None:
        self._stop = True

    def run(self) -> int:
        """Serve until stopped; 0 on a requested stop, 1 when every slot
        halted (give-up / resource verdicts — see the event log)."""
        for _ in range(self.cfg.daemons):
            slot = _Slot(self._next_instance)
            self._next_instance += 1
            self.slots.append(slot)
            self._spawn(slot)
        self._event(
            "fleet-start", daemons=self.cfg.daemons,
            min=self.cfg.min_daemons, max=self.cfg.max_daemons,
        )
        try:
            while not self._stop:
                self._reap_and_watch()
                self._autoscale()
                if self.slots and all(
                    s.state == "halted" for s in self.slots
                ):
                    self._event("fleet-give-up")
                    print(
                        "[fleet] every daemon slot halted (restart budget "
                        f"or resource verdicts); see {self.events_path}",
                        file=sys.stderr,
                    )
                    return 1
                _clk.sleep(self.cfg.poll_s)
        finally:
            for slot in self.slots:
                if slot.proc is not None and slot.proc.poll() is None:
                    self._kill(slot)
                if slot.log_fh is not None:
                    slot.log_fh.close()
            self._event("fleet-stop")
        return 0


def serve_fleet_daemons(cfg: FleetServeConfig) -> int:
    """``cli serve-fleet`` entry point: run the fleet until SIGTERM/
    SIGINT, then tear the children down and exit 0."""
    mgr = FleetManager(cfg)
    old_term = signal.signal(signal.SIGTERM, mgr.request_stop)
    old_int = signal.signal(signal.SIGINT, mgr.request_stop)
    try:
        return mgr.run()
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
