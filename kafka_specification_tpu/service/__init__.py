"""Checking-as-a-service: a warm multi-tenant serving daemon.

Every check used to be a fresh CLI process — ~2 minutes cold, ~9 seconds
warm (TODO.md) — which caps "heavy traffic from millions of users" at one
run per operator.  This package turns the checker into a service
(ROADMAP item 3):

- :mod:`queue` — a durable on-disk job queue (atomic rename state
  machine); the jax-FREE tenant side: ``cli submit`` writes a job spec,
  ``cli status`` / ``cli result`` read verdicts — clients never pay the
  jax import.
- :mod:`daemon` — ``cli serve``: one process imports jax once, holds
  jitted engine kernels in a shape-keyed in-process cache, and drains the
  queue under per-tenant resource budgets.
- :mod:`kernel_cache` — the compile cache, keyed by model schema shape
  (module, kernel source, constants, invariants): the O(1) keyed-artifact
  pattern of arXiv:2603.09555 (PAPERS.md).
- :mod:`scheduler` — batching plan + per-tenant admission/budgets
  (re-using PR 5's ResourceGovernor: a breach exits that job rc-75 typed
  without touching the daemon or siblings).
- :mod:`batch` — batched multi-config checking: jobs sharing a schema
  shape are advanced by ONE engine run (one vmapped kernel launch per
  level for the whole group) and each member's verdict is derived
  bit-identically to a solo ``cli check``.
- :mod:`verdict` — the shared ``kspec-verdict/1`` record ``cli check
  --json``, the result files, and ``cli result`` all speak.

Importing this package is jax-free; only :mod:`daemon` /
:mod:`kernel_cache` touch jax, and only when the daemon actually runs —
docs/service.md is the operator guide.
"""

from .queue import JOB_SCHEMA, JobQueue, new_job_id
from .verdict import (
    VERDICT_SCHEMA,
    error_verdict,
    render_verdict,
    verdict_exit_code,
    verdict_from_result,
)

__all__ = [
    "JOB_SCHEMA",
    "JobQueue",
    "VERDICT_SCHEMA",
    "error_verdict",
    "new_job_id",
    "render_verdict",
    "verdict_exit_code",
    "verdict_from_result",
]
