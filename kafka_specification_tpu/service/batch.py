"""Batched multi-config checking: one exploration, N verdicts.

N jobs whose configs share a schema shape (scheduler.group_key: module,
kernel source, constants, constraints — the GPUexplore insight that
batched expansion dominates explicit-state throughput, PAPERS.md
arXiv:1801.05857) are advanced by ONE engine run: the per-level vmapped
successor kernels launch once for the whole group instead of once per
job, so N toy checks cost ~1 launch per level.  Members may differ in
invariant selection (a .cfg-level difference) and in ``max_depth`` /
``max_states``.

How it stays bit-identical to ``cli check`` run solo (the acceptance
contract):

1.  Exploration is invariant-agnostic: successor generation, CONSTRAINT
    pruning, fingerprinting, dedup and chunking depend only on (model,
    engine knobs) — a solo run differs from the shared run only in
    *stopping earlier*.  The shared run uses the same knobs and explores
    to the envelope of the members' bounds (max of max_depth/max_states,
    unbounded if any member is unbounded), so every member's solo
    exploration is a prefix of the shared one, level for level, row for
    row.
2.  The shared run records everything a verdict needs: per-level state
    arrays (``collect_levels``), the parent/action trace store
    (``collect_trace``), and per-level counts.
3.  Each member's verdict is then *replayed* against the shared record
    with exactly the solo engine's semantics: init-state invariant pass
    first; then per level, chunk by chunk (same ``_next_pow2`` chunk
    boundaries), first chunk with a violation wins, first invariant in
    the member's model order within that chunk, first row within that
    invariant; ``max_depth``/``max_states`` cut at the same loop points;
    the cut-off run's final frontier gets the solo post-loop invariant
    pass (whole-frontier, per-invariant order).  Counterexample traces
    walk the shared trace store through the same ``walk_trace`` the
    engine uses — identical states, identical actions.

The derived verdicts are therefore equal to the solo runs' in counts,
depths, invariant names, and trace values (tests/test_service.py pins
this against real solo runs, violation and all).

Memory note: the shared record holds every level's states in RAM — this
runner is for the toy/small configs a multi-tenant service coalesces,
not for out-of-core runs (job specs carry no storage knobs; big runs
belong on `cli check`).  Singleton groups never come here at all: the
daemon runs them through the real solo engine path — first-violation
early exit, streamed levels — so only genuine coalescing pays the
full-envelope exploration (service/daemon.py).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np

from ..engine.bfs import (
    CheckResult,
    PreparedKernels,
    Violation,
    _next_pow2,
    check,
    walk_trace,
)


@dataclass
class Member:
    """One job's verdict-relevant view of a shared exploration."""

    job_id: str
    invariants: tuple  # names, in the member's solo model order
    max_depth: Optional[int] = None
    max_states: Optional[int] = None


class SharedExploration:
    """The shared run's record + lazy per-(level, invariant) evaluation."""

    def __init__(self, model, result: CheckResult, collected: list,
                 trace: list, chunk: int):
        self.model = model
        self.result = result
        self.levels = result.levels
        self.collected = collected
        self.trace = trace
        self.chunk = chunk
        self._preds = {i.name: i.pred for i in model.invariants}
        self._ok: dict = {}  # (level, name) -> np.bool_ array
        self._viol: dict = {}  # (name, depth, idx) -> Violation

    def _pred_fn(self, name: str, bucket: int):
        """Jitted unpack+predicate over a power-of-two state bucket,
        cached on the MODEL (like the engine's step cache) so later
        groups of the same shape pay zero re-trace: eager vmap re-traces
        per call, which dominated warm derive latency on quantifier-heavy
        invariants."""
        cache = getattr(self.model, "_inv_eval_cache", None)
        if cache is None:
            cache = {}
            try:
                self.model._inv_eval_cache = cache
            except AttributeError:
                pass
        key = (name, bucket)
        if key not in cache:
            pred = self._preds[name]
            unpack = self.model.spec.unpack

            cache[key] = jax.jit(
                lambda packed: jax.vmap(lambda row: pred(unpack(row)))(packed)
            )
        return cache[key]

    def ok(self, level: int, name: str) -> np.ndarray:
        """invariant `name` holds per state of `level` (evaluated once per
        (level, name) for the whole group — members share the cache)."""
        key = (level, name)
        if key not in self._ok:
            import jax.numpy as jnp

            rows = self.collected[level]
            n = rows.shape[0]
            bucket = _next_pow2(max(32, n))
            if bucket != n:
                pad = np.zeros((bucket - n, rows.shape[1]), rows.dtype)
                rows = np.concatenate([rows, pad])
            ok = np.asarray(self._pred_fn(name, bucket)(jnp.asarray(rows)))
            self._ok[key] = ok[:n]  # padding rows are garbage: sliced off
        return self._ok[key]

    def violation(self, name: str, depth: int, idx: int) -> Violation:
        """Walk the shared trace store once per distinct (invariant,
        depth, row) — members of a group that trip the same violation
        (the common case: N tenants checking the same buggy config) share
        the decoded trace instead of re-walking it N times."""
        key = (name, depth, idx)
        if key not in self._viol:
            self._viol[key] = walk_trace(
                self.trace, self.model.actions, self.decode, name, depth, idx
            )
        return self._viol[key]

    def decode(self, packed_row: np.ndarray):
        import jax.numpy as jnp

        s = {
            k: np.asarray(v)
            for k, v in self.model.spec.unpack(jnp.asarray(packed_row)).items()
        }
        return self.model.decode(s) if self.model.decode else s


def shared_bounds(members: list) -> tuple:
    """Envelope of the members' depth/state bounds (None dominates)."""
    md = None
    if all(m.max_depth is not None for m in members):
        md = max(m.max_depth for m in members)
    ms = None
    if all(m.max_states is not None for m in members):
        ms = max(m.max_states for m in members)
    return md, ms


def pack_members(group: list, max_group: int) -> list:
    """Split one planned group into submit-order packs of at most
    ``max_group`` members — the group-width planning hook the scheduler
    (plan_groups max_group=) and the sweep portfolio share.  The shared
    record holds every level of the ENVELOPE exploration in RAM and a
    group runs at the max of its members' bounds, so a thousand-member
    sweep group must be width-capped; contiguous submit-order packs keep
    the bounds of a sorted sweep (shallow..deep) clustered, which keeps
    each pack's envelope near its members' own bounds."""
    if max_group <= 0 or len(group) <= max_group:
        return [list(group)]
    return [
        list(group[i:i + max_group])
        for i in range(0, len(group), max_group)
    ]


def explore_shared(
    model,
    members: list,
    prepared: Optional[PreparedKernels] = None,
    min_bucket: int = 256,
    chunk_size: int = 32768,
    visited_backend: str = "device",
    run=None,
    governor=None,
    stats_path: Optional[str] = None,
) -> SharedExploration:
    """One invariant-agnostic engine run covering every member's bounds."""
    md, ms = shared_bounds(members)
    collected: list = []
    trace: list = []
    res = check(
        model,
        max_depth=md,
        max_states=ms,
        store_trace=True,
        min_bucket=min_bucket,
        check_invariants=False,
        collect_levels=collected,
        collect_trace=trace,
        chunk_size=chunk_size,
        visited_backend=visited_backend,
        prepared=prepared,
        run=run,
        governor=governor,
        stats_path=stats_path,
        # warm-path: preallocate the visited set at EXACTLY the capacity
        # the last run of this shape reached — no capacity growth, no
        # step eviction, no warm recompiles (PreparedKernels.capacity_hint)
        visited_capacity_exact=(
            prepared.capacity_hint if prepared is not None else None
        ),
    )
    if prepared is not None:
        prepared.note_result(res)
    return SharedExploration(
        model, res, collected, trace,
        chunk=_next_pow2(max(min_bucket, chunk_size)),
    )


def derive_member(shared: SharedExploration, member: Member) -> CheckResult:
    """Replay one member's solo verdict from the shared record (see module
    docstring for the exact-equivalence argument)."""
    t0 = time.perf_counter()
    L, C, T = shared.levels, shared.collected, shared.trace
    model = shared.model
    n0 = L[0]
    levels = [n0]
    total = n0
    violation = None

    def finish(depth: int) -> CheckResult:
        dt = max(time.perf_counter() - t0, 1e-9)
        return CheckResult(
            model=model.name,
            levels=levels,
            total=total,
            diameter=len(levels) - 1,
            violation=violation,
            seconds=shared.result.seconds,
            states_per_sec=total / max(shared.result.seconds, 1e-9),
            stats={"derive_ms": round(dt * 1e3, 2)},
        )

    # init-state invariant pass (solo engine: before the level loop,
    # per-invariant in model order, whole init set)
    for name in member.invariants:
        ok = shared.ok(0, name)
        if not ok.all():
            idx = int(np.argmax(~ok))
            state = shared.decode(C[0][idx])
            violation = Violation(
                invariant=name, depth=0, state=state,
                trace=[("<init>", state)],
            )
            return finish(0)

    depth = 0
    cut = False
    while True:
        n_frontier = C[depth].shape[0] if depth < len(C) else 0
        if n_frontier == 0:
            break
        if member.max_depth is not None and depth >= member.max_depth:
            cut = True
            break
        if member.max_states is not None and total >= member.max_states:
            cut = True
            break
        # mid-level scan: first chunk (solo chunk boundaries) with any
        # member-invariant violation; within it, first invariant in the
        # member's model order; within that, first row
        verdict = None
        for start in range(0, n_frontier, shared.chunk):
            end = min(start + shared.chunk, n_frontier)
            for name in member.invariants:
                bad = ~shared.ok(depth, name)[start:end]
                if bad.any():
                    verdict = (name, start + int(np.argmax(bad)))
                    break
            if verdict is not None:
                break
        if verdict is not None:
            name, idx = verdict
            violation = shared.violation(name, depth, idx)
            break
        if depth + 1 >= len(C):
            # expanding this level produced nothing new: the solo loop's
            # next iteration sees an empty frontier and exits
            break
        depth += 1
        levels.append(L[depth])
        total += L[depth]

    if violation is None and member.invariants and cut \
            and depth < len(C) and C[depth].shape[0]:
        # solo post-loop pass: the cut left this frontier unexpanded, so
        # its states still owe their invariant check (whole-frontier,
        # per-invariant order — NOT the chunked mid-level rule)
        for name in member.invariants:
            ok = shared.ok(depth, name)
            if not ok.all():
                idx = int(np.argmax(~ok))
                violation = shared.violation(name, depth, idx)
                break
    return finish(depth)


def run_group(
    model,
    members: list,
    prepared: Optional[PreparedKernels] = None,
    **explore_kw,
) -> dict:
    """Explore once, derive every member.
    -> ({job_id: CheckResult}, SharedExploration)."""
    shared = explore_shared(model, members, prepared=prepared, **explore_kw)
    return {
        m.job_id: derive_member(shared, m) for m in members
    }, shared
