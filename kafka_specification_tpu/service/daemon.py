"""The serving daemon: ``cli serve`` — a warm, multi-tenant check runner.

One process imports jax ONCE, then drains the durable job queue forever:

    claim pending jobs -> plan groups (scheduler) -> for each group:
        kernel-cache lookup (shape-keyed model + prepared jitted steps)
        one engine run (batched: one exploration serves the whole group)
        per-job verdict files + per-job obs run dirs (PR 3 treatment)

Tenancy: each run executes under the job's tenant's ResourceGovernor
(tenants.json budgets).  A budget breach raises the engine's typed
ResourceExhausted INSIDE the job — the daemon writes that job an rc-75
verdict and keeps serving; sibling jobs and the daemon itself never see
it.  Any other per-job exception becomes an error verdict (exit_code 2)
the same way: one tenant's bad config cannot take the service down.

Liveness: the daemon appends heartbeat lines to
``service/heartbeat.jsonl`` — every few seconds when idle (size-rotated
so a serve-forever daemon stays bounded), and from a background thread
while the main thread is inside a long engine run, so the supervisor's
stall detector (``cli serve --supervised``;
resilience.supervisor.daemon_supervisor_config) kills wedged daemons,
never merely busy ones.
Queue depth, cache hit/miss, batch sizes and submit->verdict latency are
exported to ``service/metrics.prom`` for scraping.

Shutdown: SIGTERM/SIGINT finish the in-flight group, then exit 0; claims
of a killed daemon are re-queued by the next daemon's startup janitor.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from dataclasses import dataclass
from typing import Optional

from .. import durable_io as _dio
from ..utils import clock as _clk
from ..engine.bfs import check
from ..obs import RunContext, fleettrace
from ..obs.metrics import MetricsRegistry
from ..resilience.faults import FaultPlan, InjectedCrash, injected_skew_s
from ..resilience.heartbeat import append_jsonl, heartbeat_record
from ..resilience.integrity import EXIT_INTEGRITY, IntegrityError
from ..resilience.resources import ResourceExhausted
from .batch import Member, derive_member, explore_shared
from .kernel_cache import (
    KernelCache,
    job_cfg,
    job_invariants,
    resolve_kernel_source,
)
from .queue import JobQueue
from .scheduler import TenantPolicy, plan_groups, union_invariants
from .verdict import (
    EXIT_RESOURCE,
    error_verdict,
    verdict_from_result,
)

# Idle heartbeat/export cadence.  The supervisor's stall detector only
# needs the heartbeat file to change within --stall-timeout (default
# 120s); ticking every poll interval (0.2s) would append ~432k lines/day
# to an IDLE serve-forever daemon for no extra liveness.
_IDLE_TICK_S = 5.0
# While a group is EXECUTING the main thread is inside the engine for
# arbitrarily long (a cold first job of a big shape is minutes of model
# build + compile), so a background thread keeps the heartbeat moving —
# otherwise --supervised would stall-kill a merely-busy daemon mid-job,
# requeue the claim, and kill the identical cold re-run forever.
_BUSY_HEARTBEAT_S = 5.0
# Rotation bound for heartbeat.jsonl: a serve-forever daemon must not
# grow it without limit.  Shrinking is safe — the stall detector treats
# ANY size change as progress (supervisor._run_attempt).
_HEARTBEAT_MAX_BYTES = 2 << 20
_HEARTBEAT_KEEP_LINES = 500


@dataclass
class ServeConfig:
    service_dir: str
    poll_s: float = 0.2
    linger_s: float = 0.05  # second claim sweep so a burst coalesces
    max_jobs: Optional[int] = None  # exit after N verdicts (bench/tests)
    idle_exit_s: Optional[float] = None  # exit after this long idle
    min_bucket: int = 256
    chunk_size: int = 32768
    visited_backend: str = "device"
    cache_entries: int = 32
    batching: bool = True
    # group-width cap (scheduler.plan_groups max_group=): a sweep can
    # legitimately queue hundreds of same-shape jobs in one drain, and
    # the batch runner holds the whole envelope exploration in RAM —
    # cap how many coalesce per engine run.  None/0 = unlimited (the
    # historical behavior); KSPEC_MAX_GROUP is the env twin.
    max_group: Optional[int] = None
    # fleet identity (service/fleet.py): instance i writes its OWN
    # heartbeat/metrics files (heartbeat-<i>.jsonl) so the fleet
    # supervisor can watch each daemon separately, answers to the
    # drain marker service/drain/<i>, and is the target of
    # crash@daemon<i>/stall@daemon<i> faults.  None (a solo `cli
    # serve`) keeps the historical shared paths.  KSPEC_DAEMON_INSTANCE
    # is the env twin the fleet launcher sets.
    instance: Optional[int] = None
    # persistent state-space cache (service/state_cache.py): repeat
    # checks of an unchanged config become chain-verified cache hits,
    # config-delta checks seed from the cached boundary.  Trust-but-
    # verify: any artifact problem degrades to a cold run with a
    # cache-fallback event — it can never produce a wrong verdict.
    state_cache: bool = True
    # cache FEDERATION (docs/service.md): the cache root defaults to
    # <svc>/state-cache, but pointing N hosts' daemons at ONE shared
    # directory (--state-cache-dir / $KSPEC_STATE_CACHE_DIR) gives them a
    # federated namespace — entries are content-addressed and re-proven
    # on every read, so host B serves host A's publishes chain-verified
    # with no coordination beyond the filesystem
    state_cache_dir: Optional[str] = None


class Daemon:
    def __init__(self, cfg: ServeConfig):
        self.cfg = cfg
        self.queue = JobQueue(cfg.service_dir)
        self.policy = TenantPolicy(self.queue.tenants_path)
        self.cache = KernelCache(max_entries=cfg.cache_entries)
        os.makedirs(self.queue.service_dir, exist_ok=True)
        # fleet identity: instance i gets its own heartbeat/metrics files
        # (the fleet supervisor watches per-daemon liveness), a drain
        # marker path, and the daemon-scoped fault sites armed
        if cfg.instance is None and os.environ.get("KSPEC_DAEMON_INSTANCE"):
            cfg.instance = int(os.environ["KSPEC_DAEMON_INSTANCE"])
        self.instance = cfg.instance
        sfx = "" if self.instance is None else f"-{self.instance}"
        self.heartbeat_path = os.path.join(
            self.queue.service_dir, f"heartbeat{sfx}.jsonl"
        )
        self.metrics_suffix = sfx
        self.events_path = os.path.join(
            self.queue.service_dir, "events.jsonl"
        )
        self.drain_marker = (
            None
            if self.instance is None
            else os.path.join(
                self.queue.service_dir, "drain", str(self.instance)
            )
        )
        # daemon-level fault plan (crash@daemon<i>:N / stall@daemon<i> /
        # flip@cache:N / enospc@cache:N): parsed once from the daemon's
        # OWN environment — per-job --fault plans ride the job governor
        # and never reach these hooks
        self.fault = FaultPlan.from_env()
        self.fault.set_instance(self.instance if self.instance is not None
                                else 0)
        # host identity (service/router.py): each host of a routed fleet
        # exports KSPEC_HOST_INSTANCE=<i> to its daemons, arming the
        # host-scoped chaos faults (kill@host<i> / partition@host<i> /
        # skew@host<i>) for exactly that host's processes
        if os.environ.get("KSPEC_HOST_INSTANCE"):
            try:
                self.fault.set_host(int(os.environ["KSPEC_HOST_INSTANCE"]))
            except ValueError:
                pass
        self.state_cache = None
        if cfg.state_cache:
            from .state_cache import StateSpaceCache

            self.state_cache = StateSpaceCache(
                cfg.state_cache_dir
                or os.environ.get("KSPEC_STATE_CACHE_DIR")
                or os.path.join(self.queue.dir, "state-cache"),
                fault_plan=self.fault,
                event=self._event,
            )
        # partition@host<i> window state: while _partition_left > 0 the
        # next jobs' cache lookups degrade to typed cold runs and their
        # publishes are deferred here, re-published when the window
        # closes (the heal) — the shared namespace was LOST, not the
        # daemon, so the work it completed meanwhile still federates
        self._partition_left = 0
        self._partition_ids: set = set()
        self._partition_deferred: list = []
        self._seeds: dict = {}  # job_id -> engine seed dict (cache delta)
        self._trace_buf: list = []  # solo runs' trace store (publication)
        self._janitor_last = 0.0
        # metrics identity: the run_id distinguishes daemon INSTANCES and
        # the const labels carry instance + host, so N fleet daemons'
        # scraped series (which share one metric namespace) never collide
        # on a bare run_id="service"
        labels = {}
        if self.instance is not None:
            labels["instance"] = str(self.instance)
        if os.environ.get("KSPEC_HOST_INSTANCE"):
            labels["host"] = os.environ["KSPEC_HOST_INSTANCE"]
        self.metrics = MetricsRegistry(
            run_id="service" + self.metrics_suffix, const_labels=labels
        )
        self.jobs_done = 0
        self.groups_run = 0
        self._stop = False
        self._last_work = _clk.monotonic()
        self._last_tick = 0.0
        # busy-heartbeat plumbing: the job ids of the group the main
        # thread is currently executing (None = idle), and the event that
        # shuts the heartbeat thread down with the daemon
        self._busy_jobs: Optional[list] = None
        # every claim of the current drain sweep that has not finished
        # yet: lease renewal must cover claims QUEUED BEHIND the active
        # group too (a sweep of several cold groups runs for many
        # minutes, and a sibling janitor must not read the later groups'
        # original-claim-time leases as expired and steal live work)
        self._sweep_jobs: list = []
        self._hb_stop = threading.Event()
        # both the main thread (_tick) and the busy-heartbeat thread write
        # heartbeat.jsonl and may rotate it; unserialized, two rotations
        # would interleave writes to the same .tmp and drop appends that
        # land between a rotation's read and its publish
        self._hb_lock = threading.Lock()

    # --- lifecycle --------------------------------------------------------
    def request_stop(self, *_a) -> None:
        self._stop = True

    def serve(self) -> int:
        """Run until stop/idle-exit/max-jobs; returns a process exit code."""
        old_term = signal.signal(signal.SIGTERM, self.request_stop)
        old_int = signal.signal(signal.SIGINT, self.request_stop)
        orphans = self.queue.requeue_orphans()
        self._event("daemon-start", pid=os.getpid(), requeued=len(orphans))
        print(
            f"[serve] daemon up: dir={self.queue.dir} pid={os.getpid()}"
            + (f" (requeued {len(orphans)} orphaned claims)" if orphans
               else ""),
            file=sys.stderr,
        )
        hb_thread = threading.Thread(
            target=self._busy_heartbeat_loop, daemon=True
        )
        hb_thread.start()
        try:
            while not self._stop:
                if self._drain_requested():
                    # graceful drain (fleet scale-down): every claimed
                    # job of the previous sweep is finished — take no new
                    # work, exit 0; the fleet reaps the slot
                    self._event("daemon-drain-exit", jobs=self.jobs_done)
                    break
                self._periodic_janitor()
                n = self.drain_once()
                self._tick(worked=bool(n))
                if n:
                    self._last_work = _clk.monotonic()
                else:
                    if self.cfg.idle_exit_s is not None and (
                        _clk.monotonic() - self._last_work
                        > self.cfg.idle_exit_s
                    ):
                        self._event("daemon-idle-exit")
                        break
                    _clk.sleep(self.cfg.poll_s)
                if (
                    self.cfg.max_jobs is not None
                    and self.jobs_done >= self.cfg.max_jobs
                ):
                    self._event("daemon-max-jobs", jobs=self.jobs_done)
                    break
        finally:
            self._hb_stop.set()
            hb_thread.join(timeout=2.0)
            self._event("daemon-stop", jobs=self.jobs_done)
            self._export_metrics(jsonl=True)
            signal.signal(signal.SIGTERM, old_term)
            signal.signal(signal.SIGINT, old_int)
        return 0

    # --- one queue sweep --------------------------------------------------
    def drain_once(self) -> int:
        """Claim everything pending (plus one linger sweep), run it
        grouped.  Returns the number of verdicts written."""
        claimed = self.queue.claim_pending()
        if claimed and self.cfg.linger_s:
            _clk.sleep(self.cfg.linger_s)  # let an in-flight burst land
            claimed += self.queue.claim_pending()
        # stall@daemon<i> wedges HERE — after the claim sweep, before any
        # lease renewal starts — so the injected failure is exactly the
        # one the fleet exists to survive: a wedged daemon sitting on
        # freshly leased claims (never returns when armed)
        self._maybe_wedge()
        if not claimed:
            return 0
        jobs = []
        done = 0  # verdicts written this sweep — short-circuits and parse
        # failures count too, or a stream of bad specs reads as "idle" to
        # the idle-exit timer while the daemon is actively publishing
        for spec in claimed:
            prior = self.queue.result(spec["job_id"])
            if prior is not None:
                # requeued orphan that already published its verdict:
                # retire the claim, never re-run (at-most-once
                # visibility).  Routed through _finish_job so the
                # published verdict counts toward --max-jobs, the
                # jobs_done gauge and kspec_svc_jobs_total like any
                # other — a controlled drain (serve --max-jobs N) must
                # terminate on it, not serve forever past it
                try:
                    self._finish_job(spec, prior)
                    done += 1
                except Exception:  # noqa: BLE001 — verdict already durable
                    pass
                continue
            try:
                cfg = job_cfg(spec)
                emitted = resolve_kernel_source(
                    spec.get("kernel_source", "auto"), spec["module"]
                )
                if self._consult_state_cache(spec, cfg, emitted):
                    done += 1  # chain-verified cache hit: verdict
                    continue  # published, nothing to run
                jobs.append((spec, cfg, emitted))
            except Exception as e:  # noqa: BLE001 — tenant input
                done += self._fail_jobs([spec], f"cannot parse job cfg: {e}")
        max_group = self.cfg.max_group
        if max_group is None and os.environ.get("KSPEC_MAX_GROUP"):
            try:
                max_group = int(os.environ["KSPEC_MAX_GROUP"])
            except ValueError:
                max_group = None
        t_plan = fleettrace.now()
        groups = (
            plan_groups(jobs, max_group=max_group)
            if self.cfg.batching
            else [[j] for j in jobs]
        )
        for group in groups:
            for spec, _c, _e in group:
                fleettrace.emit_span(
                    self.queue.dir, spec.get("trace"), "sched-group",
                    t_plan, fleettrace.now(), job_id=spec["job_id"],
                    group_size=len(group),
                    leader=group[0][0]["job_id"],
                    instance=self.instance,
                )
        self._sweep_jobs = [
            spec["job_id"] for group in groups for spec, _c, _e in group
        ]
        try:
            for group in groups:
                try:
                    done += self._run_group(group)
                finally:
                    # every exit path — normal, error-verdict returns, or
                    # an unexpected escape — must close the busy-heartbeat
                    # window
                    self._busy_jobs = None
                if self._stop:
                    break
        finally:
            self._sweep_jobs = []
        return done

    # --- group execution --------------------------------------------------
    def _run_group(self, group: list) -> int:
        specs = [spec for spec, _c, _e in group]
        leader_spec, leader_cfg, emitted = group[0]
        tenant = leader_spec.get("tenant", "default")
        # crash@daemon<i>:N (resilience.faults): the injected daemon
        # death fires BEFORE any verdict work for the Nth job, so the
        # group's claims stay leased and a sibling's janitor requeues
        # them — the exactly-once-visible-verdict drill for the fleet.
        # InjectedCrash is deliberately NOT caught by any handler below:
        # the process must die like the real crash it rehearses.  The
        # fired-marker makes the drill once-per-service-dir, so the
        # fleet's restarted daemon converges instead of crash-looping.
        if self._daemon_fault_armed("crash"):
            try:
                self.fault.daemon_crash(
                    self.jobs_done + 1, self.jobs_done + len(group)
                )
            except InjectedCrash:
                self._mark_daemon_fault("crash")
                raise
        # kill@host<i>:N — the whole-host-death drill (service/router.py):
        # same firing point and exactly-once story as crash@daemon, but
        # scoped by KSPEC_HOST_INSTANCE so one composed plan string can
        # target one host of a routed fleet.  The router sees the host's
        # heartbeats go stale and re-routes its pending jobs; the leased
        # claims come back through the takeover protocol.
        if self._daemon_fault_armed("kill"):
            try:
                self.fault.host_kill(
                    self.jobs_done + 1, self.jobs_done + len(group)
                )
            except InjectedCrash:
                self._mark_daemon_fault("kill")
                raise
        # the busy-heartbeat window opens BEFORE the kernel-cache lookup:
        # a cold miss runs build_model + prepare for minutes, and without
        # a moving heartbeat --supervised would stall-kill the daemon
        # mid-build, requeue the claim, and kill the identical re-build
        # forever (drain_once clears this on every exit path)
        self._busy_jobs = [s["job_id"] for s in specs]
        # EVERY singleton group takes the real solo engine path — first-
        # violation early exit, streamed levels (no collect_levels RAM),
        # full check_deadlock semantics — still warm through the kernel
        # cache; only groups of >= 2 pay the shared-exploration envelope.
        # (solo_only additionally keeps deadlock/fault jobs out of groups
        # at planning time — the post-hoc derivation cannot replay them.)
        # This also makes --no-batching exactly what its help says: every
        # group is a singleton, so every job runs real solo semantics.
        solo = len(group) == 1
        t0 = time.perf_counter()
        try:
            invs = (
                job_invariants(leader_spec["module"], leader_cfg)
                if solo else union_invariants(group)
            )
            members = [
                Member(
                    spec["job_id"],
                    job_invariants(spec["module"], cfg),
                    max_depth=spec.get("max_depth"),
                    max_states=spec.get("max_states"),
                )
                for spec, cfg, _e in group
            ]
            entry = self.cache.get(
                leader_spec["module"], leader_cfg, emitted, invs
            )
        except Exception as e:  # noqa: BLE001 — bad module/constants
            return self._fail_jobs(specs, f"cannot build model: {e}")
        self._cache_metrics(entry)
        fault = leader_spec.get("fault")
        leader_ctx = None
        try:
            # durable=False: a service run dir is pure observability — the
            # queue's verdict file is the job's durable record, and the
            # manifest fsyncs were the warm path's latency floor (~5/job)
            leader_ctx = RunContext(
                self.queue.run_dir(leader_spec["job_id"]), durable=False
            )
            leader_ctx.record_config(
                module=leader_spec["module"],
                engine="service",
                service={
                    "job_id": leader_spec["job_id"],
                    "tenant": tenant,
                    "group_size": len(group),
                    "group_jobs": [s["job_id"] for s in specs],
                    "cache_hit": entry["hit"],
                    **(
                        {"takeover": leader_spec["takeovers"][-1]}
                        if leader_spec.get("takeovers")
                        else {}
                    ),
                    **(
                        {"state_cache_seed": True}
                        if leader_spec.get("_state_cache_seed")
                        else {}
                    ),
                },
            )
            # a tenant-budgeted governor replaces the engine's env-derived
            # one wholesale, so the job's fault plan must ride in it —
            # otherwise governor-level faults (stall@level) silently no-op
            # for every budgeted tenant while working for unbudgeted ones
            governor = self.policy.governor(
                tenant,
                watch_dirs=[leader_ctx.dir],
                fault_plan=FaultPlan(fault) if fault else None,
            )
        except Exception as e:  # noqa: BLE001 — a malformed fault plan /
            # unwritable run dir is THAT job's problem, not the daemon's:
            # crashing here would strand the group in claimed/ and hot-loop
            # the janitor-requeue -> identical-crash cycle
            if leader_ctx is not None:
                self._close_run(leader_ctx, "error", str(e))
            return self._fail_jobs(specs, f"cannot start job: {e}")
        old_fault = os.environ.get("KSPEC_FAULT")
        if fault:
            os.environ["KSPEC_FAULT"] = fault
        seed = None
        seed_depth = None
        try:
            if solo:
                shared = None
                seed = self._seeds.pop(leader_spec["job_id"], None)

                def _run_solo(seed_arg):
                    # publication needs the per-level packed rows: alias
                    # the engine's trace store (zero extra memory) on
                    # COLD cacheable runs; seeded runs force
                    # store_trace off, so they neither collect nor
                    # publish (docs/service.md § State-space cache)
                    collect = (
                        self._trace_buf
                        if seed_arg is None
                        and self.state_cache is not None
                        and not fault
                        else None
                    )
                    return check(
                        entry["model"],
                        max_depth=leader_spec.get("max_depth"),
                        max_states=leader_spec.get("max_states"),
                        store_trace=True,
                        min_bucket=self.cfg.min_bucket,
                        check_deadlock=leader_cfg.check_deadlock,
                        chunk_size=self.cfg.chunk_size,
                        visited_backend=self.cfg.visited_backend,
                        prepared=entry["prepared"],
                        run=leader_ctx,
                        governor=governor,
                        visited_capacity_exact=(
                            entry["prepared"].capacity_hint
                        ),
                        seed=seed_arg,
                        collect_trace=collect,
                    )

                try:
                    solo_res = _run_solo(seed)
                    seed_depth = seed["depth"] if seed else None
                except InjectedCrash:
                    raise  # the process is expected to die
                except Exception as e:  # noqa: BLE001 — trust-but-verify:
                    # a seeded run that fails for ANY reason degrades to
                    # the cold run it replaced (typed cache-fallback);
                    # only an unseeded failure is the job's real error
                    if seed is None:
                        raise
                    self._event(
                        "cache-fallback",
                        reason=f"seed-error: {str(e)[:200]}",
                        jobs=[leader_spec["job_id"]],
                    )
                    self.metrics.inc("kspec_svc_state_cache_fallbacks_total")
                    seed = None
                    solo_res = _run_solo(None)
                entry["prepared"].note_result(solo_res)
            else:
                shared = explore_shared(
                    entry["model"],
                    members,
                    prepared=entry["prepared"],
                    min_bucket=self.cfg.min_bucket,
                    chunk_size=self.cfg.chunk_size,
                    visited_backend=self.cfg.visited_backend,
                    run=leader_ctx,
                    governor=governor,
                )
        except ResourceExhausted as e:
            # the engine's typed path already stamped the manifest
            # 'resource-exhausted' and closed its observer; the deactivate
            # here is a no-op belt for partial paths
            self._close_run(leader_ctx, None)
            self._event(
                "job-resource-exhausted", tenant=tenant, reason=e.reason,
                jobs=[s["job_id"] for s in specs],
            )
            n = 0
            for spec in specs:
                try:
                    self._finish_job(
                        spec,
                        self._stamp(
                            spec,
                            error_verdict(
                                f"RESOURCE_EXHAUSTED[{e.reason}]: "
                                f"{e.detail}",
                                run_id=leader_ctx.run_id,
                                exit_code=EXIT_RESOURCE,
                            ),
                            status="resource-exhausted",
                        ),
                    )
                    n += 1
                except Exception:  # noqa: BLE001 — a second ENOSPC must
                    pass  # not crash the daemon; the claim stays for the
                    # next janitor
            return n
        except IntegrityError as e:
            # typed like the resource path: the engine stamped the run
            # manifest 'integrity-violation' and closed its observer;
            # each member job gets an rc-76 verdict and the daemon (and
            # its sibling jobs) keeps serving — one tenant's corrupted
            # run never takes the service down
            self._close_run(leader_ctx, None)
            self._event(
                "job-integrity-violation", tenant=tenant, site=e.site,
                jobs=[s["job_id"] for s in specs],
            )
            n = 0
            for spec in specs:
                try:
                    self._finish_job(
                        spec,
                        self._stamp(
                            spec,
                            error_verdict(
                                f"INTEGRITY_VIOLATION[{e.site}]: "
                                f"{e.detail}",
                                run_id=leader_ctx.run_id,
                                exit_code=EXIT_INTEGRITY,
                            ),
                            status="integrity-violation",
                        ),
                    )
                    n += 1
                except Exception:  # noqa: BLE001 — same belt as rc-75
                    pass
            return n
        except Exception as e:  # noqa: BLE001 — keep the daemon alive
            # the engine does NOT close its observer on a generic raise:
            # stamp + release here or every such failure leaks a tracer fd
            self._close_run(leader_ctx, "error", str(e))
            self._event(
                "job-error", tenant=tenant, error=str(e)[:300],
                jobs=[s["job_id"] for s in specs],
            )
            return self._fail_jobs(specs, f"engine failure: {e}")
        finally:
            if fault:
                if old_fault is None:
                    os.environ.pop("KSPEC_FAULT", None)
                else:
                    os.environ["KSPEC_FAULT"] = old_fault
        n = self._publish_group(
            group, members, specs, leader_spec, leader_ctx,
            solo, solo_res if solo else None, shared, t0,
            seed_depth=seed_depth, cache_entry=entry,
        )
        if solo and self.state_cache is not None and not fault:
            # completed solo run: publish it as a state-space-cache entry
            # (files-first + atomic entry promote; every failure is a
            # cache-fallback event, never a job failure).  Cold runs
            # publish the full seedable artifact from their trace rows;
            # seeded runs publish a verdict-only entry (their trace
            # store has no below-seed levels), which still turns the
            # NEXT repeat check into an O(verify) hit
            rows = (
                [t[0] for t in self._trace_buf]
                if seed is None and solo_res.violation is None
                else None
            )
            self._publish_state_cache(
                leader_spec, leader_cfg, emitted, entry, solo_res,
                level_rows=rows,
            )
        # a run that GREW the device visited set evicted the small-bucket
        # steps the next run of this shape will need at the new capacity
        # fixed point: re-compile them now — verdicts are already
        # published, the busy-heartbeat window is still open, and no job
        # is waiting on this — so the SECOND job of the shape shows zero
        # compile spans even when the first had to grow
        try:
            warmed = entry["prepared"].rewarm()
            if warmed:
                self.metrics.inc("kspec_svc_rewarmed_steps_total", warmed)
        except Exception as e:  # noqa: BLE001 — purely an optimization
            self._event("rewarm-error", error=str(e)[:300])
        return n

    def _publish_group(self, group, members, specs, leader_spec,
                       leader_ctx, solo, solo_res, shared, t0,
                       seed_depth=None, cache_entry=None) -> int:
        """Derive + publish every member's verdict.  Runs with
        ``_busy_jobs`` still set (cleared by drain_once): derive_member
        jit-compiles per-(invariant, level-bucket) predicates and walks
        traces on the host, which on a cold big shape can outlast
        ``--supervised``'s stall timeout — ending the busy-heartbeat
        window at the engine's return would let the supervisor stall-kill
        a merely-busy daemon mid-derive and requeue the group into an
        identical kill loop."""
        wall_s = time.perf_counter() - t0
        self.groups_run += 1
        self.metrics.inc("kspec_svc_groups_total")
        if len(group) > 1:
            self.metrics.inc("kspec_svc_batched_jobs_total", len(group))
        # fleet-trace run window + stage histograms: the wall window is
        # reconstructed backward from the run's end so the span's clock
        # and the engine's perf_counter duration agree
        t_run_end = fleettrace.now()
        cache_hit = bool(cache_entry.get("hit")) if cache_entry else None
        compile_ms = (
            0.0 if cache_entry is None or cache_hit
            else round(float(cache_entry.get("build_s") or 0.0) * 1e3, 1)
        )
        if compile_ms:
            self.metrics.observe("kspec_svc_stage_compile_ms", compile_ms)
        self.metrics.observe(
            "kspec_svc_stage_explore_ms",
            max(0.0, wall_s * 1e3 - compile_ms),
        )
        for (spec, mcfg, memitted), member in zip(group, members):
            # per-member guard: a derivation/publication failure (a
            # predicate erroring on a decoded state, an OSError on a
            # member run dir) must cost THAT member an error verdict, not
            # crash the daemon with the whole group stuck in claimed/ —
            # the janitor would requeue it into an identical re-crash
            try:
                res = solo_res if solo else derive_member(shared, member)
                rec = self._stamp(
                    spec,
                    verdict_from_result(res, run_id=leader_ctx.run_id),
                    status="violation" if res.violation else "complete",
                    wall_s=wall_s,
                )
                if seed_depth is not None:
                    # config-delta run: the frontier was seeded from the
                    # cached boundary instead of Init (state_cache)
                    rec["cache"] = {
                        "state_cache": "seed",
                        "from_depth": int(seed_depth),
                    }
                if len(group) > 1:
                    rec["batch"] = {
                        "group_size": len(group),
                        "leader_run_id": leader_ctx.run_id,
                    }
                if spec is leader_spec:
                    # the engine's RunObserver already finished the
                    # manifest with the SHARED result; overwrite the
                    # summary with the member's own derived verdict +
                    # service metadata
                    leader_ctx.finish(rec["status"], **_summary(rec))
                else:
                    ctx = RunContext(
                        self.queue.run_dir(spec["job_id"]), durable=False
                    )
                    ctx.record_config(
                        module=spec["module"],
                        engine="service",
                        service={
                            "job_id": spec["job_id"],
                            "tenant": spec.get("tenant", "default"),
                            "group_size": len(group),
                            "leader_run_id": leader_ctx.run_id,
                            "cache_hit": True,  # rode the leader's kernels
                        },
                    )
                    rec["run_id"] = ctx.run_id
                    ctx.finish(rec["status"], **_summary(rec))
                self._finish_job(spec, rec)
                fleettrace.emit_span(
                    self.queue.dir, spec.get("trace"), "svc-run",
                    t_run_end - wall_s, t_run_end,
                    job_id=spec["job_id"],
                    run_id=rec.get("run_id") or leader_ctx.run_id,
                    group_size=len(group), solo=bool(solo),
                    cache_hit=cache_hit, compile_ms=compile_ms,
                    verdict=rec["status"], seed_depth=seed_depth,
                    instance=self.instance,
                )
                if not solo and self.state_cache is not None:
                    # batched members publish VERDICT-ONLY entries (their
                    # per-level rows live only in the shared record, so
                    # there is no seedable artifact) — a repeat sweep of
                    # the same lattice then O(verify)-hits every member
                    # instead of re-running the whole group.  Publication
                    # failure is a typed cache-fallback, never the job's.
                    self._publish_state_cache(
                        spec, mcfg, memitted,
                        {"model": shared.model}, res,
                        level_rows=None,
                    )
            except Exception as e:  # noqa: BLE001 — keep the daemon alive
                self._event(
                    "job-error", tenant=spec.get("tenant", "default"),
                    error=str(e)[:300], jobs=[spec["job_id"]],
                )
                try:
                    self._fail_job(spec, f"verdict derivation failed: {e}")
                except Exception:  # noqa: BLE001 — even the error verdict
                    # failed (service dir unwritable): leave the job
                    # claimed for the next daemon's janitor
                    pass
        return len(specs)

    # --- state-space cache (service/state_cache.py) -----------------------
    def _consult_state_cache(self, spec: dict, cfg, emitted: bool) -> bool:
        """Repeat-check short circuit: True when a chain-verified cache
        hit published this job's verdict (nothing to run).  A config-
        delta hit registers an engine seed for the solo path and returns
        False (the job still runs, just not from Init).  Every cache
        problem is a typed cache-fallback (inside lookup) + False."""
        if self.state_cache is None or spec.get("fault"):
            return False
        t_lk = fleettrace.now()

        def _trace_lookup(outcome: str, **attrs) -> None:
            # verify stage = the chain-verify/lookup window of the shared
            # state cache, whatever the outcome
            t1 = fleettrace.now()
            self.metrics.observe(
                "kspec_svc_stage_verify_ms", max(0.0, (t1 - t_lk) * 1e3)
            )
            fleettrace.emit_span(
                self.queue.dir, spec.get("trace"), "cache-lookup",
                t_lk, t1, job_id=spec["job_id"], outcome=outcome,
                instance=self.instance, **attrs,
            )

        if self._partition_check(spec):
            # partition@host<i>: the shared cache namespace is GONE for
            # this window — degrade to a local-cold run with the typed
            # fallback every other cache problem gets; the publish side
            # defers and re-publishes on heal
            self._event(
                "cache-fallback", reason="partition",
                jobs=[spec["job_id"]],
            )
            self.metrics.inc("kspec_svc_state_cache_fallbacks_total")
            _trace_lookup("fallback", reason="partition")
            return False
        from .state_cache import CacheHit, CacheSeed, key_for_job
        from .verdict import VERDICT_SCHEMA

        try:
            key = key_for_job(
                spec, cfg, emitted,
                job_invariants(spec["module"], cfg),
            )
            found = self.state_cache.lookup(key)
        except Exception as e:  # noqa: BLE001 — the cache may never fail
            # a job: an unexpected lookup error is just a cold run
            self._event(
                "cache-fallback", reason=f"lookup-error: {str(e)[:200]}",
                jobs=[spec["job_id"]],
            )
            self.metrics.inc("kspec_svc_state_cache_fallbacks_total")
            _trace_lookup("fallback", reason="lookup-error")
            return False
        if isinstance(found, CacheHit):
            rec = dict(found.verdict)
            rec["schema"] = VERDICT_SCHEMA
            rec.setdefault("run_id", None)
            rec = self._stamp(
                spec, rec,
                status="violation" if rec.get("violation") else "complete",
            )
            rec["cache"] = {
                "state_cache": "hit",
                "reason": found.reason,
                "published_unix": found.entry.get("created_unix"),
            }
            self._finish_job(spec, rec)
            self.metrics.inc("kspec_svc_state_cache_hits_total")
            _trace_lookup("hit", reason=found.reason)
            return True
        if isinstance(found, CacheSeed):
            self._seeds[spec["job_id"]] = found.seed
            # seeded jobs must run REAL solo semantics (the engine seed
            # plugs into check(), not the batched runner)
            spec["_state_cache_seed"] = True
            self.metrics.inc("kspec_svc_state_cache_seeds_total")
            _trace_lookup("seed", from_depth=int(found.from_depth))
            return False
        self.metrics.inc("kspec_svc_state_cache_misses_total")
        _trace_lookup("miss")
        return False

    def _partition_check(self, spec: dict) -> bool:
        """True while this job's cache consultation falls inside an
        injected partition window (partition@host<i>[:N], armed lazily
        on the first consultation after the fault matches; durable
        fired-marker, so a restarted daemon converges).  The window
        counts PUBLISHING jobs: each one registers here, defers its
        publish, and the last one's deferral triggers the heal."""
        if self._partition_left == 0 and self._daemon_fault_armed(
            "partition"
        ):
            n = self.fault.host_partition()
            if n:
                self._mark_daemon_fault("partition")
                self._partition_left = n
                self._event("cache-partition-injected", jobs_degraded=n)
        if self._partition_left <= 0:
            return False
        self._partition_ids.add(spec["job_id"])
        return True

    def _heal_partition(self) -> None:
        """The partition window closed: the shared namespace is back, so
        everything completed meanwhile re-publishes — the federation
        sees the host's partitioned work as if it had never dropped off."""
        deferred, self._partition_deferred = self._partition_deferred, []
        for args in deferred:
            self._publish_state_cache(*args)
        self._event("cache-partition-heal", republished=len(deferred))

    def _publish_state_cache(self, spec, cfg, emitted, entry, res,
                             level_rows=None) -> None:
        from .state_cache import key_for_job

        jid = spec.get("job_id")
        if jid in self._partition_ids:
            # mid-partition: the namespace is unreachable — defer, and
            # re-publish when the window closes (never publish into a
            # namespace the fault says we cannot see)
            self._partition_ids.discard(jid)
            self._partition_deferred.append(
                (spec, cfg, emitted, entry, res, level_rows)
            )
            self._partition_left = max(0, self._partition_left - 1)
            self._event(
                "cache-publish-deferred", reason="partition", jobs=[jid],
            )
            if self._partition_left == 0:
                self._heal_partition()
            return
        t_pub = fleettrace.now()
        published = False
        try:
            key = key_for_job(
                spec, cfg, emitted, job_invariants(spec["module"], cfg)
            )
            rows = level_rows
            if rows is not None:
                # an exhausted run's trace store carries one trailing
                # EMPTY level (the final zero-new iteration) beyond the
                # levels list — trim it; any other length mismatch
                # (violation early-exit) means no artifact
                rows = list(rows)
                while len(rows) > len(res.levels) and not len(rows[-1]):
                    rows.pop()
                if len(rows) != len(res.levels):
                    rows = None
            if self.state_cache.publish(
                key,
                verdict_from_result(res),
                exact64=bool(entry["model"].spec.exact64),
                lanes=int(entry["model"].spec.num_lanes),
                level_rows=rows,
                diameter=res.diameter,
            ):
                self.metrics.inc("kspec_svc_state_cache_publish_total")
                published = True
        except Exception as e:  # noqa: BLE001 — publication is an
            # optimization: its failure must never fail the job
            self._event(
                "cache-fallback", reason=f"publish-error: {str(e)[:200]}",
            )
            self.metrics.inc("kspec_svc_state_cache_fallbacks_total")
        fleettrace.emit_span(
            self.queue.dir, spec.get("trace"), "cache-publish",
            t_pub, fleettrace.now(), job_id=jid,
            published=published, verdict_only=level_rows is None,
            instance=self.instance,
        )

    # --- helpers ----------------------------------------------------------
    def _stamp(self, spec: dict, rec: dict, status: str,
               wall_s: Optional[float] = None) -> dict:
        now = _clk.now()
        rec["job_id"] = spec["job_id"]
        rec["tenant"] = spec.get("tenant", "default")
        rec["status"] = status
        if spec.get("takeovers"):
            # the job reached this daemon via a janitor takeover from a
            # dead/wedged claimer: attribute it in the verdict (and `cli
            # report` renders it from the run manifest's service block)
            last = dict(spec["takeovers"][-1])
            last["count"] = len(spec["takeovers"])
            rec["takeover"] = last
        sub = spec.get("submitted_unix")
        claim = spec.get("claimed_unix")
        rec["timing"] = {
            "submitted_unix": sub,
            "claimed_unix": claim,
            "done_unix": round(now, 3),
            "wait_s": round(claim - sub, 3) if sub and claim else None,
            "wall_s": round(wall_s, 3) if wall_s is not None else None,
            "latency_s": round(now - sub, 3) if sub else None,
        }
        if rec["timing"]["latency_s"] is not None:
            self.metrics.observe(
                "kspec_svc_latency_ms", rec["timing"]["latency_s"] * 1e3
            )
        if rec["timing"]["wait_s"] is not None:
            self.metrics.observe(
                "kspec_svc_stage_queue_wait_ms",
                max(0.0, rec["timing"]["wait_s"] * 1e3),
            )
        return rec

    def _finish_job(self, spec: dict, rec: dict) -> None:
        t_fin = fleettrace.now()
        self.queue.finish(spec["job_id"], rec)
        t_done = fleettrace.now()
        self.metrics.observe(
            "kspec_svc_stage_publish_ms", max(0.0, (t_done - t_fin) * 1e3)
        )
        fleettrace.emit_span(
            self.queue.dir, spec.get("trace"), "verdict-publish",
            t_fin, t_done, job_id=spec["job_id"],
            status=rec.get("status", "?"),
            cache=(rec.get("cache") or {}).get("state_cache"),
            instance=self.instance,
        )
        try:  # finished jobs leave the lease-renewal set immediately
            self._sweep_jobs.remove(spec["job_id"])
        except ValueError:
            pass
        self.jobs_done += 1
        self.metrics.inc("kspec_svc_jobs_total", status=rec.get("status", "?"))

    def _fail_job(self, spec: dict, message: str) -> None:
        self._finish_job(
            spec, self._stamp(spec, error_verdict(message), status="error")
        )

    def _fail_jobs(self, specs: list, message: str) -> int:
        """Best-effort error verdicts; returns how many were written.  A
        failure writing even the ERROR verdict (ENOSPC on the service
        dir) must not crash the daemon into the janitor-requeue crash
        loop — the job stays claimed for the next daemon's janitor."""
        n = 0
        for spec in specs:
            try:
                self._fail_job(spec, message)
                n += 1
            except Exception:  # noqa: BLE001
                pass
        return n

    @staticmethod
    def _close_run(ctx, status: Optional[str], error: Optional[str] = None):
        """Best-effort terminal cleanup for a run dir whose engine died
        outside the engine's own terminal paths (the engine finishes the
        manifest and closes the tracer fd only on clean/typed exits): a
        tenant repeatedly crashing the engine must not leak one tracer fd
        per failure (EMFILE eventually takes every tenant down), and the
        run index must not report the dir as 'running' forever under the
        daemon's live pid.  status=None skips the manifest stamp (the
        engine already wrote its own terminal status, e.g.
        'resource-exhausted')."""
        try:
            if status is not None:
                ctx.finish(status, **({"error": error[:300]} if error
                                      else {}))
        except Exception:  # noqa: BLE001
            pass
        try:
            ctx.deactivate()  # idempotent: closed fd / cleared tracer ok
        except Exception:  # noqa: BLE001
            pass

    def _cache_metrics(self, entry: dict) -> None:
        if entry["hit"]:
            self.metrics.inc("kspec_svc_cache_hits_total")
        else:
            self.metrics.inc("kspec_svc_cache_misses_total")
            self.metrics.observe(
                "kspec_svc_model_build_ms", entry["build_s"] * 1e3
            )

    def _event(self, kind: str, **fields) -> None:
        if self.instance is not None:
            fields.setdefault("instance", self.instance)
        try:
            append_jsonl(
                self.events_path,
                heartbeat_record("service", event=kind, **fields),
            )
        except OSError:
            pass  # telemetry on a full disk must never take the daemon down

    def _drain_requested(self) -> bool:
        """True once the fleet has marked this instance for graceful
        retirement (service/drain/<i>): finish what is claimed, take no
        new jobs, exit 0."""
        return self.drain_marker is not None and os.path.exists(
            self.drain_marker
        )

    def _periodic_janitor(self) -> None:
        """requeue_orphans is not only a STARTUP janitor: a live daemon
        sweeping it periodically is what lets a healthy sibling take
        over a wedged daemon's claims at lease expiry without anyone
        restarting anything (the fleet's takeover primitive).  Cadence
        tracks the lease TTL so a short-TTL test observes takeover in
        seconds while a production daemon sweeps at most every 30s."""
        import time as _t

        ttl = float(os.environ.get("KSPEC_CLAIM_LEASE_TTL", 900.0))
        interval = min(30.0, max(0.5, ttl / 3.0))
        now = _t.monotonic()
        if now - self._janitor_last < interval:
            return
        self._janitor_last = now
        try:
            moved = self.queue.requeue_orphans()
        except OSError:
            return
        if moved:
            self._event("lease-takeover", jobs=sorted(moved))
            self.metrics.inc("kspec_svc_takeovers_total", len(moved))

    def _daemon_fault_marker(self, kind: str) -> str:
        return os.path.join(
            self.queue.service_dir, "faults-fired",
            f"{kind}-daemon{self.instance if self.instance is not None else 0}",
        )

    def _daemon_fault_armed(self, kind: str) -> bool:
        """Daemon-scoped faults fire ONCE PER SERVICE DIR, not once per
        process: a restarted daemon re-reads KSPEC_FAULT, and without
        this durable fired-marker a crash@daemon<i> drill would re-kill
        every restart into a crash loop.  Same convergence rule as
        crash@level's checkpoint deferral — a supervised restart must
        converge, never re-rehearse."""
        return not os.path.exists(self._daemon_fault_marker(kind))

    def _mark_daemon_fault(self, kind: str) -> None:
        try:
            path = self._daemon_fault_marker(kind)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w"):
                pass
        except OSError:
            pass  # worst case the drill re-fires; never block the fault

    def _maybe_wedge(self) -> None:
        """stall@daemon<i> (resilience.faults): deterministically wedge
        THIS daemon after a claim sweep — claims held, leases never
        renewed again, heartbeat frozen.  The fleet supervisor's stall
        detector kills the process; a sibling's janitor takes the claims
        over at lease expiry.  The sleep loop never returns."""
        if not self._daemon_fault_armed("stall"):
            return
        if not self.fault.daemon_stalled():
            return
        self._mark_daemon_fault("stall")
        self._event("daemon-wedge-injected", pid=os.getpid())
        while True:  # pragma: no cover — killed externally
            _clk.sleep(3600.0)

    def _tick(self, worked: bool = False) -> None:
        now = _clk.monotonic()
        if not worked and now - self._last_tick < _IDLE_TICK_S:
            return
        self._last_tick = now
        pending = self.queue.pending_count()
        self.metrics.set_gauge("kspec_svc_queue_pending", pending)
        self.metrics.set_gauge(
            "kspec_svc_queue_claimed", self.queue.claimed_count()
        )
        self.metrics.set_gauge("kspec_svc_jobs_done", self.jobs_done)
        self.metrics.set_gauge(
            "kspec_svc_cache_entries", len(self.cache)
        )
        cs = self.cache.stats()
        self.metrics.set_gauge("kspec_svc_cache_hit_rate", cs["hit_rate"])
        self._heartbeat(pending=pending, cache=cs)
        # metrics.jsonl is an append-only snapshot stream: writing it on
        # every idle tick would grow without bound on a serve-forever
        # daemon, so snapshots land only when work happened (plus the
        # terminal export); metrics.prom is an atomic replace of constant
        # size and stays fresh every tick
        self._export_metrics(jsonl=worked)

    def _heartbeat(self, **fields) -> None:
        with self._hb_lock:
            try:
                append_jsonl(
                    self.heartbeat_path,
                    heartbeat_record(
                        "service-heartbeat",
                        # skew@host<i>:SECS shifts the clock this host
                        # stamps into cross-host-visible metadata — the
                        # router's freshness check reads these `unix`
                        # fields, and its KSPEC_CLOCK_SKEW allowance is
                        # what this fault rehearses (0-shift otherwise)
                        t=_clk.now() + injected_skew_s(),
                        pid=os.getpid(),
                        jobs_done=self.jobs_done,
                        **fields,
                    ),
                )
            except OSError:
                pass  # liveness writes must never take the daemon down
            self._rotate_heartbeat()

    def _rotate_heartbeat(self) -> None:
        """Bound heartbeat.jsonl: keep the newest lines once it outgrows
        the cap (atomic replace; any size CHANGE reads as liveness to the
        supervisor's stall detector, shrink included)."""
        try:
            if os.path.getsize(self.heartbeat_path) <= _HEARTBEAT_MAX_BYTES:
                return
            with open(self.heartbeat_path) as fh:
                tail = fh.readlines()[-_HEARTBEAT_KEEP_LINES:]
            tmp = self.heartbeat_path + ".tmp"
            with open(tmp, "w") as fh:
                fh.writelines(tail)
            _dio.replace(tmp, self.heartbeat_path)
        except OSError:
            pass  # rotation must never take the daemon down

    def _busy_heartbeat_loop(self) -> None:
        """Background thread: keep the heartbeat moving while the main
        thread is inside a long engine run (model build + compile can be
        minutes), so --supervised never stall-kills a busy daemon — and
        renew the claim LEASES of the in-flight group for the same
        reason: a sibling daemon sharing this queue directory must read
        a long-running job as live, not orphaned (queue.requeue_orphans)."""
        while not self._hb_stop.wait(_BUSY_HEARTBEAT_S):
            jobs = self._busy_jobs
            if jobs is not None:
                self._heartbeat(busy=True, jobs=jobs)
            # renew every unfinished claim of the sweep, not just the
            # active group: claims queued behind a minutes-long cold
            # build must stay visibly live to sibling janitors (a lease
            # recreated in the instant after finish retires it is a
            # dangling sidecar the next janitor sweeps — harmless)
            sweep = list(self._sweep_jobs)
            if sweep:
                try:
                    self.queue.renew_leases(sweep)
                except Exception:  # noqa: BLE001 — advisory metadata only
                    pass

    def _export_metrics(self, jsonl: bool = False) -> None:
        svc = self.queue.service_dir
        sfx = self.metrics_suffix  # per-instance files in a fleet: two
        # daemons must not alternate-overwrite one prom textfile
        try:
            if jsonl:
                self.metrics.write_jsonl(
                    os.path.join(svc, f"metrics{sfx}.jsonl")
                )
            self.metrics.write_prom(os.path.join(svc, f"metrics{sfx}.prom"))
        except OSError:
            pass  # metrics export must never take the daemon down


def _summary(rec: dict) -> dict:
    """Manifest result summary from a verdict record."""
    out = {
        k: rec.get(k)
        for k in ("model", "distinct_states", "diameter", "seconds",
                  "states_per_sec", "exit_code")
    }
    if rec.get("violation"):
        out["violation"] = rec["violation"]
    if rec.get("error"):
        out["error"] = rec["error"]
    if rec.get("batch"):
        out["batch"] = rec["batch"]
    return out


def serve(cfg: ServeConfig) -> int:
    return Daemon(cfg).serve()
