"""Shape-keyed compile cache: the warm heart of the serving daemon.

A cold ``cli check`` pays ~2 minutes of jax import + reference parse +
model build + trace/XLA-compile for seconds of actual checking (TODO.md).
The daemon pays each of those exactly once per *schema shape* and then
serves every later job of that shape warm, following the compiler-first
portable-cache design of arXiv:2603.09555 (PAPERS.md): make compilation a
keyed artifact, look it up in O(1).

The key: in this corpus a model's tensor schema (ops/packing.StateSpec —
field names, shapes, bounds, lane packing) and its compiled step programs
are a pure function of ``(module, kernel source, constants)``; the
invariant selection adds/removes predicate kernels AND fixes the
first-violation order, so it keys too — ORDERED.  Two .cfg files with
the same semantic content — regardless of path, comments, or formatting
— therefore hit the same cache line.  One consequence: a schema shape
served both solo (cfg-order invariants) and batched (sorted-union
invariants) holds up to two cache lines when those orders differ —
first-violation semantics genuinely depend on the model's invariant
order, so the lines cannot be merged without a model/invariant-overlay
split (ROADMAP notes this as open); the LRU bounds the cost.  Engine knobs (bucket
floor, chunk size, visited backend) select among the per-model compiled
step variants and ride in the GROUP key (scheduler), not here: the
expensive artifact, the built Model with its jitted-step cache, is shared
across knob settings.

What a cache line holds: the built :class:`~..models.base.Model` plus its
:class:`~..engine.bfs.PreparedKernels`.  The Model object carries the
jitted-step cache (``_step_cache``), so a hit skips model build AND every
step trace/compile — the engine then emits zero ``compile`` spans into
the job's trace, which is the warm path's observable proof
(docs/service.md).

Not jax-free (building models touches jax): imported only by the daemon,
never by the client commands.
"""

from __future__ import annotations

import time
from typing import Optional

from ..utils.cfg import (
    TlcConfig,
    build_model,
    parse_cfg,
    resolved_invariants,
)


def canonical_constants(constants: dict) -> tuple:
    """Hashable canonical form of a .cfg's CONSTANTS block."""
    return tuple(
        (k, tuple(v) if isinstance(v, list) else v)
        for k, v in sorted(constants.items())
    )


def resolve_kernel_source(kernel_source: str, module: str) -> bool:
    """'auto'|'emitted'|'hand' -> emitted? — same resolution as the CLI
    (`auto` = emitted iff the reference checkout has the module)."""
    if kernel_source == "emitted":
        return True
    if kernel_source == "hand":
        return False
    from ..models.emitted import ref_path

    return (ref_path() / f"{module}.tla").exists()


def shape_key(module: str, cfg: TlcConfig, emitted: bool,
              invariants: tuple) -> tuple:
    """The compile-cache key (see module docstring for why these and only
    these fields determine the compiled artifact)."""
    return (
        module,
        bool(emitted),
        canonical_constants(cfg.constants),
        tuple(invariants),
        tuple(cfg.constraints),
        bool(cfg.check_deadlock),
    )


class KernelCache:
    """In-process cache of built models + prepared engine kernels, keyed
    by schema shape.  Bounded LRU (``max_entries``): compiled programs are
    tens of MB of host memory each on big models, and a long-lived daemon
    must not grow without bound across every shape it has ever seen."""

    def __init__(self, max_entries: int = 32):
        self.max_entries = max_entries
        self._entries: dict = {}  # key -> entry dict
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, module: str, cfg: TlcConfig, emitted: bool,
            invariants: tuple) -> dict:
        """-> {model, prepared, key, hit, build_s}; builds on miss."""
        from ..engine.bfs import prepare

        key = shape_key(module, cfg, emitted, invariants)
        entry = self._entries.get(key)
        if entry is not None:
            self.hits += 1
            entry["last_used"] = time.time()
            entry["uses"] += 1
            return {**entry, "hit": True}
        self.misses += 1
        t0 = time.perf_counter()
        build_cfg = TlcConfig(
            constants=dict(cfg.constants),
            invariants=list(invariants),
            constraints=list(cfg.constraints),
            specification=cfg.specification,
            check_deadlock=cfg.check_deadlock,
        )
        model = build_model(module, build_cfg, emitted=emitted)
        entry = {
            "key": key,
            "model": model,
            "prepared": prepare(model),
            "build_s": round(time.perf_counter() - t0, 3),
            "last_used": time.time(),
            "uses": 1,
        }
        self._entries[key] = entry
        if len(self._entries) > self.max_entries:
            lru = min(self._entries.values(), key=lambda e: e["last_used"])
            del self._entries[lru["key"]]
            self.evictions += 1
        return {**entry, "hit": False}

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "hit_rate": round(
                self.hits / max(1, self.hits + self.misses), 4
            ),
        }


def job_cfg(spec: dict) -> TlcConfig:
    """Parse a job spec's inline .cfg text."""
    cfg = parse_cfg(spec["cfg_text"])
    return cfg


def job_invariants(module: str, cfg: TlcConfig) -> tuple:
    """The invariant names, in model order, that a solo ``cli check`` of
    this job would build and check.  Delegates to build_model's own
    resolution (utils.cfg.resolved_invariants) so the batched replay
    (service/batch.py) can never drift from the solo path; an unknown
    module raises KeyError loudly, same as build_model."""
    return resolved_invariants(module, cfg)
