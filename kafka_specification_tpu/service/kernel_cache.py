"""Shape-keyed compile cache: the warm heart of the serving daemon.

A cold ``cli check`` pays ~2 minutes of jax import + reference parse +
model build + trace/XLA-compile for seconds of actual checking (TODO.md).
The daemon pays each of those exactly once per *schema shape* and then
serves every later job of that shape warm, following the compiler-first
portable-cache design of arXiv:2603.09555 (PAPERS.md): make compilation a
keyed artifact, look it up in O(1).

Two layers (the model-layer/invariant-overlay split, ROADMAP item 3):

**Model layer** — keyed by ``(module, kernel source, canonical CONSTANTS,
constraints)``: the expensive artifact.  One entry holds the built
:class:`~..models.base.Model` (reference parse, symbolic emit, schema,
action kernels) constructed with the sorted UNION of every invariant any
overlay of this shape has asked for, plus the model-lifetime jitted-step
cache (``_step_cache``).

**Invariant overlay** — keyed by the full shape key (ordered invariants +
deadlock flag): a cheap view over its base model.  The invariant
selection adds/removes predicate kernels AND fixes the first-violation
order, so it must key — ORDERED — but it does not need a second model
build: the overlay reorders the base's Invariant objects (and
column-permutes the base's fused invariant evaluator) and SHARES the
base's step cache.  Step-cache keys carry the ordered invariant names
(engine.bfs._Step.inv_sig), so invariant-free step programs — the whole
batched-exploration path — are shared across every overlay of a shape,
while each ordering's invariant-bearing programs compile once per order.
This is what retires the old "mixed solo/batched traffic of one schema
shape holds two cache lines" note: solo (cfg-order invariants) and
batched (sorted-union invariants) traffic now share one model build and
one step cache, and the solo order only adds its own thin overlay.

Two .cfg files with the same semantic content — regardless of path,
comments, or formatting — therefore hit the same overlay.  Engine knobs
(bucket floor, chunk size, visited backend) select among the per-model
compiled step variants and ride in the GROUP key (scheduler), not here.

A hit skips model build AND every step trace/compile — the engine then
emits zero ``compile`` spans into the job's trace, which is the warm
path's observable proof (docs/service.md).

Not jax-free (building models touches jax): imported only by the daemon,
never by the client commands.
"""

from __future__ import annotations

import time
from typing import Optional

from ..utils.cfg import (
    TlcConfig,
    build_model,
    parse_cfg,
    resolved_invariants,
)


def canonical_constants(constants: dict) -> tuple:
    """Hashable canonical form of a .cfg's CONSTANTS block."""
    return tuple(
        (k, tuple(v) if isinstance(v, list) else v)
        for k, v in sorted(constants.items())
    )


def resolve_kernel_source(kernel_source: str, module: str) -> bool:
    """'auto'|'emitted'|'hand' -> emitted? — same resolution as the CLI
    (`auto` = emitted iff the reference checkout has the module)."""
    if kernel_source == "emitted":
        return True
    if kernel_source == "hand":
        return False
    from ..models.emitted import ref_path

    return (ref_path() / f"{module}.tla").exists()


def shape_key(module: str, cfg: TlcConfig, emitted: bool,
              invariants: tuple) -> tuple:
    """The overlay key (ordered invariants fix the first-violation rule,
    so they key verbatim; see module docstring)."""
    return (
        module,
        bool(emitted),
        canonical_constants(cfg.constants),
        tuple(invariants),
        tuple(cfg.constraints),
        bool(cfg.check_deadlock),
    )


def model_key(module: str, cfg: TlcConfig, emitted: bool) -> tuple:
    """The model-layer key: everything that shapes the built Model except
    the invariant selection (overlaid) and the deadlock flag (a pure
    engine knob — the step programs compute deadlock info either way)."""
    return (
        module,
        bool(emitted),
        canonical_constants(cfg.constants),
        tuple(cfg.constraints),
    )


def _overlay_model(base, invariants: tuple):
    """A cheap Model view selecting `invariants` (ordered) from `base`.

    Shares the base's spec/actions/decode AND its step cache (the
    expensive compiled artifacts); the fused invariant evaluator is a
    column permutation of the base's, so the shared predicate core
    compiles once per base, not once per ordering."""
    base_names = [i.name for i in base.invariants]
    if tuple(base_names) == tuple(invariants):
        return base
    import dataclasses

    import jax.numpy as jnp

    idx = tuple(base_names.index(n) for n in invariants)
    fused = None
    if base.invariants_fused is not None:
        def fused(s, _f=base.invariants_fused, _ix=idx):
            return _f(s)[jnp.asarray(_ix)]

    view = dataclasses.replace(
        base,
        invariants=[base.invariant(n) for n in invariants],
        invariants_fused=fused,
    )
    # one step cache per BASE: overlays share compiled programs; the
    # ordered-invariant component of each step key (engine.bfs._Step)
    # keeps invariant-bearing programs per-order while everything
    # invariant-free is shared
    for attr in ("_step_cache", "_step_compiled_log"):
        store = getattr(base, attr, None)
        if store is None:
            store = {} if attr == "_step_cache" else set()
            setattr(base, attr, store)
        setattr(view, attr, store)
    return view


class KernelCache:
    """In-process two-layer cache of built models + prepared engine
    kernels.  Bounded LRU over the overlays (``max_entries``): compiled
    programs are tens of MB of host memory each on big models, and a
    long-lived daemon must not grow without bound across every shape it
    has ever seen.  Base models are dropped when their last overlay is
    evicted."""

    def __init__(self, max_entries: int = 32):
        self.max_entries = max_entries
        self._entries: dict = {}  # overlay key -> entry dict
        self._models: dict = {}  # model key -> {model, names, build_s}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.model_builds = 0
        self.overlay_derives = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _base(self, module: str, cfg: TlcConfig, emitted: bool,
              invariants: tuple) -> dict:
        """The model-layer entry covering `invariants`, building (or
        rebuilding with a grown union) when needed."""
        bkey = model_key(module, cfg, emitted)
        base = self._models.get(bkey)
        if base is not None and set(invariants) <= set(base["names"]):
            return base
        union = sorted(set(invariants) | set(base["names"] if base else ()))
        t0 = time.perf_counter()
        build_cfg = TlcConfig(
            constants=dict(cfg.constants),
            invariants=list(union),
            constraints=list(cfg.constraints),
            specification=cfg.specification,
            check_deadlock=cfg.check_deadlock,
        )
        model = build_model(module, build_cfg, emitted=emitted)
        self.model_builds += 1
        if base is not None:
            # a grown union replaced the base: overlays derived from the
            # OLD base would otherwise pin a second full model + step
            # cache for this shape (the exact cost this split retires) —
            # drop them so their next request re-derives from the new
            # base (in-flight callers keep their own references)
            for k in [
                k for k, e in self._entries.items()
                if e.get("base_key") == bkey
            ]:
                del self._entries[k]
        base = {
            "key": bkey,
            "model": model,
            # the names actually RESOLVED into the model (builders may
            # apply defaults), so coverage checks match reality
            "names": tuple(i.name for i in model.invariants),
            "build_s": round(time.perf_counter() - t0, 3),
        }
        self._models[bkey] = base
        return base

    def get(self, module: str, cfg: TlcConfig, emitted: bool,
            invariants: tuple) -> dict:
        """-> {model, prepared, key, hit, build_s}; builds on miss.
        A miss that lands on a warm model layer derives an invariant
        overlay (no model build, no step compiles for the shared
        invariant-free programs) — ``overlay`` is True on such entries."""
        from ..engine.bfs import prepare

        key = shape_key(module, cfg, emitted, invariants)
        entry = self._entries.get(key)
        if entry is not None:
            self.hits += 1
            entry["last_used"] = time.time()
            entry["uses"] += 1
            return {**entry, "hit": True}
        self.misses += 1
        t0 = time.perf_counter()
        prior = self._models.get(model_key(module, cfg, emitted))
        base = self._base(module, cfg, emitted, invariants)
        overlay = prior is not None and prior is base  # warm base, no build
        model = _overlay_model(base["model"], tuple(invariants))
        if model is not base["model"]:
            self.overlay_derives += 1
        entry = {
            "key": key,
            "base_key": base["key"],
            "model": model,
            "prepared": prepare(model),
            "build_s": round(time.perf_counter() - t0, 3),
            "overlay": bool(overlay),
            "last_used": time.time(),
            "uses": 1,
        }
        self._entries[key] = entry
        if len(self._entries) > self.max_entries:
            lru = min(self._entries.values(), key=lambda e: e["last_used"])
            del self._entries[lru["key"]]
            self.evictions += 1
            # drop the base model once no overlay references it
            bk = lru.get("base_key")
            if bk is not None and not any(
                e.get("base_key") == bk for e in self._entries.values()
            ):
                self._models.pop(bk, None)
        return {**entry, "hit": False}

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "hit_rate": round(
                self.hits / max(1, self.hits + self.misses), 4
            ),
            "model_layer": {
                "entries": len(self._models),
                "builds": self.model_builds,
                "overlay_derives": self.overlay_derives,
            },
        }


def job_cfg(spec: dict) -> TlcConfig:
    """Parse a job spec's inline .cfg text."""
    cfg = parse_cfg(spec["cfg_text"])
    return cfg


def job_invariants(module: str, cfg: TlcConfig) -> tuple:
    """The invariant names, in model order, that a solo ``cli check`` of
    this job would build and check.  Delegates to build_model's own
    resolution (utils.cfg.resolved_invariants) so the batched replay
    (service/batch.py) can never drift from the solo path; an unknown
    module raises KeyError loudly, same as build_model."""
    return resolved_invariants(module, cfg)
