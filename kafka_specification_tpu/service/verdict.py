"""The one machine-readable verdict format (``kspec-verdict/1``).

``cli check --json``, the service's ``results/<job_id>.json`` files, and
``cli result`` all emit/consume this record — one schema, stamped with a
version, a run_id, and the process exit code, so a service client can
switch between "run it locally" and "submit it to the daemon" without
changing its parser:

    {"schema": "kspec-verdict/1",
     "model": ..., "distinct_states": ..., "diameter": ..., "levels": [...],
     "states_per_sec": ..., "seconds": ...,
     "violation": null | {"invariant": ..., "depth": ..., "trace_len": ...},
     "run_id": ..., "exit_code": 0|1|75|2,
     ...service jobs add: job_id, tenant, status, timing, batch}

Exit-code vocabulary (mirrors the CLI's):
  0   exhaustive pass, no violation
  1   invariant violated (the verdict IS the product — not an error)
  75  RESOURCE_EXHAUSTED (resilience.resources): the job ran out of its
      budgeted disk/RSS/time and exited typed; resubmit after the
      operator/tenant frees the budget
  2   error (bad config, unknown module, engine failure)

Must stay jax-free: ``cli result`` renders these on operator boxes whose
accelerator stack is wedged.
"""

from __future__ import annotations

from typing import Optional

# the canonical rc-75 constant (resilience.resources is jax-free too)
from ..resilience.resources import EXIT_RESOURCE_EXHAUSTED as EXIT_RESOURCE

VERDICT_SCHEMA = "kspec-verdict/1"

EXIT_OK = 0
EXIT_VIOLATION = 1
EXIT_ERROR = 2


def verdict_from_result(res, run_id: Optional[str] = None) -> dict:
    """Build the verdict record from an engine CheckResult (duck-typed:
    anything with model/total/diameter/levels/seconds/states_per_sec/
    violation attributes)."""
    violation = None
    if res.violation is not None:
        violation = {
            "invariant": res.violation.invariant,
            "depth": res.violation.depth,
            "trace_len": len(res.violation.trace),
        }
    return {
        "schema": VERDICT_SCHEMA,
        "model": res.model,
        "distinct_states": res.total,
        "diameter": res.diameter,
        "levels": list(res.levels),
        "states_per_sec": round(res.states_per_sec, 1),
        "seconds": round(res.seconds, 3),
        "violation": violation,
        "run_id": run_id,
        "exit_code": EXIT_OK if res.violation is None else EXIT_VIOLATION,
    }


def error_verdict(message: str, run_id: Optional[str] = None,
                  exit_code: int = EXIT_ERROR) -> dict:
    """Verdict for a job that produced no CheckResult (build failure,
    resource exhaustion, daemon-side crash)."""
    return {
        "schema": VERDICT_SCHEMA,
        "model": None,
        "distinct_states": None,
        "diameter": None,
        "levels": None,
        "states_per_sec": None,
        "seconds": None,
        "violation": None,
        "error": message,
        "run_id": run_id,
        "exit_code": exit_code,
    }


def verdict_exit_code(rec: dict) -> int:
    """The process exit code a consumer of this verdict should use."""
    code = rec.get("exit_code")
    return EXIT_ERROR if code is None else int(code)


def render_verdict(rec: dict) -> str:
    """Human one-glance rendering (``cli result`` without --json)."""
    lines = []
    status = rec.get("status")
    head = f"Job {rec['job_id']}" if rec.get("job_id") else "Verdict"
    if status:
        head += f"  [{status.upper()}]"
    lines.append(head)
    if rec.get("tenant"):
        lines.append(f"  tenant: {rec['tenant']}")
    if rec.get("run_id"):
        lines.append(f"  run: {rec['run_id']}")
    if rec.get("error"):
        lines.append(f"  error: {rec['error']}")
    if rec.get("model") is not None:
        lines.append(
            f"  {rec['model']}: {rec['distinct_states']} distinct states, "
            f"diameter {rec['diameter']}, {rec['seconds']}s "
            f"({rec['states_per_sec']:,.0f} states/sec)"
        )
    v = rec.get("violation")
    if v:
        lines.append(
            f"  Invariant {v['invariant']} is VIOLATED at depth "
            f"{v['depth']} (trace of {v['trace_len']} states in the run "
            f"report)"
        )
    elif rec.get("model") is not None:
        lines.append("  No invariant violations. Exhaustive check complete.")
    t = rec.get("timing") or {}
    if t:
        lines.append(
            f"  latency: wait {t.get('wait_s', '?')}s + "
            f"run {t.get('wall_s', '?')}s = {t.get('latency_s', '?')}s "
            f"submit->verdict"
        )
    b = rec.get("batch") or {}
    if b.get("group_size", 0) > 1:
        lines.append(
            f"  batched: group of {b['group_size']} jobs sharing schema "
            f"shape (leader run {b.get('leader_run_id')})"
        )
    lines.append(f"  exit code: {verdict_exit_code(rec)}")
    return "\n".join(lines)
