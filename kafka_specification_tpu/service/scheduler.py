"""Scheduler: group claimed jobs for batching, enforce per-tenant policy.

Grouping.  Jobs coalesce into one engine run when they share a **group
key**: the compile-cache shape key minus the invariant selection
(module, kernel source, canonical constants, constraints), plus tenant
and deadlock flag — i.e. *configs sharing a schema shape*.  Members of a
group may differ in invariant selection, ``max_depth`` and
``max_states``: the batch runner (service/batch.py) explores once with
the UNION of the group's invariants compiled in and derives every
member's verdict bit-identically from the shared exploration.  Grouping
is per-tenant so resource accounting stays exact (the compile cache
already amortizes across tenants — the expensive part is shared
globally; only the per-level launches are per-tenant).

Jobs that cannot coalesce run the REAL solo engine path — a plain
``check()`` with full check_invariants/check_deadlock semantics, still
warm through the kernel cache: deadlock-checking jobs (the deadlock
verdict is entangled with chunk order in a way the post-hoc derivation
does not replay) and jobs carrying a fault-injection plan.

Tenancy.  ``<svc>/tenants.json`` (resilience.resources.TenantBudget)
gives each tenant disk/RSS budgets, a per-level deadline, and a
``max_pending`` admission cap.  Each job runs under a FRESH per-tenant
ResourceGovernor watching that job's run directory: a breach exits that
job typed (rc-75 verdict) without touching the daemon or sibling jobs.

Must stay jax-free (pure bookkeeping; the daemon imports the jax side).
"""

from __future__ import annotations

import os
from typing import Optional

from ..resilience.resources import (
    ResourceGovernor,
    budget_for_tenant,
    load_tenant_budgets,
)
from .kernel_cache import canonical_constants, job_invariants

# re-parse budgets at most this often (seconds): operators edit
# tenants.json under a live daemon
_BUDGET_TTL_S = 5.0


def group_key(spec: dict, cfg, emitted: bool) -> tuple:
    return (
        spec.get("tenant", "default"),
        spec["module"],
        bool(emitted),
        canonical_constants(cfg.constants),
        tuple(cfg.constraints),
        bool(cfg.check_deadlock),
    )


def solo_only(spec: dict, cfg) -> bool:
    """True when this job must run alone (see module docstring).  A
    state-cache-seeded job (daemon._consult_state_cache) also runs solo:
    the engine seed plugs into check(), not the batched runner.  A job
    submitted with ``solo: true`` (queue.submit(solo=True) — the sweep
    portfolio marks predicted-expensive points this way) is honored too:
    one huge member would otherwise drag its whole group's shared
    exploration out to ITS bounds envelope."""
    return (
        bool(cfg.check_deadlock)
        or bool(spec.get("fault"))
        or bool(spec.get("solo"))
        or bool(spec.get("_state_cache_seed"))
    )


def plan_groups(jobs: list, max_group: Optional[int] = None) -> list:
    """claimed [(spec, cfg, emitted)] -> list of groups (lists of those
    triples), submit-order preserved within and across groups.
    ``max_group`` caps group width by splitting an oversized group into
    submit-order packs (batch.pack_members): a thousand-point sweep
    sharing one schema shape must not force one exploration to the
    envelope of ALL thousand bounds — packs keep the memory-resident
    shared record (batch.py holds every level in RAM) bounded."""
    groups: dict = {}
    order: list = []
    for item in jobs:
        spec, cfg, emitted = item
        if solo_only(spec, cfg):
            order.append([item])
            continue
        key = group_key(spec, cfg, emitted)
        g = groups.get(key)
        if g is None:
            g = groups[key] = []
            order.append(g)
        g.append(item)
    if max_group is not None and max_group > 0:
        from .batch import pack_members

        packed: list = []
        for g in order:
            packed.extend(pack_members(g, max_group))
        return packed
    return order


def union_invariants(group: list) -> tuple:
    """Union of the members' invariant selections, SORTED — arrival order
    is semantically irrelevant to a shared exploration (invariants are
    compiled out of it; verdict derivation replays each member's own
    order by name), so sorting canonicalizes the kernel-cache shape key:
    {TypeOk, WeakIsr} hits the same cache line whichever job arrived
    first.  Solo-semantics jobs (deadlock/fault) bypass this and build
    with their own .cfg order, where first-violation order matters."""
    names: set = set()
    for spec, cfg, _em in group:
        names.update(job_invariants(spec["module"], cfg))
    return tuple(sorted(names))


class TenantPolicy:
    """Budget lookup + admission control, re-reading tenants.json with a
    small TTL so edits under a live daemon take effect."""

    def __init__(self, tenants_path: str):
        self.path = tenants_path
        self._budgets: dict = {}
        self._loaded_at = 0.0
        self._mtime = None

    def _refresh(self) -> None:
        import sys

        from ..utils import clock as _clk

        now = _clk.monotonic()
        if now - self._loaded_at < _BUDGET_TTL_S:
            return
        self._loaded_at = now
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            mtime = None
        if mtime == self._mtime:
            return
        try:
            budgets = load_tenant_budgets(self.path)
        except Exception as e:  # noqa: BLE001 — operator typo mid-edit
            # a malformed tenants.json under a LIVE daemon must not crash
            # it (the TTL reload exists precisely for live edits): keep
            # the previous budgets, warn, and retry next TTL — _mtime is
            # only advanced on success so the fix is picked up
            print(
                f"[serve] WARNING: ignoring malformed {self.path}: {e} "
                "(keeping previous tenant budgets)",
                file=sys.stderr,
            )
            return
        self._mtime = mtime
        self._budgets = budgets

    def budget(self, tenant: str):
        self._refresh()
        return budget_for_tenant(self._budgets, tenant)

    # NOTE: max_pending admission is enforced client-side at submit time
    # (utils/cli.py), where a malformed tenants.json should fail LOUDLY
    # (exit 2) rather than be tolerated like the live daemon does here.

    def governor(self, tenant: str, watch_dirs=(),
                 fault_plan=None) -> Optional[ResourceGovernor]:
        """A fresh per-job governor under the tenant's budgets, or None
        when the tenant is unbudgeted (engine falls back to env knobs).
        The job's fault plan rides along: a supplied governor replaces the
        engine's env-derived one, fault hooks included."""
        b = self.budget(tenant)
        if b is None:
            return None
        return b.governor(watch_dirs=watch_dirs, fault_plan=fault_plan)
