"""Durable on-disk job queue: the tenants' side of checking-as-a-service.

Layout under one service directory (``--service-dir`` /
``$KSPEC_SERVICE_DIR``)::

    <svc>/
      queue/pending/<job_id>.json   submitted, waiting for the daemon
      queue/claimed/<job_id>.json   claimed by a live daemon (in flight)
      queue/done/<job_id>.json      terminal (spec retained for audit)
      queue/by-tenant/<digest>/<job_id>   empty admission-index markers
      results/<job_id>.json         the kspec-verdict/1 record
      runs/<job_id>/                per-job obs run directory (PR 3)
      service/                      daemon heartbeat/metrics/events/logs
      tenants.json                  per-tenant budgets (resilience.resources)

Every transition is a single atomic filesystem operation — submit is
tmp-write + ``os.rename`` into ``pending/``, claim and finish are
``os.rename`` between state directories, the verdict is tmp-write +
rename — so a crash at any instant leaves each job in exactly one state
and never publishes a torn spec or verdict.  A daemon that died mid-job
leaves its claims in ``claimed/``; the next daemon's startup janitor
(:meth:`JobQueue.requeue_orphans`) moves them back to ``pending/`` (job
execution is idempotent: nothing is committed until the verdict rename).

Claim leases: every claim is stamped with a sidecar
``claimed/<job_id>.lease`` recording the claimer's pid + per-process
token + a lease timestamp, renewed while the daemon works (the
busy-heartbeat loop calls :meth:`renew_leases` for every unfinished
claim of its drain sweep).  The janitor requeues a claim only when its lease
is ORPHANED — no lease file, the pid is gone, or the lease expired
(``lease_ttl``, default 900s, covering a wedged-but-alive daemon and
shared-filesystem queues where pid liveness can't be probed).  A live
sibling's claim is left alone, which is what lets two daemons share one
queue directory: both janitors run at startup, neither steals in-flight
work, and a genuinely dead daemon's claims still come back.

Job spec (``kspec-job/1``)::

    {"schema": "kspec-job/1", "job_id": ..., "tenant": ...,
     "module": ..., "cfg_text": "<inline TLC .cfg>", "cfg_path": ...,
     "kernel_source": "auto"|"emitted"|"hand",
     "max_depth": null|int, "max_states": null|int,
     "submitted_unix": <float>, "fault": null|"<KSPEC_FAULT plan>"}

The .cfg travels INLINE (the client reads the file at submit time): the
daemon never depends on the tenant's filesystem, and the job file is the
complete, self-contained unit of work.

Must stay jax-free: ``cli submit/status/result`` run on client boxes that
never pay the jax import (the whole point of the service).
"""

from __future__ import annotations

import errno
import hashlib
import json
import os
import random
import time
from typing import Optional

# one shared tmp-write+fsync+replace idiom (jax-free like this module);
# job/result paths are unique per writer so the fixed .tmp suffix is safe
from .. import durable_io as _dio
from ..obs import fleettrace
from ..obs.atomicio import atomic_write_json
# every timing decision routes through the injectable clock boundary so
# the simfleet harness can own lease/heartbeat/backoff time wholesale
# (utils/clock.py; default = real time, zero behavior change)
from ..utils import clock as _clk

JOB_SCHEMA = "kspec-job/1"

# --- transient-error retry (the jax-free submit-side router's core) -------
#
# Service directories live on network filesystems in a fleet deployment,
# where stat/open/rename can fail TRANSIENTLY (EAGAIN under load, EIO on a
# flapping mount, ESTALE after a server-side rename).  A client that
# surfaces those as a raw traceback — or worse, masks them as a wrong
# answer ("unknown job", "no verdict") — makes every submit script flaky.
# Every client-side queue operation (submit/status/result/overview) runs
# through `retry_transient`: bounded exponential backoff on the transient
# errno classes only (ENOENT is NOT one — "file absent" is an answer, not
# a fault), then the last error propagates for the caller to render.
_TRANSIENT_ERRNOS = frozenset(
    v
    for v in (
        errno.EAGAIN,
        getattr(errno, "EWOULDBLOCK", errno.EAGAIN),
        errno.EIO,
        getattr(errno, "ESTALE", None),
        errno.EBUSY,
        errno.ENFILE,
        errno.EMFILE,
    )
    if v is not None
)

#: bounded backoff schedule: attempts x base (doubling, capped) — ~0.3s
#: worst case at the defaults, far below any submit script's own timeout
RETRY_ATTEMPTS = int(os.environ.get("KSPEC_QUEUE_RETRY_ATTEMPTS", "5"))
RETRY_BASE_S = float(os.environ.get("KSPEC_QUEUE_RETRY_BASE_S", "0.02"))
RETRY_CAP_S = 0.25

#: module-level jitter source; callers (tests) may pass their own seeded
#: ``random.Random`` for a reproducible backoff trace
_RETRY_RNG = random.Random()

#: allowance for wall-clock disagreement between hosts sharing a queue
#: directory (router vs claimer, janitor vs claimer): every freshness /
#: expiry comparison of a timestamp WRITTEN BY ANOTHER HOST widens its
#: window by this much, so a live claim from a slightly-behind clock is
#: never stolen (KSPEC_CLOCK_SKEW overrides; single-host deployments can
#: set it to 0)
DEFAULT_CLOCK_SKEW_S = 5.0


def clock_skew_s(explicit: Optional[float] = None) -> float:
    """The effective skew allowance.  ``explicit`` (a harness or an
    operator threading the value as a parameter) wins over the
    ``KSPEC_CLOCK_SKEW`` env default; both are clamped non-negative —
    a negative allowance would NARROW freshness windows and steal live
    claims."""
    if explicit is not None:
        return max(0.0, float(explicit))
    try:
        return max(
            0.0, float(os.environ.get("KSPEC_CLOCK_SKEW",
                                      DEFAULT_CLOCK_SKEW_S))
        )
    except ValueError:
        return DEFAULT_CLOCK_SKEW_S


def is_transient_oserror(e: OSError) -> bool:
    return e.errno in _TRANSIENT_ERRNOS


def retry_transient(fn, attempts: Optional[int] = None,
                    base: Optional[float] = None, rng=None):
    """Run `fn()`; on a transient OSError retry with bounded FULL-JITTER
    backoff, re-raising the final failure.  Non-transient OSErrors
    (ENOENT, EACCES, ...) propagate immediately — they are answers or
    real faults, not flakes.

    Full jitter (sleep ~ U[0, min(cap, base*2^i)]) instead of the plain
    capped exponential: when a fleet-wide ESTALE hits every client of a
    shared service directory at once, deterministic backoff re-collides
    the whole fleet on each retry; uniform jitter spreads the herd.
    `rng` (a ``random.Random``) makes the schedule reproducible in tests.
    """
    attempts = RETRY_ATTEMPTS if attempts is None else attempts
    base = RETRY_BASE_S if base is None else base
    rng = _RETRY_RNG if rng is None else rng
    for i in range(max(1, attempts)):
        try:
            return fn()
        except OSError as e:
            if not is_transient_oserror(e) or i >= attempts - 1:
                raise
            # the injected clock, not the wall: under simfleet a flaky-fs
            # schedule's whole backoff ladder costs virtual time only
            _clk.sleep(rng.uniform(0.0, min(RETRY_CAP_S,
                                            base * (2.0 ** i))))

PENDING = "pending"
CLAIMED = "claimed"
DONE = "done"

#: default seconds before an unrenewed claim lease counts as orphaned
#: (KSPEC_CLAIM_LEASE_TTL overrides; generous — the busy-heartbeat loop
#: renews every few seconds, so expiry means the claimer is truly gone
#: or wedged beyond its own supervisor's stall timeout)
DEFAULT_LEASE_TTL = 900.0

#: per-process claim token: pid alone cannot identify a claimer (a
#: restarted daemon can be handed its dead predecessor's recycled pid,
#: especially in small-pid-space containers) — leases carry pid+token,
#: and only a matching PAIR reads as "our own claim"
_PROC_TOKEN = os.urandom(8).hex()


def _pid_alive(pid: int) -> bool:
    """Best-effort pid liveness (same-host daemons).  Treats EPERM as
    alive (the pid exists under another uid) and any other failure as
    unknowable-alive=False."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except PermissionError:
        return True
    except OSError:
        return False


def new_job_id() -> str:
    """Sortable, collision-resistant without coordination (same recipe as
    obs run ids, distinct prefix so job and run ids never read alike)."""
    return "job-{}-{}-{}".format(
        time.strftime("%Y%m%dT%H%M%S", time.gmtime(_clk.now())),
        os.getpid(),
        os.urandom(3).hex(),
    )


class JobQueue:
    """One service directory's queue; safe for many concurrent submitters
    and one daemon (claims are renames: first mover wins, losers skip)."""

    def __init__(self, service_dir: str, create: bool = True,
                 skew_s: Optional[float] = None):
        """create=False opens read-only (``cli status``/``result``): a
        mistyped --service-dir must raise, not silently fabricate an
        empty service tree that masks the typo as 'no such job'.

        ``skew_s`` pins this queue's clock-skew allowance explicitly
        (crashcheck's crashed-process view passes 0.0; simfleet threads
        its scenario value) — ``None`` keeps the ``KSPEC_CLOCK_SKEW``
        env default.  An explicit parameter instead of an env mutation:
        the env var is process-global and two concurrent harnesses would
        trample each other's save/restore."""
        self.skew_s = skew_s
        self.dir = os.path.normpath(service_dir)
        self.queue_dir = os.path.join(self.dir, "queue")
        self.results_dir = os.path.join(self.dir, "results")
        self.runs_dir = os.path.join(self.dir, "runs")
        self.service_dir = os.path.join(self.dir, "service")
        self.tenants_path = os.path.join(self.dir, "tenants.json")
        self.tenant_index_dir = os.path.join(self.queue_dir, "by-tenant")
        if create:
            for state in (PENDING, CLAIMED, DONE):
                os.makedirs(
                    os.path.join(self.queue_dir, state), exist_ok=True
                )
            os.makedirs(self.tenant_index_dir, exist_ok=True)
            os.makedirs(self.results_dir, exist_ok=True)
            os.makedirs(self.runs_dir, exist_ok=True)
            # startup-janitor parity (crashcheck `queue` scenario): a
            # publisher killed mid-atomic-write leaves a `.tmp` sibling
            # in a queue state dir that no except block will ever
            # collect.  These dirs are MULTI-writer (every client
            # constructs a JobQueue), so unlike the single-owner storage
            # structures the sweep is grace-aged: only tmps old enough
            # that no live writer can still be mid-promote are removed.
            for d in (
                os.path.join(self.queue_dir, PENDING),
                os.path.join(self.queue_dir, CLAIMED),
                os.path.join(self.queue_dir, DONE),
                self.results_dir,
            ):
                _dio.sweep_tmp(d, min_age_s=_dio.TMP_SWEEP_GRACE_S)
        elif not os.path.isdir(self.queue_dir):
            raise FileNotFoundError(
                f"no service directory at {self.dir!r} (queue/ missing — "
                "check --service-dir / $KSPEC_SERVICE_DIR)"
            )

    # --- paths ------------------------------------------------------------
    def _job_path(self, state: str, job_id: str) -> str:
        return os.path.join(self.queue_dir, state, f"{job_id}.json")

    def _lease_path(self, job_id: str) -> str:
        return os.path.join(self.queue_dir, CLAIMED, f"{job_id}.lease")

    def result_path(self, job_id: str) -> str:
        return os.path.join(self.results_dir, f"{job_id}.json")

    def run_dir(self, job_id: str) -> str:
        return os.path.join(self.runs_dir, job_id)

    def _skew(self, override: Optional[float] = None) -> float:
        """Effective skew allowance for this queue's freshness math: a
        per-call override wins, then the instance pin, then the env."""
        return clock_skew_s(override if override is not None
                            else self.skew_s)

    def _tenant_dir(self, tenant: str) -> str:
        """Per-tenant marker directory (admission-control index).  Keyed
        by a digest: tenant names are tenant input and must not be able
        to escape the index dir or collide with each other's paths."""
        digest = hashlib.sha1(tenant.encode("utf-8", "replace")).hexdigest()
        return os.path.join(self.tenant_index_dir, digest[:16])

    # --- client side ------------------------------------------------------
    def submit(
        self,
        cfg_text: str,
        module: str,
        tenant: str = "default",
        cfg_path: Optional[str] = None,
        kernel_source: str = "auto",
        max_depth: Optional[int] = None,
        max_states: Optional[int] = None,
        fault: Optional[str] = None,
        job_id: Optional[str] = None,
        solo: bool = False,
    ) -> dict:
        """Atomically publish one job spec into pending/; returns it.
        ``solo=True`` stamps the spec so the scheduler never coalesces
        this job into a batched group (the sweep portfolio marks
        predicted-expensive points this way — one huge member would drag
        its group's shared exploration out to ITS bounds envelope)."""
        if kernel_source not in ("auto", "emitted", "hand"):
            raise ValueError(f"bad kernel_source {kernel_source!r}")
        # the submit span's window must come from ONE clock (the trace
        # clock, which a skew fault shifts wholesale) — mixing the wall
        # anchor with a skewed close stamp would tear the root span
        # across two clock domains in a single record
        t_sub = fleettrace.now()
        spec = {
            "schema": JOB_SCHEMA,
            "job_id": job_id or new_job_id(),
            "tenant": tenant,
            "module": module,
            "cfg_text": cfg_text,
            "cfg_path": cfg_path,
            "kernel_source": kernel_source,
            "max_depth": max_depth,
            "max_states": max_states,
            "submitted_unix": round(_clk.now(), 3),
            "fault": fault,
        }
        if solo:
            # optional stamp (absent on non-solo specs): old daemons that
            # predate it just ignore the key — kspec-job/1 stays one schema
            spec["solo"] = True
        # the fleet trace context rides INSIDE the spec (same optional-key
        # contract as "solo"): it survives re-route, crash takeover, and
        # sweep batching with zero side channels, and components that
        # predate it no-op their stamp sites (obs/fleettrace.py)
        spec["trace"] = fleettrace.mint_trace(
            spec["job_id"], spec["submitted_unix"]
        )
        # marker BEFORE the spec publish: the admission index may briefly
        # overcount a submit that dies here (lazily cleaned on the next
        # count), but can never undercount a published job.  The whole
        # publish sequence rides the transient-retry router: a flapping
        # network mount costs a bounded backoff, never a failed client
        # (every step is idempotent, so a retry after a partial attempt
        # just re-does it)
        def publish():
            tdir = self._tenant_dir(tenant)
            os.makedirs(tdir, exist_ok=True)
            marker = os.path.join(tdir, spec["job_id"])
            _dio.write_text(marker, "")
            atomic_write_json(self._job_path(PENDING, spec["job_id"]), spec)

        retry_transient(publish)
        # the trace root: anchored at submitted_unix, closing when the
        # spec is durably visible in pending/
        fleettrace.emit_span(
            self.dir, spec["trace"], "job-submit",
            t_sub, fleettrace.now(),
            job_id=spec["job_id"], span_id=spec["trace"]["span_id"],
            tenant=tenant, module=module,
        )
        return spec

    def status(self, job_id: str) -> dict:
        """-> {job_id, state: pending|claimed|done|unknown, result?}.

        The verdict file is checked FIRST: a published verdict is
        terminal truth wherever the spec sits (a daemon that died between
        verdict write and claim retire leaves the job requeued in
        pending/ — status must still say done, like `cli result` does).
        Two scan passes for the rest: the daemon's claim is an os.rename
        racing these isfile probes, so a single sweep can miss a live job
        in the instant it moves pending -> claimed; a second sweep closes
        that window before reporting 'unknown'."""
        rec = self.result(job_id)
        if rec is not None:
            return {"job_id": job_id, "state": DONE, "result": rec}
        for _attempt in (0, 1):
            for state in (PENDING, CLAIMED, DONE):
                if self._isfile(self._job_path(state, job_id)):
                    out = {"job_id": job_id, "state": state}
                    if state == DONE:
                        rec = self.result(job_id)
                        if rec is not None:
                            out["result"] = rec
                    return out
            # the verdict may have landed while we scanned
            rec = self.result(job_id)
            if rec is not None:
                return {"job_id": job_id, "state": DONE, "result": rec}
        return {"job_id": job_id, "state": "unknown"}

    @staticmethod
    def _isfile(path: str) -> bool:
        """os.path.isfile with the transient-retry treatment: a flaky
        stat (EAGAIN/EIO/ESTALE on a network filesystem) must not read
        as "file absent" — that masks a live job as 'unknown' and a
        published verdict as 'no verdict'."""
        import stat as _stat

        def probe():
            try:
                st = os.stat(path)
            except FileNotFoundError:
                return False
            except NotADirectoryError:
                return False
            return _stat.S_ISREG(st.st_mode)

        try:
            return retry_transient(probe)
        except OSError:
            return False  # persistent non-transient failure: honest miss

    def result(self, job_id: str) -> Optional[dict]:
        def read():
            try:
                with open(self.result_path(job_id)) as fh:
                    return json.load(fh)
            except FileNotFoundError:
                return None  # no verdict yet — an answer, not a fault

        try:
            return retry_transient(read)
        except (OSError, ValueError):
            return None

    def wait_result(self, job_id: str, timeout: float = 120.0,
                    poll: float = 0.05) -> Optional[dict]:
        deadline = _clk.monotonic() + timeout
        while True:
            rec = self.result(job_id)
            if rec is not None:
                return rec
            if _clk.monotonic() >= deadline:
                return None
            _clk.sleep(poll)

    def overview(self) -> dict:
        """Queue depths + recent terminal jobs (``cli status`` no-arg)."""
        counts = {
            state: len(self._list(state)) for state in (PENDING, CLAIMED, DONE)
        }
        recent = sorted(self._list(DONE))[-10:]
        return {"dir": self.dir, "counts": counts, "recent_done": recent}

    # --- daemon side ------------------------------------------------------
    def _list(self, state: str) -> list:
        try:
            names = retry_transient(
                lambda: os.listdir(os.path.join(self.queue_dir, state))
            )
            return [
                n[: -len(".json")] for n in names if n.endswith(".json")
            ]
        except OSError:
            return []

    def pending_count(self) -> int:
        return len(self._list(PENDING))

    def pending_for_tenant(self, tenant: str,
                           stop_at: Optional[int] = None) -> int:
        """Pending jobs queued by `tenant` (admission control), counted
        from the per-tenant marker index submit maintains: O(this
        tenant's markers) isfile probes, never an open/parse of every
        pending spec in the whole queue — one deep tenant must not make
        every OTHER tenant's submit pay an O(queue) scan.  Markers whose
        pending spec is gone (claimed/finished) are lazily removed;
        ``stop_at`` bounds the scan for threshold-only callers."""
        tdir = self._tenant_dir(tenant)
        try:
            markers = os.listdir(tdir)
        except OSError:
            return 0
        n = 0
        for job_id in markers:
            if os.path.isfile(self._job_path(PENDING, job_id)):
                n += 1
                if stop_at is not None and n >= stop_at:
                    return n
            else:
                try:  # claimed or finished since: retire the marker
                    _dio.unlink(os.path.join(tdir, job_id))
                except OSError:
                    pass
        return n

    def claimed_count(self) -> int:
        return len(self._list(CLAIMED))

    def claim_pending(self, limit: Optional[int] = None) -> list:
        """Move pending jobs to claimed/ (submit-order) and return their
        parsed specs.  Unparsable/torn specs are quarantined as done with
        no verdict rather than wedging the queue forever."""
        out = []
        for job_id in sorted(self._list(PENDING)):
            if limit is not None and len(out) >= limit:
                break
            src = self._job_path(PENDING, job_id)
            dst = self._job_path(CLAIMED, job_id)
            t_claim = fleettrace.now()
            try:
                _dio.rename(src, dst)
            except OSError:
                continue  # another daemon won the claim, or it vanished
            try:
                # rename PRESERVES the submit-time mtime: refresh it so
                # the janitor's leaseless-claim grace window (which keys
                # on the claim file's age) actually covers a claim of a
                # job that sat queued longer than the window.  Stamped
                # from the injected clock so a virtual-time janitor
                # compares like against like.
                t_mt = _clk.now()
                os.utime(dst, (t_mt, t_mt))
            except OSError:
                pass
            self._write_lease(job_id)
            try:
                with open(dst) as fh:
                    spec = json.load(fh)
                if spec.get("schema") != JOB_SCHEMA:
                    raise ValueError(
                        f"unsupported job schema {spec.get('schema')!r}"
                    )
                spec["claimed_unix"] = round(_clk.now(), 3)
                fleettrace.emit_span(
                    self.dir, spec.get("trace"), "queue-claim",
                    t_claim, fleettrace.now(), job_id=job_id,
                    claimer_pid=os.getpid(),
                )
                out.append(spec)
            except FileNotFoundError:
                # the claim vanished after we won the rename — a sibling
                # daemon's janitor requeued it (it cannot tell a live
                # claim from an orphan).  The job is VALID: leave it for
                # whoever holds it now, never quarantine it as corrupt
                continue
            except OSError:
                # transient read failure (EMFILE under fd pressure, a
                # momentary EIO) on a spec we just claimed: the job is
                # almost certainly valid — submit publishes atomically —
                # so put the claim back for a later sweep instead of
                # permanently quarantining it with an exit-2 verdict.
                # If even the requeue fails, the claim stays for the
                # next janitor.
                try:
                    _dio.rename(dst, src)
                    self._drop_lease(job_id)
                except OSError:
                    pass
            except ValueError as e:
                self.finish(job_id, verdict=None, error=f"bad job spec: {e}")
        return out

    # --- claim leases -----------------------------------------------------
    def _write_lease(self, job_id: str) -> None:
        """Stamp (or renew) this process's lease on a claimed job.  Plain
        tmp-less write: the lease is advisory liveness metadata, a torn
        read is treated as no-lease (orphan) which only costs a requeue
        of an idempotent job."""
        try:
            # injected_skew_s: the skew@host<i> fault shifts the wall
            # clock THIS host stamps into cross-host-visible metadata
            # (0.0 outside the chaos drills) — the rehearsal for a fleet
            # member whose clock drifted
            from ..resilience.faults import injected_skew_s

            _dio.write_text(
                self._lease_path(job_id),
                json.dumps(
                    {
                        "pid": os.getpid(),
                        "token": _PROC_TOKEN,
                        "lease_unix": round(
                            _clk.now() + injected_skew_s(), 3
                        ),
                    }
                ),
            )
        except OSError:
            pass  # lease-less claims degrade to the pre-lease behavior

    def _drop_lease(self, job_id: str) -> None:
        try:
            _dio.unlink(self._lease_path(job_id))
        except OSError:
            pass

    def read_lease(self, job_id: str) -> Optional[dict]:
        try:
            with open(self._lease_path(job_id)) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def renew_leases(self, job_ids) -> None:
        """Re-stamp the lease timestamp on in-flight claims (the daemon's
        busy-heartbeat loop calls this every few seconds while a group
        runs, so a healthy daemon's leases never approach the TTL)."""
        for job_id in job_ids:
            self._write_lease(job_id)

    def lease_orphaned(self, job_id: str,
                       lease_ttl: Optional[float] = None,
                       skew_s: Optional[float] = None) -> bool:
        """True iff a claimed job's lease marks it as abandoned: no lease
        sidecar (pre-lease claim or write failure), a dead claimer pid on
        this host, or an expired timestamp (shared-filesystem queues,
        where pids from another box LOOK dead — expiry is what finally
        frees their claims; a live same-host sibling renews well inside
        any sane TTL).  Expiry dominates everything, including our own
        pid: an expired lease means the claimer is wedged beyond its
        renewal loop (or a foreign/recycled pid merely aliases a live
        one), and requeueing an idempotent job is the safe response."""
        lease = self.read_lease(job_id)
        if lease is None:
            # grace window: a sibling writes its lease right AFTER winning
            # the claim rename, so a leaseless-but-fresh claim may be a
            # live claim mid-stamp — only a leaseless claim that has SAT
            # there is an orphan (pre-lease daemons also land here)
            try:
                age = _clk.now() - os.path.getmtime(
                    self._job_path(CLAIMED, job_id)
                )
            except OSError:
                return True  # claim vanished under us: nothing to hold
            return age > 10.0 + self._skew(skew_s)
        if lease_ttl is None:
            lease_ttl = float(
                os.environ.get("KSPEC_CLAIM_LEASE_TTL", DEFAULT_LEASE_TTL)
            )
        # the lease timestamp may come from ANOTHER host's clock: widen
        # the expiry window by the skew allowance so a live claimer whose
        # clock runs a few seconds behind ours is never stolen from
        age = _clk.now() - float(lease.get("lease_unix", 0.0))
        if age >= lease_ttl + self._skew(skew_s):
            # expiry dominates even a live pid: the busy-heartbeat loop
            # renews every few seconds, so an expired lease means the
            # claimer is wedged beyond rescue (or a foreign-host daemon
            # died and its pid merely ALIASES a live local one)
            return True
        pid = int(lease.get("pid", -1))
        if pid == os.getpid():
            # ours ONLY if the token matches too: a recycled pid from a
            # dead predecessor must read as the orphan it is, or its
            # claims sit stuck until the TTL instead of requeueing at
            # our own startup janitor
            return lease.get("token") != _PROC_TOKEN
        return not _pid_alive(pid)

    def requeue_orphans(self, lease_ttl: Optional[float] = None,
                        skew_s: Optional[float] = None) -> list:
        """Startup janitor: claims whose LEASE is orphaned (dead pid /
        expired / missing — see :meth:`lease_orphaned`) go back to
        pending/ (idempotent jobs; nothing commits before the verdict).
        A live sibling daemon's leased claims are left untouched — the
        prerequisite for two daemons sharing one queue directory.
        ``skew_s`` threads an explicit allowance through every expiry
        decision of this sweep (see :meth:`lease_orphaned`)."""
        moved = []
        self._adopt_stale_requeues()
        # only forward skew_s when explicitly given: tests (and older
        # subclasses) stub lease_orphaned with the two-arg signature, and
        # the default sweep must stay call-compatible with them
        skw = {} if skew_s is None else {"skew_s": skew_s}
        for job_id in self._list(CLAIMED):
            if not self.lease_orphaned(job_id, lease_ttl=lease_ttl, **skw):
                continue
            lease = self.read_lease(job_id)
            claimed_path = self._job_path(CLAIMED, job_id)
            # TAKEOVER PROTOCOL (race-free with concurrent janitors +
            # re-claims): (1) atomically move the claim to a janitor-
            # private name — exactly one janitor can win this rename, and
            # the job is never visible in pending/ until step (4); (2)
            # RE-VERIFY the orphan decision on the lease as it is NOW —
            # between our check and the rename a sibling janitor may have
            # requeued the job and a live daemon re-claimed it (fresh
            # lease at the same path), in which case our rename just
            # grabbed LIVE work and must be undone; (3) stamp the
            # takeover attribution into the private copy (no concurrent
            # reader exists); (4) publish into pending/.
            private = claimed_path + f".requeue-{os.getpid()}"
            try:
                _dio.rename(claimed_path, private)
            except OSError:
                continue  # a sibling janitor (or a finishing daemon) won
            if not self.lease_orphaned(job_id, lease_ttl=lease_ttl, **skw):
                # stale decision: a live daemon re-claimed between our
                # check and the rename — give its claim file back
                try:
                    _dio.rename(private, claimed_path)
                except OSError:
                    pass
                continue
            spec = None
            takeover = {
                "from_pid": lease.get("pid") if lease else None,
                "by_pid": os.getpid(),
                "reason": (
                    "no-lease" if lease is None else "lease-expired"
                    if _clk.now() - float(lease.get("lease_unix", 0))
                    >= float(
                        lease_ttl
                        if lease_ttl is not None
                        else os.environ.get(
                            "KSPEC_CLAIM_LEASE_TTL",
                            DEFAULT_LEASE_TTL,
                        )
                    ) + self._skew(skew_s)
                    else "dead-pid"
                ),
                "at": round(_clk.now(), 3),
            }
            try:
                with open(private) as fh:
                    spec = json.load(fh)
                spec.setdefault("takeovers", []).append(takeover)
                atomic_write_json(private, spec)
            except (OSError, ValueError):
                pass  # attribution is best-effort; the requeue is not
            try:
                _dio.rename(private, self._job_path(PENDING, job_id))
                self._drop_lease(job_id)
                moved.append(job_id)
            except OSError:
                pass
            else:
                # crash adoption is an ANNOTATION on the job's one trace,
                # not a new trace: the context rode inside the spec
                fleettrace.emit_event(
                    self.dir,
                    spec.get("trace") if isinstance(spec, dict) else None,
                    "queue-requeue", job_id=job_id,
                    from_pid=takeover["from_pid"],
                    by_pid=takeover["by_pid"],
                    reason=takeover["reason"],
                )
        # dangling leases (spec vanished mid-claim, or retired without
        # cleanup by an older daemon) are dead weight: sweep them
        try:
            for name in os.listdir(os.path.join(self.queue_dir, CLAIMED)):
                if not name.endswith(".lease"):
                    continue
                jid = name[: -len(".lease")]
                if not os.path.isfile(self._job_path(CLAIMED, jid)):
                    self._drop_lease(jid)
        except OSError:
            pass
        return moved

    def _adopt_stale_requeues(self) -> None:
        """Recovery sweep for the takeover protocol: a janitor that died
        between the private rename and the pending publish leaves
        `claimed/<id>.json.requeue-<pid>`.  A later janitor adopts it —
        once the stamping pid is dead — by finishing the publish (the
        spec already carries the takeover stamp, or is still valid
        without one)."""
        try:
            names = os.listdir(os.path.join(self.queue_dir, CLAIMED))
        except OSError:
            return
        for name in names:
            if ".json.requeue-" not in name:
                continue
            job_id, _, pid_s = name.rpartition(".requeue-")
            job_id = job_id[: -len(".json")]
            try:
                if _pid_alive(int(pid_s)):
                    continue  # that janitor is mid-protocol: leave it
            except ValueError:
                continue
            try:
                _dio.rename(
                    os.path.join(self.queue_dir, CLAIMED, name),
                    self._job_path(PENDING, job_id),
                )
                self._drop_lease(job_id)
            except OSError:
                pass

    def finish(self, job_id: str, verdict: Optional[dict],
               error: Optional[str] = None) -> None:
        """Publish the verdict (atomic) THEN retire the claim: a crash
        between the two leaves a claimed job with a verdict, which the
        janitor requeues and the daemon then short-circuits on the
        existing result (execute-at-most-once for the visible verdict)."""
        if verdict is None:
            from .verdict import error_verdict

            verdict = error_verdict(error or "unknown failure")
            verdict["job_id"] = job_id
        atomic_write_json(self.result_path(job_id), verdict)
        claimed = self._job_path(CLAIMED, job_id)
        if os.path.isfile(claimed):
            try:
                _dio.rename(claimed, self._job_path(DONE, job_id))
            except OSError:
                pass
        self._drop_lease(job_id)
