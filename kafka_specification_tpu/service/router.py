"""Cross-host router: one submit surface over N per-host service dirs.

PR 14's fleet made ONE host survivable (supervised daemons, lease
takeover, chain-verified state cache).  This module is the layer above
it: a jax-free router that fronts N per-host queue directories — real
hosts, or isolated container "hosts" that share nothing but the
federated state-cache namespace — and gives tenants a single
submit/status/result surface with:

- **health-aware placement**: each host's daemons already append
  heartbeat records (``service/heartbeat*.jsonl``); the router reads the
  newest record's ``unix`` stamp (through ``retry_transient`` — the
  heartbeat files live on the same flaky network mounts as everything
  else) and treats a host as DEAD once that stamp is stale past
  ``dead_after_s`` plus the ``KSPEC_CLOCK_SKEW`` allowance.  Timestamps
  written by another host's clock are never compared raw.
- **depth-aware placement**: among routable hosts, submits go to the
  smallest backlog (pending + claimed), index-stable on ties.
- **per-tenant admission**: the router dir carries its own
  ``tenants.json`` (resilience.resources budget machinery); a tenant's
  ``max_pending`` is enforced against the SUM of its pending jobs across
  every fronted host — the fleet-wide cap the per-host check cannot see.
- **dead-host re-route, exactly once**: a sweep over a dead host first
  runs the host queue's own janitor (``requeue_orphans`` — expired /
  dead-pid leases return to pending THROUGH the existing takeover
  protocol, attribution stamps included), then moves each pending job to
  a survivor via a rename-private / stamp / publish / unlink protocol
  that mirrors the janitor's: exactly one router wins the private
  rename, the intended target is durably recorded INSIDE the private
  file before the copy, and a router that dies mid-protocol is adopted
  by a later sweep (re-published if the copy never landed, retired if it
  did).  A job whose verdict already exists is never re-routed — a
  published verdict is terminal wherever its spec sits.

Death is only ever declared on evidence: a host that has NEVER
heartbeat-ed is "unseen" (its daemons may still be booting — jobs queue
and wait), not dead.  The host-state taxonomy (`classify_host`) mirrors
resilience.supervisor.classify_exit: ok | unseen | dead.

State under the router dir::

    <router>/router.json            {schema, hosts, dead_after_s, ...}
    <router>/routes/<job_id>.json   placement record + reroute history
    <router>/tenants.json           fleet-wide tenant budgets
    <router>/events.jsonl           route/sweep/reroute events
    <router>/router-heartbeat.jsonl the router's own liveness trail

Must stay jax-free: the router runs on a box that never pays the jax
import, same contract as the queue clients it fronts.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from .. import durable_io as _dio
from ..obs import fleettrace
from ..obs.atomicio import atomic_write_json
from ..resilience.heartbeat import append_jsonl, heartbeat_record
from ..utils import clock as _clk
from ..resilience.resources import budget_for_tenant, load_tenant_budgets
from .queue import (
    CLAIMED,
    DONE,
    PENDING,
    JobQueue,
    _pid_alive,
    clock_skew_s,
    retry_transient,
)

ROUTER_SCHEMA = "kspec-router/1"

#: default seconds of heartbeat silence before a host reads as dead
#: (plus the KSPEC_CLOCK_SKEW allowance; must exceed the daemons' idle
#: heartbeat cadence with margin, or an idle fleet reads as a dead one)
DEFAULT_DEAD_AFTER_S = 30.0

#: sticky batch-affinity release threshold: a module stays on its
#: affinity host until that host's backlog exceeds the least-loaded
#: routable host's by this many jobs.  Keeping a module co-located lets
#: the daemon claim one large batched group (one envelope exploration,
#: one compile-cache entry) instead of paying the group's fixed cost
#: per host — the Kafka sticky-partitioner economics, applied to
#: placement; the slack bounds the imbalance a hot module can cause
AFFINITY_SLACK_JOBS = 64


class AdmissionDenied(RuntimeError):
    """Fleet-wide tenant budget exceeded (`cli submit --router` exit 2)."""

    def __init__(self, tenant: str, cap: int, pending: int):
        self.tenant, self.cap, self.pending = tenant, cap, pending
        super().__init__(
            f"tenant {tenant!r} at max_pending cap {cap} "
            f"({pending} pending across the fleet)"
        )


def classify_host(seen: bool, alive: bool) -> str:
    """Host-state taxonomy, the cross-host row of the failure table
    (docs/resilience.md) — mirrors resilience.supervisor.classify_exit:

    - ``ok``: fresh heartbeats — routable, jobs flow.
    - ``unseen``: no heartbeat EVER — routable (daemons may be booting;
      death needs evidence), deprioritized behind live hosts.
    - ``dead``: heartbeats went stale past the skew-tolerant threshold —
      not routable; pending re-routed, claimed taken over at lease
      expiry."""
    if not seen:
        return "unseen"
    return "ok" if alive else "dead"


class Router:
    """The jax-free cross-host front.  Construct with ``hosts`` to
    create/refresh the router dir, or without to open an existing one."""

    def __init__(self, router_dir: str, hosts: Optional[list] = None,
                 dead_after_s: Optional[float] = None,
                 skew_s: Optional[float] = None):
        # explicit skew pin (None = the KSPEC_CLOCK_SKEW env default):
        # threads through every heartbeat-freshness decision AND down
        # into the fronted queues' lease-expiry math — the harness-safe
        # alternative to mutating the process-global env var
        self.skew_s = skew_s
        self.dir = os.path.normpath(router_dir)
        self.routes_dir = os.path.join(self.dir, "routes")
        self.config_path = os.path.join(self.dir, "router.json")
        self.tenants_path = os.path.join(self.dir, "tenants.json")
        self.events_path = os.path.join(self.dir, "events.jsonl")
        self.heartbeat_path = os.path.join(
            self.dir, "router-heartbeat.jsonl"
        )
        cfg = self._load_config()
        if hosts is None:
            if cfg is None:
                raise FileNotFoundError(
                    f"{self.config_path}: not a router dir (create one "
                    "with `cli route <dir> --hosts <svc0> <svc1> ...`)"
                )
            hosts = cfg["hosts"]
        if dead_after_s is None:
            dead_after_s = (
                float(cfg["dead_after_s"]) if cfg else DEFAULT_DEAD_AFTER_S
            )
        self.hosts = [os.path.normpath(h) for h in hosts]
        if not self.hosts:
            raise ValueError("router needs at least one host service dir")
        self.dead_after_s = float(dead_after_s)
        # module -> host sticky-batching hint (in-memory: a routing
        # efficiency, not a correctness property — concurrent routers
        # converge per-router, and a restart just re-sticks)
        self._affinity = {}
        os.makedirs(self.routes_dir, exist_ok=True)
        # startup-janitor parity (crashcheck `router` scenario): a route
        # writer killed mid-atomic-write leaves a nonce'd `.tmp` here;
        # routes are multi-writer (every router instance), so grace-aged
        _dio.sweep_tmp(self.routes_dir, min_age_s=_dio.TMP_SWEEP_GRACE_S)
        self.queues = [JobQueue(h, skew_s=skew_s) for h in self.hosts]
        if cfg is None or cfg.get("hosts") != self.hosts or (
            float(cfg.get("dead_after_s", -1.0)) != self.dead_after_s
        ):
            atomic_write_json(
                self.config_path,
                {
                    "schema": ROUTER_SCHEMA,
                    "hosts": self.hosts,
                    "dead_after_s": self.dead_after_s,
                    "created_unix": (
                        cfg.get("created_unix") if cfg
                        else round(_clk.now(), 3)
                    ),
                },
            )

    def _load_config(self) -> Optional[dict]:
        try:
            with open(self.config_path) as fh:
                cfg = json.load(fh)
        except (OSError, ValueError):
            return None
        if cfg.get("schema") != ROUTER_SCHEMA:
            raise ValueError(
                f"{self.config_path}: schema {cfg.get('schema')!r} is not "
                f"{ROUTER_SCHEMA} (version skew: upgrade the router CLI "
                "or recreate the dir)"
            )
        return cfg

    # --- telemetry --------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        try:
            append_jsonl(
                self.events_path,
                heartbeat_record("router", event=kind, **fields),
            )
        except OSError:
            pass  # telemetry must never take the router down

    # --- health -----------------------------------------------------------
    def _newest_heartbeat_unix(self, host: int) -> Optional[float]:
        """Newest heartbeat `unix` stamp across the host's daemons, read
        through retry_transient; None = no heartbeat has ever landed.
        The JSON `unix` field, not file mtime, is what the skew fault
        (skew@host<i>) shifts and the skew allowance defends — mtime
        would silently use the FILESYSTEM's clock and dodge the drill."""
        svc = self.queues[host].service_dir

        def scan():
            newest = None
            try:
                names = os.listdir(svc)
            except FileNotFoundError:
                return None
            for name in names:
                if not (
                    name.startswith("heartbeat")
                    and name.endswith(".jsonl")
                ):
                    continue
                path = os.path.join(svc, name)
                try:
                    with open(path, "rb") as fh:
                        fh.seek(0, os.SEEK_END)
                        size = fh.tell()
                        fh.seek(max(0, size - 8192))
                        lines = fh.read().splitlines()
                except FileNotFoundError:
                    continue
                stamp = None
                for ln in reversed(lines):
                    try:
                        stamp = float(json.loads(ln)["unix"])
                        break
                    except (ValueError, KeyError, TypeError):
                        continue  # torn tail line: try the one before
                if stamp is None:
                    continue
                newest = stamp if newest is None else max(newest, stamp)
            return newest

        try:
            return retry_transient(scan)
        except OSError:
            return None

    def host_health(self, host: int) -> dict:
        """One host's routable-state snapshot (see `classify_host`)."""
        q = self.queues[host]
        hb = self._newest_heartbeat_unix(host)
        now = _clk.now()
        seen = hb is not None
        # the heartbeat stamp came from ANOTHER host's clock: the
        # staleness window widens by the skew allowance, so a live host
        # running a few seconds behind is never declared dead
        alive = bool(
            seen
            and (now - hb) <= self.dead_after_s + clock_skew_s(self.skew_s)
        )
        return {
            "host": host,
            "dir": q.dir,
            "state": classify_host(seen, alive),
            "hb_age_s": round(now - hb, 3) if seen else None,
            "pending": q.pending_count(),
            "claimed": q.claimed_count(),
        }

    def healths(self) -> list:
        return [self.host_health(i) for i in range(len(self.queues))]

    # --- placement --------------------------------------------------------
    def _choose_host(self, healths: list, module: str = None) -> int:
        """Placement among routable hosts: live hosts first, never-seen
        hosts (booting daemons) as the fallback, and only when EVERY
        host is dead does placement fall back to all of them — a queued
        job on a dead host beats a rejected submit, and the sweep
        re-routes it the moment anything comes back.

        Within the pool: same-module submits STICK to their module's
        host (sticky batch affinity — the daemons batch same-shape
        pending jobs into one engine group, so co-locating a module
        buys one group run instead of one per host), released to the
        least-loaded host when the affinity host falls
        AFFINITY_SLACK_JOBS behind it or leaves the pool."""
        for pool_state in (("ok",), ("unseen",), ("ok", "unseen", "dead")):
            pool = [h for h in healths if h["state"] in pool_state]
            if not pool:
                continue
            least = min(
                pool,
                key=lambda h: (h["pending"] + h["claimed"], h["host"]),
            )
            sticky = self._affinity.get(module)
            if sticky is not None:
                for h in pool:
                    if h["host"] != sticky:
                        continue
                    lag = (h["pending"] + h["claimed"]) - (
                        least["pending"] + least["claimed"]
                    )
                    if lag <= AFFINITY_SLACK_JOBS:
                        return sticky
                    break  # too far behind: re-stick below
            if module is not None:
                self._affinity[module] = least["host"]
            return least["host"]
        raise ValueError("router has no hosts")  # unreachable: len >= 1

    def _check_admission(self, tenant: str) -> None:
        try:
            budgets = load_tenant_budgets(self.tenants_path)
        except ValueError:
            raise  # a malformed governance config must fail the submit
        budget = budget_for_tenant(budgets, tenant)
        cap = budget.max_pending if budget is not None else None
        if cap is None:
            return
        total = 0
        for q in self.queues:
            total += q.pending_for_tenant(tenant, stop_at=cap - total)
            if total >= cap:
                raise AdmissionDenied(tenant, cap, total)

    def submit(self, cfg_text: str, module: str, tenant: str = "default",
               host: Optional[int] = None, **kw) -> dict:
        """Route one submit: fleet-wide admission, health + depth
        placement (or an explicit ``host`` pin — the operator escape
        hatch), then the chosen host queue's own atomic submit.  Returns
        the published spec with ``spec['host']`` set."""
        t_place = fleettrace.now()
        self._check_admission(tenant)
        pinned = host is not None
        if host is None:
            host = self._choose_host(self.healths(), module=module)
        elif not (0 <= host < len(self.queues)):
            raise ValueError(
                f"host {host} out of range (0..{len(self.queues) - 1})"
            )
        spec = self.queues[host].submit(
            cfg_text, module, tenant=tenant, **kw
        )
        self._write_route(spec["job_id"], host, why="submit")
        self._event(
            "route-submit", job=spec["job_id"], host=host, tenant=tenant,
        )
        # placement span lands under the ROUTER dir: the router is its
        # own clock domain, and `cli trace` unions it with the hosts'
        fleettrace.emit_span(
            self.dir, spec.get("trace"), "route-place",
            t_place, fleettrace.now(), job_id=spec["job_id"],
            to_host=host, why="pinned" if pinned else "health",
        )
        spec["host"] = host
        return spec

    # --- route records ----------------------------------------------------
    def _route_path(self, job_id: str) -> str:
        return os.path.join(self.routes_dir, f"{job_id}.json")

    def _write_route(self, job_id: str, host: int, why: str) -> None:
        rec = self.read_route(job_id) or {
            "schema": ROUTER_SCHEMA,
            "job_id": job_id,
            "history": [],
        }
        rec["host"] = host
        rec["dir"] = self.hosts[host]
        rec["history"].append(
            {"host": host, "why": why, "at": round(_clk.now(), 3)}
        )
        try:
            atomic_write_json(
                self._route_path(job_id), rec,
                # route records race ACROSS router instances to the same
                # final path: a shared `.tmp` name would let one racer
                # promote/unlink the sibling's half-written tmp (the
                # PR 16 torn-promote precedent) — privatise it
                tmp_nonce=f"{os.getpid():x}-{os.urandom(4).hex()}",
            )
        except OSError:
            pass  # resolution falls back to the all-hosts scan

    def read_route(self, job_id: str) -> Optional[dict]:
        try:
            with open(self._route_path(job_id)) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def locate(self, job_id: str) -> Optional[int]:
        """Best-effort host index for a job: the route record when it
        exists, else a scan of every host (a job submitted around the
        router, or a record lost to a full disk, still resolves)."""
        rec = self.read_route(job_id)
        if rec is not None:
            host = rec.get("host")
            if isinstance(host, int) and 0 <= host < len(self.queues):
                return host
        for i, q in enumerate(self.queues):
            if q.result(job_id) is not None:
                return i
            if any(
                q._isfile(q._job_path(st, job_id))
                for st in (PENDING, CLAIMED, DONE)
            ):
                return i
        return None

    def status(self, job_id: str) -> dict:
        host = self.locate(job_id)
        if host is None:
            return {"job_id": job_id, "state": "unknown", "host": None}
        out = self.queues[host].status(job_id)
        out["host"] = host
        return out

    def result(self, job_id: str) -> Optional[dict]:
        """The verdict, wherever it landed.  The routed host is checked
        first, but a verdict is accepted from ANY host: a re-route that
        lost its record update still resolves (verdicts are
        deterministic and published exactly once, so whichever dir holds
        it is the answer)."""
        host = self.locate(job_id)
        if host is not None:
            rec = self.queues[host].result(job_id)
            if rec is not None:
                return rec
        for q in self.queues:
            rec = q.result(job_id)
            if rec is not None:
                return rec
        return None

    def wait_result(self, job_id: str, timeout: float = 120.0,
                    poll: float = 0.05) -> Optional[dict]:
        deadline = _clk.monotonic() + timeout
        while True:
            rec = self.result(job_id)
            if rec is not None:
                return rec
            if _clk.monotonic() >= deadline:
                return None
            _clk.sleep(poll)

    def overview(self) -> dict:
        try:
            routes = len(os.listdir(self.routes_dir))
        except OSError:
            routes = 0
        return {
            "dir": self.dir,
            "dead_after_s": self.dead_after_s,
            "clock_skew_s": clock_skew_s(self.skew_s),
            "routes": routes,
            "hosts": self.healths(),
        }

    # --- the sweep (health scan + dead-host recovery) ---------------------
    def sweep(self) -> dict:
        """One router pass: adopt any dead router's half-done re-routes,
        then for every DEAD host run its queue's own janitor (leased
        claims return through the takeover protocol at lease expiry) and
        re-route its pending jobs to survivors.  Idempotent; safe to run
        from several routers at once (every move is an atomic rename
        exactly one actor wins)."""
        self._adopt_stale_reroutes()
        healths = self.healths()
        survivors = [h["host"] for h in healths if h["state"] == "ok"]
        out = {"hosts": healths, "takeover": {}, "rerouted": {}}
        for h in healths:
            if h["state"] != "dead":
                continue
            q = self.queues[h["host"]]
            try:
                moved = q.requeue_orphans(skew_s=self.skew_s)
            except OSError:
                moved = []
            if moved:
                out["takeover"][h["host"]] = sorted(moved)
                self._event(
                    "host-takeover", host=h["host"], jobs=sorted(moved),
                )
            if survivors:
                rerouted = self._reroute_pending(h["host"], survivors)
                if rerouted:
                    out["rerouted"][h["host"]] = rerouted
            elif q.pending_count():
                self._event("reroute-stranded", host=h["host"])
        try:
            append_jsonl(
                self.heartbeat_path,
                heartbeat_record(
                    "router-heartbeat",
                    pid=os.getpid(),
                    hosts={
                        str(h["host"]): h["state"] for h in healths
                    },
                ),
            )
        except OSError:
            pass
        return out

    def _reroute_pending(self, dead: int, survivors: list) -> list:
        """Move a dead host's pending jobs to survivors, exactly once.

        Per job: (1) atomically rename the pending spec to a
        router-private name — one actor wins; (2) stamp the re-route
        attribution INCLUDING the intended target into the private file
        (durable intent: adoption after a router death knows where the
        copy was headed); (3) publish into the target's pending/ (plus
        its tenant admission marker); (4) unlink the private file and
        update the route record.  A job whose verdict already published
        is retired in place, never re-run."""
        q = self.queues[dead]
        depths = {
            s: self.queues[s].pending_count()
            + self.queues[s].claimed_count()
            for s in survivors
        }
        moved = []
        for job_id in sorted(q._list(PENDING)):
            if q.result(job_id) is not None:
                # terminal truth already on disk (daemon died between
                # verdict write and claim retire, then got requeued):
                # retire the spec so nobody ever re-runs it
                try:
                    _dio.rename(
                        q._job_path(PENDING, job_id),
                        q._job_path(DONE, job_id),
                    )
                except OSError:
                    pass
                continue
            target = min(survivors, key=lambda s: (depths[s], s))
            src = q._job_path(PENDING, job_id)
            private = src + f".reroute-{os.getpid()}"
            try:
                _dio.rename(src, private)
            except OSError:
                continue  # claimed / another router won: not ours
            try:
                with open(private) as fh:
                    spec = json.load(fh)
                spec.setdefault("reroutes", []).append(
                    {
                        "from_host": dead,
                        "to_host": target,
                        "by_pid": os.getpid(),
                        "reason": "host-dead",
                        "at": round(_clk.now(), 3),
                    }
                )
                atomic_write_json(private, spec)
                tq = self.queues[target]
                tdir = tq._tenant_dir(spec.get("tenant", "default"))
                os.makedirs(tdir, exist_ok=True)
                _dio.write_text(os.path.join(tdir, job_id), "")
                atomic_write_json(tq._job_path(PENDING, job_id), spec)
            except (OSError, ValueError):
                # cannot complete the move: put the job back where one
                # actor-at-a-time recovery can retry it
                try:
                    _dio.rename(private, src)
                except OSError:
                    pass
                continue
            try:
                _dio.unlink(private)
            except OSError:
                pass  # adoption retires it once this pid is gone
            self._write_route(job_id, target, why="reroute:host-dead")
            self._event(
                "route-reroute", job=job_id, from_host=dead,
                to_host=target,
            )
            # the re-route is a typed annotation on the job's ONE trace
            # (the context rode inside the spec), never a gap in it
            fleettrace.emit_event(
                self.dir, spec.get("trace"), "route-reroute",
                job_id=job_id, from_host=dead, to_host=target,
                reason="host-dead",
            )
            depths[target] += 1
            moved.append(job_id)
        return moved

    def _adopt_stale_reroutes(self) -> None:
        """Recovery sweep for the re-route protocol: a router that died
        mid-move leaves `pending/<id>.json.reroute-<pid>`.  Once that
        pid is dead, the stamped intent decides: if the job already
        exists at the recorded target (the copy landed), the private
        file is retired; otherwise it returns to pending for the next
        sweep to move — either way, exactly one runnable copy."""
        for q in self.queues:
            try:
                names = os.listdir(os.path.join(q.queue_dir, PENDING))
            except OSError:
                continue
            for name in names:
                if ".json.reroute-" not in name:
                    continue
                job_id, _, pid_s = name.rpartition(".reroute-")
                job_id = job_id[: -len(".json")]
                try:
                    if _pid_alive(int(pid_s)):
                        continue  # that router is mid-protocol
                except ValueError:
                    continue
                path = os.path.join(q.queue_dir, PENDING, name)
                target = None
                try:
                    with open(path) as fh:
                        stamps = json.load(fh).get("reroutes") or []
                    if stamps:
                        target = stamps[-1].get("to_host")
                except (OSError, ValueError):
                    pass
                landed = False
                if isinstance(target, int) and 0 <= target < len(
                    self.queues
                ):
                    tq = self.queues[target]
                    landed = tq.result(job_id) is not None or any(
                        os.path.isfile(tq._job_path(st, job_id))
                        for st in (PENDING, CLAIMED, DONE)
                    )
                try:
                    if landed:
                        _dio.unlink(path)
                    else:
                        _dio.rename(path, q._job_path(PENDING, job_id))
                except OSError:
                    pass

    def serve(self, poll_s: float = 1.0,
              max_sweeps: Optional[int] = None) -> None:
        """The blocking router loop (``cli route``): sweep, sleep,
        repeat.  `max_sweeps` bounds it for tests and `--once`."""
        n = 0
        self._stop = False
        while not getattr(self, "_stop", False):
            self.sweep()
            n += 1
            if max_sweeps is not None and n >= max_sweeps:
                return
            _clk.sleep(poll_s)

    def request_stop(self) -> None:
        self._stop = True
