"""Level-pipeline registry — the single jax-free source of truth for
pipeline names the CLI parser, ``cli pipelines --list`` and the engine's
``resolve_pipeline`` all validate against (the FAULT_REGISTRY pattern,
resilience/faults.py: one registry, no silently-diverging copies).

``engine/pipeline.py`` imports :data:`PIPELINE_REGISTRY` and re-exports
``PIPELINES``/``resolve_pipeline`` for its callers; keep this module
importable WITHOUT jax (the jax-free CLI subcommands and tests list
pipelines on boxes with no accelerator stack).
"""

from __future__ import annotations

import os

PIPELINE_ENV = "KSPEC_PIPELINE"

#: the two engines a pipeline selection can land on (`--sharded` picks
#: the second) — keys of every registry entry's per-engine support matrix
ENGINES = ("single-device", "sharded")

#: the visited backends a pipeline can be asked to serve — keys of every
#: registry entry's per-BACKEND support matrix ("backends").  Each cell
#: states whether the pipeline serves that backend natively or degrades
#: (and to what), so `stats['device']['fallback']` reasons and the
#: `cli pipelines` dump both read from ONE jax-free source instead of
#: strings scattered across the engines.
BACKENDS = ("device", "device-hash", "host")

#: name -> registry entry; insertion order is the display order and the
#: degradation ladder reads right-to-left (device -> fused -> legacy).
#: Each entry's "engines" matrix states, PER ENGINE, whether the name
#: selects a distinct implementation there and why/when the combination
#: degrades — the sharded engine used to silently ignore --pipeline;
#: now every (pipeline, engine) cell is documented and queryable
#: (`cli pipelines --list/--json`).
PIPELINE_REGISTRY = {
    "device": {
        "launches": "<=2 successor launches per LEVEL",
        "description": (
            "device-resident level pipeline: a bounded lax.while_loop "
            "processes every gated chunk of a BFS level in ONE dispatched "
            "program — guard-matrix expansion, in-jit segmented "
            "compaction, fingerprints, intra-level dedup against a "
            "device-resident level-new sorted set, invariant/deadlock "
            "verdicts and (device backend) the per-level digest folds "
            "all fused on-device.  Sorted-set backend: the O(capacity) "
            "visited merge runs once per level instead of once per "
            "chunk.  Host/disk-tier backends: the visited probe is "
            "DEFERRED to one batched host call per level (host syncs "
            "O(1)/level instead of O(chunks)).  Requires analyzer-"
            "proven per-field value hulls; anything else degrades to "
            "'fused'"
        ),
        "fallback": "fused",
        "backends": {
            "device": {
                "supported": True,
                "detail": (
                    "in-jit dual-probe dedup (read-only visited "
                    "shard + level-new set), ONE O(capacity) rank-"
                    "scatter merge per level, in-jit digest folds"
                ),
            },
            "host": {
                "supported": True,
                "detail": (
                    "deferred once-per-level batched host dedup "
                    "— intra-level novelty on the device level-new set, "
                    "the level's novel candidates probed/inserted "
                    "against the C-arena FpSet (or the disk tier's "
                    "bloom/interval-gated sorted runs) in ONE chunk-"
                    "major batch per level; serial winner rule "
                    "preserved, so results stay bit-identical to "
                    "'legacy'"
                ),
            },
            "device-hash": {
                "supported": False,
                "detail": (
                    "the open-addressing HBM table mutates in place per "
                    "probe (no read-only in-loop form), so a whole-"
                    "level program has no exact replay on overflow — "
                    "runs the fused per-chunk ladder instead (identical "
                    "results)"
                ),
            },
        },
        "engines": {
            "single-device": {
                "supported": True,
                "detail": (
                    "one lax.while_loop program per level, <=2 successor "
                    "launches/level, on the device AND host/disk-tier "
                    "visited backends (host: deferred once-per-level "
                    "batched dedup); degrades to 'fused' per-chunk on "
                    "the device-hash backend, sub-gate chunks, shadow "
                    "re-execution, unproven field hulls, or compile "
                    "failure"
                ),
            },
            "sharded": {
                "supported": True,
                "detail": (
                    "per-shard one-dispatch level programs: each shard "
                    "runs a whole level's gated chunks — expansion, the "
                    "per-chunk all_to_all/all_gather exchange (+ the "
                    "compression codec), dual-probe dedup against a "
                    "read-only visited shard + a per-shard level-new "
                    "set, in-jit digest folds — inside ONE dispatched "
                    "program: O(1) collective-bearing launches per "
                    "level per shard, the O(capacity) visited merge "
                    "(device backend) or ONE batched per-shard host "
                    "FpSet probe (host/disk-tier backends) once per "
                    "level per shard.  Requires proven field hulls and "
                    "a sorted-dedup backend; device-hash degrades to "
                    "the per-chunk sharded step "
                    "(sharded-device -> per-chunk -> legacy ladder)"
                ),
            },
        },
    },
    "fused": {
        "launches": "2 successor launches per chunk",
        "description": (
            "successor mega-kernels (the default): one batched "
            "guard-predicate-matrix launch over the (frontier x choice) "
            "lattice, C-speed host compaction into a shared data-driven-"
            "width buffer, one update-skeleton launch.  Compile/alloc "
            "failure degrades the run to 'legacy'"
        ),
        "fallback": "legacy",
        "backends": {
            "device": {
                "supported": True,
                "detail": "in-jit sort/probe/rank-merge per chunk",
            },
            "host": {
                "supported": True,
                "detail": (
                    "per-chunk squeeze+fingerprint on device, "
                    "all dedup on the host FpSet / disk tier (one host "
                    "probe per chunk — the O(chunks)-sync shape the "
                    "'device' pipeline's deferred probe collapses)"
                ),
            },
            "device-hash": {
                "supported": True,
                "detail": (
                    "per-chunk insert-or-find on the HBM "
                    "open-addressing table"
                ),
            },
        },
        "engines": {
            "single-device": {
                "supported": True,
                "detail": "the default single-device path",
            },
            "sharded": {
                "supported": False,
                "detail": (
                    "runs the per-chunk sharded step: expansion + "
                    "exchange are already ONE monolithic jitted program "
                    "per chunk in this engine, so there is no separate "
                    "fused variant to select — the name degrades to the "
                    "per-chunk path (identical results)"
                ),
            },
        },
    },
    "legacy": {
        "launches": "one successor-kernel pass per action per chunk",
        "description": (
            "the historical per-action monolithic step with "
            "AdaptiveCompact two-phase compaction and the overflow-retry "
            "escalation ladder — the bit-identity oracle every other "
            "pipeline is pinned against"
        ),
        "fallback": None,
        "backends": {
            "device": {
                "supported": True,
                "detail": "the historical in-step sorted dedup",
            },
            "host": {
                "supported": True,
                "detail": (
                    "per-chunk host FpSet insert (the oracle "
                    "path for the deferred-probe bit-identity pins)"
                ),
            },
            "device-hash": {
                "supported": True,
                "detail": "per-chunk HBM hash-table insert",
            },
        },
        "engines": {
            "single-device": {
                "supported": True,
                "detail": "the single-device bit-identity oracle",
            },
            "sharded": {
                "supported": True,
                "detail": (
                    "the per-chunk monolithic sharded step — this "
                    "engine's bit-identity oracle path (what the "
                    "sharded engine always ran before the device "
                    "variant existed)"
                ),
            },
        },
    },
}

DEFAULT_PIPELINE = "fused"


def backend_support(name: str, backend: str) -> dict:
    """The (pipeline, backend) support cell: {"supported": bool,
    "detail": str}.  `backend` must be one of :data:`BACKENDS`.  The
    detail string of an unsupported cell is the ONE fallback-reason
    text the engines stamp into ``stats['device']['fallback']`` and the
    ``pipeline-fallback`` event — so the reason an operator sees names
    the backend and is identical to what ``cli pipelines`` documents."""
    if backend not in BACKENDS:
        raise ValueError(
            f"unknown visited backend {backend!r} (expected one of "
            f"{BACKENDS})"
        )
    if name not in PIPELINE_REGISTRY:
        raise ValueError(
            f"unknown pipeline {name!r} (expected one of "
            f"{pipeline_names()})"
        )
    return PIPELINE_REGISTRY[name]["backends"][backend]


def backend_fallback_reason(name: str, backend: str):
    """None when `name` natively serves `backend`, else the human-
    readable (backend-naming) degradation reason."""
    cell = backend_support(name, backend)
    if cell["supported"]:
        return None
    return f"visited backend {backend!r}: {cell['detail']}"


def engine_support(name: str, engine: str) -> dict:
    """The (pipeline, engine) support cell: {"supported": bool,
    "detail": str}.  `engine` must be one of :data:`ENGINES`."""
    if engine not in ENGINES:
        raise ValueError(
            f"unknown engine {engine!r} (expected one of {ENGINES})"
        )
    if name not in PIPELINE_REGISTRY:
        raise ValueError(
            f"unknown pipeline {name!r} (expected one of "
            f"{pipeline_names()})"
        )
    return PIPELINE_REGISTRY[name]["engines"][engine]


def pipeline_names() -> tuple:
    return tuple(PIPELINE_REGISTRY)


def resolve_pipeline(name=None) -> str:
    """CLI/env resolution: explicit arg > $KSPEC_PIPELINE > the default.
    Unknown names are rejected loudly with the valid set named (typos
    must never silently fall back to a different implementation)."""
    n = name or os.environ.get(PIPELINE_ENV) or DEFAULT_PIPELINE
    if n not in PIPELINE_REGISTRY:
        raise ValueError(
            f"unknown pipeline {n!r} (expected one of "
            f"{pipeline_names()}; `cli pipelines --list` describes them)"
        )
    return n


def list_pipelines() -> list:
    """Registry dump for ``cli pipelines --list`` (jax-free)."""
    return [
        {"name": name, "default": name == DEFAULT_PIPELINE, **entry}
        for name, entry in PIPELINE_REGISTRY.items()
    ]
