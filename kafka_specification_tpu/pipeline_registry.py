"""Level-pipeline registry — the single jax-free source of truth for
pipeline names the CLI parser, ``cli pipelines --list`` and the engine's
``resolve_pipeline`` all validate against (the FAULT_REGISTRY pattern,
resilience/faults.py: one registry, no silently-diverging copies).

``engine/pipeline.py`` imports :data:`PIPELINE_REGISTRY` and re-exports
``PIPELINES``/``resolve_pipeline`` for its callers; keep this module
importable WITHOUT jax (the jax-free CLI subcommands and tests list
pipelines on boxes with no accelerator stack).
"""

from __future__ import annotations

import os

PIPELINE_ENV = "KSPEC_PIPELINE"

#: name -> registry entry; insertion order is the display order and the
#: degradation ladder reads right-to-left (device -> fused -> legacy)
PIPELINE_REGISTRY = {
    "device": {
        "launches": "<=2 successor launches per LEVEL",
        "description": (
            "device-resident level pipeline: a bounded lax.while_loop "
            "processes every gated chunk of a BFS level in ONE dispatched "
            "program — guard-matrix expansion, in-jit segmented "
            "compaction, fingerprints, dedup against the device-resident "
            "visited set, invariant/deadlock verdicts and the per-level "
            "digest folds all fused on-device; the visited merge runs "
            "once per level instead of once per chunk.  Requires the "
            "sorted-set device visited backend and analyzer-proven "
            "per-field value hulls; anything else degrades to 'fused'"
        ),
        "fallback": "fused",
    },
    "fused": {
        "launches": "2 successor launches per chunk",
        "description": (
            "successor mega-kernels (the default): one batched "
            "guard-predicate-matrix launch over the (frontier x choice) "
            "lattice, C-speed host compaction into a shared data-driven-"
            "width buffer, one update-skeleton launch.  Compile/alloc "
            "failure degrades the run to 'legacy'"
        ),
        "fallback": "legacy",
    },
    "legacy": {
        "launches": "one successor-kernel pass per action per chunk",
        "description": (
            "the historical per-action monolithic step with "
            "AdaptiveCompact two-phase compaction and the overflow-retry "
            "escalation ladder — the bit-identity oracle every other "
            "pipeline is pinned against"
        ),
        "fallback": None,
    },
}

DEFAULT_PIPELINE = "fused"


def pipeline_names() -> tuple:
    return tuple(PIPELINE_REGISTRY)


def resolve_pipeline(name=None) -> str:
    """CLI/env resolution: explicit arg > $KSPEC_PIPELINE > the default.
    Unknown names are rejected loudly with the valid set named (typos
    must never silently fall back to a different implementation)."""
    n = name or os.environ.get(PIPELINE_ENV) or DEFAULT_PIPELINE
    if n not in PIPELINE_REGISTRY:
        raise ValueError(
            f"unknown pipeline {n!r} (expected one of "
            f"{pipeline_names()}; `cli pipelines --list` describes them)"
        )
    return n


def list_pipelines() -> list:
    """Registry dump for ``cli pipelines --list`` (jax-free)."""
    return [
        {"name": name, "default": name == DEFAULT_PIPELINE, **entry}
        for name, entry in PIPELINE_REGISTRY.items()
    ]
