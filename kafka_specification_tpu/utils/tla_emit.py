"""Mechanical kernel emission from the TLA+ expression IR.

Closes the loop utils/tla_expr.py opens: given a parsed module, constant
valuations, and a tensor-encoding schema for each VARIABLE, this module

  1. extracts the action list from `Next` (quantifier prefixes become the
     static choice lattice; each disjunct becomes one action),
  2. normalizes each action body (inline operator applications and LET
     bindings, hoist update-dominating \\E quantifiers into the choice
     space, split conjuncts into guards vs primed assignments),
  3. evaluates guards/updates SYMBOLICALLY over jnp state tensors —
     producing exactly the `(state, choice) -> (enabled, next_state)`
     kernels the engine vmaps (models/base.Action), and
  4. evaluates the same IR CONCRETELY over Python values — an independent
     successor enumerator used to cross-check both the emitted kernels and
     the hand-written models.

Integer values carry static interval bounds (IVal) so quantifiers over
data-dependent ranges (e.g. `0 .. logs[r].endOffset - 1` in TypeOk,
FiniteReplicatedLog.tla:95) unroll to masked reductions with a static trip
count — the jit-compatibility requirement.

Scope: the full expression surface of the corpus — L1/L2
(Util/IdSequence/FiniteReplicatedLog) and L3/L4 (KafkaReplication and its
variants): INSTANCE ... WITH substitution (KafkaReplication.tla:77-84),
bitmask-encoded `SUBSET Replicas` state fields, the epoch-keyed
`leaderAndIsrRequests` message-set encoding (SURVEY.md §2.2), symbolic
CHOOSE (Util's Min/Max), set comprehensions (Kip101/Kip279 truncation
math), data-dependent existential domains (`\\E newLeader \\in
quorumState.isr`), and disjunctive action bodies (ControllerShrinkIsr's
three cases) via DNF splitting.  This retires SURVEY.md §2.5 row 1's
"hand-written kernels acceptable for v0" caveat: the kernels are emitted
mechanically from the reference text and cross-checked against the
hand-written models by exact per-level state-set equality (tests/).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np

from . import tla_expr as E
from .tla_frontend import TlaModule


# ------------------------------------------------------------------ schemas
@dataclass(frozen=True)
class SInt:
    """Integer leaf stored in state[field][<enclosing function indices>]."""

    field: str
    lo: int
    hi: int


@dataclass(frozen=True)
class SFun:
    """Function over 0..size-1."""

    size: int
    elem: Any


@dataclass(frozen=True)
class SRec:
    fields: dict  # name -> schema


@dataclass(frozen=True)
class SBitset:
    """Set over 0..size-1 stored as a bitmask in one int lane (the canonical
    ISR encoding, SURVEY.md §2.2)."""

    field: str
    size: int


@dataclass(frozen=True)
class SKeyedSet:
    """Grow-only set of records uniquely keyed by an int field, stored as
    key-indexed per-field arrays (the `leaderAndIsrRequests` encoding: every
    request carries a fresh leaderEpoch, KafkaReplication.tla:138-145, so
    the epoch IS the slot index; a slot is absent while `absent_field` holds
    `absent`)."""

    size: int
    key: str  # record field whose value equals the slot index
    fields: dict  # record field name -> leaf schema (SInt / SBitset)
    absent_field: str
    absent: int


@dataclass(frozen=True)
class SPairSet:
    """Grow-only set of [isr: SUBSET Replicas, version: 0..n_versions-1]
    records where versions may REPEAT with different isr values (AsyncIsr's
    `requests`: the leader reuses its current version, AsyncIsr.tla:88-115).
    Stored as a per-version bitset over the 2^n_set subset lattice: bit s of
    lane[v] says record [isr with mask s, version v] is present."""

    field: str
    n_versions: int
    n_set: int  # |Replicas|; subset lattice has 2^n_set points
    isr_field: str = "isr"
    version_field: str = "version"


# ------------------------------------------------------- symbolic int value
class IVal:
    """Symbolic integer with static interval bounds [lo, hi]."""

    __slots__ = ("val", "lo", "hi")

    def __init__(self, val, lo: int, hi: int):
        self.val = val
        self.lo = int(lo)
        self.hi = int(hi)

    @staticmethod
    def of(x) -> "IVal":
        if isinstance(x, IVal):
            return x
        if isinstance(x, (int, np.integer)):
            # interned: concrete constants (quantifier-unroll elements,
            # literals) share ONE instance per value, so the trace CSE's
            # id-keyed env matching fires across unrolls — e.g. WeakIsr
            # and StrongIsr binding the same replica index reuse one
            # evaluated body.  IVal is immutable by convention (no field
            # is ever written after construction).
            x = int(x)
            got = _IVAL_INTERN.get(x)
            if got is None:
                got = _IVAL_INTERN.setdefault(x, IVal(x, x, x))
            return got
        raise TypeError(f"not an integer value: {x!r}")

    def __add__(self, o):
        o = IVal.of(o)
        return IVal(self.val + o.val, self.lo + o.lo, self.hi + o.hi)

    def __sub__(self, o):
        o = IVal.of(o)
        return IVal(self.val - o.val, self.lo - o.hi, self.hi - o.lo)

    def __mul__(self, o):
        o = IVal.of(o)
        cs = [self.lo * o.lo, self.lo * o.hi, self.hi * o.lo, self.hi * o.hi]
        return IVal(self.val * o.val, min(cs), max(cs))

    def __neg__(self):
        return IVal(-self.val, -self.hi, -self.lo)

    def __repr__(self):
        return f"IVal({self.val!r}, [{self.lo},{self.hi}])"


_IVAL_INTERN: dict = {}  # int -> canonical concrete IVal (see IVal.of)


def _where_ival(cond, a: IVal, b: IVal) -> IVal:
    return IVal(jnp.where(cond, a.val, b.val), min(a.lo, b.lo), max(a.hi, b.hi))


# ------------------------------------------------------ symbolic set values
@dataclass
class SetRange:
    lo: IVal
    hi: IVal  # inclusive; may be symbolic (bounds give the static trip count)


@dataclass
class SetLitV:
    elems: list  # of IVal (or record values)


@dataclass
class SetUnion:
    parts: list


@dataclass
class SetDiffV:
    base: Any
    excl: Any  # ANY set value (membership decides exclusion)


@dataclass
class SetCondV:  # IF cond THEN s1 ELSE s2 (data-dependent set)
    cond: Any
    a: Any
    b: Any


@dataclass
class LazySet:
    """Materialized static unroll: [(elem, present_cond)] — the result form
    of set comprehensions ({x \\in S : p} / {e : x \\in S})."""

    items: list


@dataclass
class BitsetV:
    """Set over 0..size-1 as a (possibly traced) bitmask."""

    mask: Any
    size: int


@dataclass
class PowerSetV:  # SUBSET S — type positions only
    base: Any


@dataclass
class KeyedSetInsertV:
    """`keyedset \\union {rec, ...}` — an update RHS for SKeyedSet vars."""

    base: Any  # KeyedSetV
    recs: list


@dataclass
class PairSetInsertV:
    """`pairset \\union {rec, ...}` — an update RHS for SPairSet vars."""

    base: Any  # PairSetV
    recs: list


@dataclass
class NatV:
    """The builtin Nat — membership-only (x >= 0), never enumerable."""


@dataclass
class FunTypeV:
    dom: Any  # set value
    rng: Any  # set value


@dataclass
class RecTypeV:
    fields: dict  # name -> set value


_SETV = (SetRange, SetLitV, SetUnion, SetDiffV, SetCondV, LazySet, BitsetV)


def _rec_keys(v):
    """Record field names of a record-ish value, else None."""
    if isinstance(v, RecV):
        return list(v._f)
    if isinstance(v, PatchRecV):
        base = _rec_keys(v.base)
        if base is not None and v.name not in base:
            base = base + [v.name]
        return base
    if isinstance(v, CondV):
        return _rec_keys(v.a) or _rec_keys(v.b)
    return None


def _rec_field(v, k):
    """Field access with scalar promotion: a scalar standing in a record
    position (the canonical Nil = all-lanes -1 convention) yields itself for
    every field."""
    if _rec_keys(v) is not None:
        return v.field(k)
    return IVal.of(v)


def _eq(a, b):
    """TLA `=` over the symbolic value domain (ints, records, sets)."""
    if isinstance(a, BitsetV) or isinstance(b, BitsetV):
        sz = a.size if isinstance(a, BitsetV) else b.size
        return _mask_of(a, sz) == _mask_of(b, sz)
    if isinstance(a, _SETV) or isinstance(b, _SETV) or isinstance(a, KeyedSetV) or isinstance(b, KeyedSetV):
        ia, ib = _set_iter_static(a), _set_iter_static(b)

        def incl(xs, ys):
            r = jnp.bool_(True)
            for e, c in xs:
                hit = jnp.bool_(False)
                for f, d in ys:
                    hit = hit | (_eq(e, f) & _as_bool(d))
                r = r & (hit | ~_as_bool(c))
            return r

        return incl(ia, ib) & incl(ib, ia)
    ka, kb = _rec_keys(a), _rec_keys(b)
    if ka is not None or kb is not None:
        keys = ka if ka is not None else kb
        r = jnp.bool_(True)
        for k in keys:
            r = r & _eq(_rec_field(a, k), _rec_field(b, k))
        return r
    return IVal.of(a).val == IVal.of(b).val


def _mask_of(s, size: int):
    """Bitmask form of a set-over-0..size-1 value."""
    if isinstance(s, BitsetV):
        return s.mask
    if isinstance(s, SetLitV):
        m = jnp.int32(0) if s.elems else 0
        for e in s.elems:
            m = m | (jnp.int32(1) << IVal.of(e).val)
        return m
    if isinstance(s, SetCondV):
        return jnp.where(
            _as_bool(s.cond), _mask_of(s.a, size), _mask_of(s.b, size)
        )
    m = jnp.int32(0)
    for e, c in _set_iter_static(s):
        m = m | jnp.where(_as_bool(c), jnp.int32(1) << IVal.of(e).val, 0)
    return m


def _set_member(x: IVal, s) -> Any:
    if isinstance(s, SetRange):
        return (x.val >= s.lo.val) & (x.val <= s.hi.val)
    if isinstance(s, SetLitV):
        r = False
        for e in s.elems:
            t = _eq(x, e)
            r = r | t if r is not False else t
        return r if r is not False else jnp.bool_(False)
    if isinstance(s, SetUnion):
        r = jnp.bool_(False)
        for p in s.parts:
            r = r | _set_member(x, p)
        return r
    if isinstance(s, SetDiffV):
        return _set_member(x, s.base) & ~_set_member(x, s.excl)
    if isinstance(s, SetCondV):
        c = _as_bool(s.cond)
        return (c & _set_member(x, s.a)) | (~c & _set_member(x, s.b))
    if isinstance(s, BitsetV):
        return ((s.mask >> x.val) & 1) == 1
    if isinstance(s, (LazySet, KeyedSetV, PairSetV)):
        r = jnp.bool_(False)
        for e, c in _set_iter_static(s):
            r = r | (_eq(x, e) & _as_bool(c))
        return r
    if isinstance(s, NatV):
        return x.val >= 0
    raise NotImplementedError(f"membership in {type(s).__name__}")


def _member_generic(x, s) -> Any:
    """`x \\in s` for any element kind (records use equality search)."""
    if _rec_keys(x) is not None:
        r = jnp.bool_(False)
        for e, c in _set_iter_static(s):
            r = r | (_eq(x, e) & _as_bool(c))
        return r
    return _set_member(IVal.of(x), s)


def _value_in_type(v, t) -> Any:
    """`v \\in T` for function/record types, powersets and integer sets."""
    if isinstance(t, RecTypeV):
        r = jnp.bool_(True)
        for name, fs in t.fields.items():
            r = r & _value_in_type(_rec_field(v, name), fs)
        return r
    if isinstance(t, FunTypeV):
        def chk(i):
            return _value_in_type(v.apply(IVal.of(i)), t.rng)

        return _set_forall(t.dom, chk)
    if isinstance(t, PowerSetV):
        if not isinstance(v, BitsetV):
            raise NotImplementedError("SUBSET membership needs a bitset value")
        r = jnp.bool_(True)
        for i in range(v.size):
            has = _as_bool(((v.mask >> i) & 1) == 1)
            r = r & (~has | _set_member(IVal.of(i), t.base))
        return r
    if isinstance(t, SetUnion):
        r = jnp.bool_(False)
        for p in t.parts:
            r = r | _value_in_type(v, p)
        return r
    return _member_generic(v, t)


def _set_iter_static(s):
    """Static unroll list [(elem, present_cond)]; elems are IVals or record
    views.  The unroll length is state-independent (the jit requirement)."""
    if isinstance(s, SetRange):
        # unroll over the static hull [lo.lo, hi.hi]; mask each slot by the
        # (possibly symbolic) actual bounds — the static-trip-count form of
        # a data-dependent range
        out = []
        for i in range(s.lo.lo, s.hi.hi + 1):
            cond = True
            if i < s.lo.hi:  # may fall below the actual lower bound
                cond = cond & (IVal.of(i).val >= s.lo.val)
            if i > s.hi.lo:  # may exceed the actual upper bound
                cond = cond & (IVal.of(i).val <= s.hi.val)
            out.append((IVal.of(i), cond))
        return out
    if isinstance(s, SetLitV):
        return [(e, True) for e in s.elems]
    if isinstance(s, SetUnion):
        out = []
        for p in s.parts:
            out.extend(_set_iter_static(p))
        return out
    if isinstance(s, SetDiffV):
        return [
            (e, _as_bool(c) & ~_as_bool(_member_generic(e, s.excl)))
            for e, c in _set_iter_static(s.base)
        ]
    if isinstance(s, SetCondV):
        c = _as_bool(s.cond)
        out = [(e, p & c) for e, p in _set_iter_static(s.a)]
        out += [(e, p & ~c) for e, p in _set_iter_static(s.b)]
        return out
    if isinstance(s, LazySet):
        return s.items
    if isinstance(s, BitsetV):
        return [
            (IVal.of(i), ((s.mask >> i) & 1) == 1) for i in range(s.size)
        ]
    if isinstance(s, KeyedSetV):
        return [
            (s.slot(IVal.of(i)), s.present(i)) for i in range(s.size)
        ]
    if isinstance(s, PairSetV):
        return s.items()
    if isinstance(s, RecTypeV):
        # cartesian product of the field domains -> record elements
        items = [(RecV({}), jnp.bool_(True))]
        for name, fs in s.fields.items():
            nxt = []
            for base, c in items:
                for e, ec in _set_iter_static(fs):
                    nxt.append(
                        (RecV({**base._f, name: e}), c & _as_bool(ec))
                    )
            items = nxt
        return items
    raise NotImplementedError(f"cannot unroll {type(s).__name__}")


def _set_forall(s, pred: Callable) -> Any:
    r = jnp.bool_(True)
    for e, present in _set_iter_static(s):
        p = pred(e)
        r = r & (p | ~_as_bool(present))
    return r


def _set_exists(s, pred: Callable) -> Any:
    r = jnp.bool_(False)
    for e, present in _set_iter_static(s):
        r = r | (pred(e) & _as_bool(present))
    return r


def _as_bool(x):
    return jnp.bool_(x) if isinstance(x, bool) else x


# ----------------------------------------------- function / record values
class RecV:
    """Record value protocol: .field(name) -> value."""

    def __init__(self, fields: dict):
        self._f = fields

    def field(self, name):
        v = self._f[name]
        return v() if callable(v) else v


class FunV:
    """Function value protocol: .apply(IVal) -> value; .size for unrolls."""

    def __init__(self, size: int, fn: Callable):
        self.size = size
        self._fn = fn

    def apply(self, i):
        return self._fn(IVal.of(i))


# Trace-local cache of leaf state-tensor reads, installed by
# Emitter.memo_scope: (cache dict, pin list) or None.  The value-protocol
# wrappers (RecV/FunV/KeyedSetV) are lazy, so the SAME state element is
# re-read — and re-emits its whole index-op chain — every time a guard or
# update touches it through a fresh wrapper; the Emitter-level CSE caches
# the wrappers, not the tensor ops behind their lambdas.  XLA's own CSE
# recovers only part of this (measured: the optimized flagship expand
# program stays ~2.4x the hand one).  Keys use concrete ints directly and
# id() for traced index values; pins keep id()'d objects alive so a
# recycled address can never alias a distinct read.  Sound because kernels
# only ever READ from the kernel-input state dict (updates materialize
# into a fresh dict), so within one memo scope a (state, field, idx) read
# is a pure function.  Module-global (not per-Emitter) on the standing
# assumption that tracing is single-threaded in-process — parallelism in
# this framework is multiprocess.
_LEAF_MEMO = None


def _leaf_tensor(field: str, state: dict, idx: tuple):
    raws = [k.val if isinstance(k, IVal) else k for k in idx]
    memo = _LEAF_MEMO
    if memo is not None:
        cache, pins = memo
        key = tuple(
            [id(state), field]
            + [
                int(r) if isinstance(r, (int, np.integer)) else ("t", id(r))
                for r in raws
            ]
        )
        hit = cache.get(key, cache)
        if hit is not cache:
            # a hit's id()-keyed parts necessarily name the pinned (alive)
            # originals, so no re-pin is needed
            return hit
    v = state[field]
    for r in raws:
        v = v[r]
    if memo is not None:
        cache[key] = v
        # pin every id()-keyed object at entry creation: as long as the
        # entry exists, its key ids can never be recycled addresses
        pins.append((state, [r for r in raws if not isinstance(r, (int, np.integer))]))
    return v


def _state_value(schema, state: dict, idx: tuple):
    """Wrap live state tensors in the value protocol per the schema."""
    if isinstance(schema, SInt):
        return IVal(_leaf_tensor(schema.field, state, idx), schema.lo, schema.hi)
    if isinstance(schema, SBitset):
        return BitsetV(_leaf_tensor(schema.field, state, idx), schema.size)
    if isinstance(schema, SRec):
        return RecV(
            {
                n: (lambda s=s: _state_value(s, state, idx))
                for n, s in schema.fields.items()
            }
        )
    if isinstance(schema, SFun):
        return FunV(schema.size, lambda i: _state_value(schema.elem, state, idx + (i,)))
    if isinstance(schema, SKeyedSet):
        return KeyedSetV(schema, state, idx)
    if isinstance(schema, SPairSet):
        return PairSetV(schema, state, idx)
    raise TypeError(schema)


class KeyedSetV:
    """State-backed keyed record set (see SKeyedSet).  Slot i is the record
    whose key field equals i; `present(i)` reads the absence marker."""

    def __init__(self, schema: SKeyedSet, state: dict, idx: tuple):
        self.schema, self._state, self._idx = schema, state, idx
        self.size = schema.size

    def slot(self, i) -> "RecV":
        i = IVal.of(i)
        fields = {
            n: (lambda s=s, i=i: _state_value(s, self._state, self._idx + (i,)))
            for n, s in self.schema.fields.items()
        }
        fields[self.schema.key] = i
        return RecV(fields)

    def present(self, i):
        sch = self.schema.fields[self.schema.absent_field]
        v = _state_value(sch, self._state, self._idx + (IVal.of(i),))
        marker = v.val if isinstance(v, IVal) else v.mask
        return marker != self.schema.absent


class PairSetV:
    """State-backed (isr-subset, version) pair set (see SPairSet)."""

    def __init__(self, schema: SPairSet, state: dict, idx: tuple):
        self.schema, self._state, self._idx = schema, state, idx

    def items(self):
        """[(record, present)] over the full (version x subset) lattice."""
        sch = self.schema
        out = []
        for v in range(sch.n_versions):
            lane = _leaf_tensor(sch.field, self._state, self._idx + (v,))
            for s in range(1 << sch.n_set):
                rec = RecV(
                    {
                        sch.isr_field: BitsetV(s, sch.n_set),
                        sch.version_field: IVal.of(v),
                    }
                )
                out.append((rec, ((lane >> s) & 1) == 1))
        return out


class CondV:
    """IF-merged structured value."""

    def __init__(self, cond, a, b):
        self.cond, self.a, self.b = cond, a, b
        self.size = getattr(a, "size", None)

    def field(self, name):
        return _merge(self.cond, self.a.field(name), self.b.field(name))

    def apply(self, i):
        return _merge(self.cond, self.a.apply(i), self.b.apply(i))


def _merge(cond, a, b):
    cond = _as_bool(cond)
    if isinstance(a, BitsetV) or isinstance(b, BitsetV):
        sz = a.size if isinstance(a, BitsetV) else b.size
        return BitsetV(jnp.where(cond, _mask_of(a, sz), _mask_of(b, sz)), sz)
    if isinstance(a, _SETV) or isinstance(b, _SETV):
        return SetCondV(cond, a, b)
    ka, kb = _rec_keys(a), _rec_keys(b)
    if ka is not None or kb is not None:
        # scalar-vs-record merge (GetLatestRecord's `IF empty THEN Nil
        # ELSE record`, FiniteReplicatedLog.tla:59-62): promote the scalar
        # over the record's fields — sound because Nil's canonical tensor
        # encoding is all-fields -1
        keys = ka if ka is not None else kb
        if ka is None:
            a = RecV({k: IVal.of(a) for k in keys})
        if kb is None:
            b = RecV({k: IVal.of(b) for k in keys})
        return CondV(cond, a, b)
    if isinstance(a, IVal) or isinstance(b, IVal):
        return _where_ival(cond, IVal.of(a), IVal.of(b))
    return CondV(cond, a, b)


class PatchFunV:
    """base with index `at` replaced by sub-value `val`."""

    def __init__(self, base, at: IVal, val):
        self.base, self.at, self.val = base, at, val
        self.size = getattr(base, "size", None)

    def apply(self, i):
        i = IVal.of(i)
        return _merge(i.val == self.at.val, self.val, self.base.apply(i))


class PatchRecV:
    def __init__(self, base, name: str, val):
        self.base, self.name, self.val = base, name, val

    def field(self, name):
        return self.val if name == self.name else self.base.field(name)


# ------------------------------------------------------- symbolic evaluator
class Emitter:
    """Evaluates IR symbolically over jnp state tensors.

    env value kinds: IVal | bool-ish | RecV/FunV/... | set values.
    """

    def __init__(self, defs: dict, consts: dict, var_schemas: dict):
        self.defs = defs  # name -> (params, ast)
        self.consts = consts  # name -> IVal | set value
        self.var_schemas = var_schemas  # TLA variable -> schema
        self._memo = None  # trace-local CSE cache (see memo_scope)
        self._free_cache = {}  # id(node) -> (node, frozenset of free names)
        self._def_free_cache = {}  # def name -> frozenset

    def _def_free(self, name: str) -> frozenset:
        """Free names a reference to def `name` depends on (its body's free
        names minus its parameters), cached per def; cycles yield {} for
        the back-edge (a recursive def's fixpoint is its non-cyclic part)."""
        cache = self._def_free_cache
        if name in cache:
            return cache[name]
        cache[name] = frozenset()  # cycle guard
        params, body = self.defs[name]
        cache[name] = self._free_names(body) - frozenset(params)
        return cache[name]

    def _free_names(self, ast) -> frozenset:
        """Free names of `ast`: every env slot its evaluation can read —
        transitively through def references, with state-variable reads
        mapped to the "__state__" slot and EXCEPT's @ to "@".

        Memoized per AST node (the cache entry pins the node, so its id
        can't be recycled).  Sound over-approximation: after inline()
        binders are α-renamed fresh, so including a bound var's name merely
        widens a memo key, never conflates two distinct valuations."""
        cached = self._free_cache.get(id(ast))
        if cached is not None and cached[0] is ast:
            return cached[1]
        out = set()
        if isinstance(ast, E.Name):
            out.add(ast.id)
            if ast.id in self.var_schemas:
                out.add("__state__")
            elif ast.id in self.defs:
                out |= self._def_free(ast.id)
        elif isinstance(ast, E.At):
            out.add("@")
        elif isinstance(ast, E.Apply):
            if ast.op in self.defs:
                out |= self._def_free(ast.op)
            for x in ast.args:
                out |= self._free_names(x)
        elif isinstance(ast, (tuple, list)):
            for x in ast:
                out |= self._free_names(x)
        elif hasattr(ast, "__dataclass_fields__"):
            for f in ast.__dataclass_fields__:
                out |= self._free_names(getattr(ast, f))
        else:
            return frozenset()  # str/int leaves: nothing to cache
        fs = frozenset(out)
        self._free_cache[id(ast)] = (ast, fs)
        return fs

    def memo_scope(self):
        """Context manager enabling common-subexpression caching of eval.

        Within one kernel trace, guards and updates re-evaluate the same
        state reads and operator applications many times; each re-eval
        re-traces its whole jnp op tree (~1ms/op of tracing overhead and a
        bigger compiled program).  The memo keys on (AST node identity,
        identity of every env binding), so it is exact: a different bound
        value or a different state dict misses.  Scoped per trace because
        cached values hold that trace's tracers — they must not leak into
        another trace.
        """
        import contextlib

        @contextlib.contextmanager
        def scope():
            global _LEAF_MEMO
            old = self._memo
            old_pins = getattr(self, "_memo_pins", None)
            old_leaf = _LEAF_MEMO
            self._memo = {}
            self._memo_pins = []
            _LEAF_MEMO = ({}, [])
            try:
                yield
            finally:
                self._memo = old
                self._memo_pins = old_pins
                _LEAF_MEMO = old_leaf

        return scope()

    def eval(self, ast, env: dict):
        memo = self._memo
        if memo is None:
            return self._eval(ast, env)
        # key on the node identity plus ONLY the env slots its evaluation
        # can read (its free names): a subtree shared across contexts —
        # e.g. a LET body used both inside and outside a function
        # constructor whose bound var it never mentions — then hits the
        # cache instead of re-tracing per context
        free = self._free_names(ast)
        keyed = tuple(
            sorted((k, id(v)) for k, v in env.items() if k in free)
        )
        key = (id(ast), keyed)
        hit = memo.get(key, memo)
        if hit is not memo:
            return hit
        out = self._eval(ast, env)
        memo[key] = out
        # pin the AST node and the keyed env values for the scope's
        # lifetime: the key uses id()s, and a GC'd object's address could
        # be recycled by a fresh one, turning a distinct (ast, env) into a
        # false cache hit
        self._memo_pins.append((ast, tuple(env[k] for k, _ in keyed)))
        return out

    def _eval(self, ast, env: dict):
        ev = self.eval
        if isinstance(ast, E.Num):
            return IVal.of(ast.v)
        if isinstance(ast, E.At):
            return env["@"]
        if isinstance(ast, E.Name):
            if ast.id in env:
                return env[ast.id]
            if ast.id in self.consts:
                return self.consts[ast.id]
            if ast.id == "Nat":
                return NatV()
            if ast.id in self.var_schemas:
                return _state_value(
                    self.var_schemas[ast.id], env["__state__"], ()
                )
            if ast.id in self.defs:
                params, body = self.defs[ast.id]
                if params:
                    raise TypeError(f"{ast.id} needs arguments")
                return ev(body, env)
            raise NameError(ast.id)
        if isinstance(ast, E.Apply):
            params, body = self.defs[ast.op]
            args = [ev(a, env) for a in ast.args]
            sub = dict(env)
            sub.update(zip(params, args))
            return ev(body, sub)
        if isinstance(ast, E.Let):
            sub = dict(env)
            for name, params, expr in ast.binds:
                if params:
                    raise NotImplementedError("parameterized LET")
                sub[name] = ev(expr, sub)
            return ev(ast.body, sub)
        if isinstance(ast, E.Unop):
            if ast.op == "not":
                return ~_as_bool(ev(ast.a, env))
            if ast.op == "neg":
                return -ev(ast.a, env)
        if isinstance(ast, E.Binop):
            op = ast.op
            if op == "and":
                return _as_bool(ev(ast.a, env)) & _as_bool(ev(ast.b, env))
            if op == "or":
                return _as_bool(ev(ast.a, env)) | _as_bool(ev(ast.b, env))
            if op == "\\in":
                return _value_in_type(ev(ast.a, env), ev(ast.b, env))
            if op == "\\notin":
                return ~_value_in_type(ev(ast.a, env), ev(ast.b, env))
            if op == "\\subseteq":
                t = ev(ast.b, env)
                return _set_forall(ev(ast.a, env), lambda e: _value_in_type(e, t))
            if op == "..":
                return SetRange(IVal.of(ev(ast.a, env)), IVal.of(ev(ast.b, env)))
            if op == "\\union":
                a, b = ev(ast.a, env), ev(ast.b, env)
                if isinstance(a, BitsetV):
                    return BitsetV(a.mask | _mask_of(b, a.size), a.size)
                if isinstance(b, BitsetV):
                    return BitsetV(_mask_of(a, b.size) | b.mask, b.size)
                if isinstance(a, KeyedSetV):
                    if not isinstance(b, SetLitV):
                        raise NotImplementedError("keyed-set union needs literal records")
                    return KeyedSetInsertV(a, list(b.elems))
                if isinstance(a, PairSetV):
                    if not isinstance(b, SetLitV):
                        raise NotImplementedError("pair-set union needs literal records")
                    return PairSetInsertV(a, list(b.elems))
                return SetUnion([a, b])
            if op == "\\":
                a, b = ev(ast.a, env), ev(ast.b, env)
                if not isinstance(b, _SETV) and not isinstance(b, KeyedSetV):
                    b = SetLitV([IVal.of(b)])
                if isinstance(a, BitsetV):
                    return BitsetV(a.mask & ~_mask_of(b, a.size), a.size)
                return SetDiffV(a, b)
            a, b = ev(ast.a, env), ev(ast.b, env)
            if op in ("+", "-", "*"):
                a, b = IVal.of(a), IVal.of(b)
                return {"+": a + b, "-": a - b, "*": a * b}[op]
            if op == "=":
                return _eq(a, b)
            if op == "#":
                return ~_eq(a, b)
            av = a.val if isinstance(a, IVal) else a
            bv = b.val if isinstance(b, IVal) else b
            return {"<": av < bv, ">": av > bv, "<=": av <= bv, ">=": av >= bv}[op]
        if isinstance(ast, E.Index):
            return ev(ast.base, env).apply(IVal.of(ev(ast.idx, env)))
        if isinstance(ast, E.FieldAcc):
            return ev(ast.base, env).field(ast.name)
        if isinstance(ast, E.IfThenElse):
            c = _as_bool(ev(ast.cond, env))
            return _merge(c, ev(ast.then, env), ev(ast.other, env))
        if isinstance(ast, E.Quant):
            def q(binds, body, env):
                if not binds:
                    return _as_bool(ev(body, env))
                (var, dom), rest = binds[0], binds[1:]
                s = ev(dom, env)
                red = _set_forall if ast.kind == "A" else _set_exists
                return red(
                    s, lambda e: q(rest, body, {**env, var: e})
                )
            return q(list(ast.binds), ast.body, env)
        if isinstance(ast, E.FunCons):
            dom = ev(ast.domain, env)
            if not isinstance(dom, SetRange) or dom.lo.lo != dom.lo.hi or dom.hi.lo != dom.hi.hi:
                raise NotImplementedError("function domain must be a static range")
            size = dom.hi.hi - dom.lo.lo + 1
            return FunV(
                size,
                lambda i: self.eval(ast.body, {**env, ast.var: i}),
            )
        if isinstance(ast, E.RecordCons):
            return RecV({n: ev(x, env) for n, x in ast.fields})
        if isinstance(ast, E.RecordType):
            return RecTypeV({n: ev(x, env) for n, x in ast.fields})
        if isinstance(ast, E.FunType):
            return FunTypeV(ev(ast.dom, env), ev(ast.rng, env))
        if isinstance(ast, E.SetLit):
            return SetLitV([ev(x, env) for x in ast.elems])
        if isinstance(ast, E.SetMap):
            dom = ev(ast.domain, env)
            return LazySet(
                [
                    (ev(ast.body, {**env, ast.var: e}), c)
                    for e, c in _set_iter_static(dom)
                ]
            )
        if isinstance(ast, E.SetFilter):
            dom = ev(ast.domain, env)
            return LazySet(
                [
                    (
                        e,
                        _as_bool(c)
                        & _as_bool(ev(ast.pred, {**env, ast.var: e})),
                    )
                    for e, c in _set_iter_static(dom)
                ]
            )
        if isinstance(ast, E.PowerSet):
            return PowerSetV(ev(ast.base, env))
        if isinstance(ast, E.Choose):
            # static-unrolled deterministic CHOOSE: the first element (in
            # unroll order) satisfying the body.  The corpus only uses
            # CHOOSE with a unique witness (Util's Min/Max, Util.tla:22-23),
            # so unroll order never changes the result.
            s = ev(ast.domain, env)
            items = _set_iter_static(s)
            if not items:
                raise NotImplementedError("CHOOSE over statically empty set")
            val = None
            found = jnp.bool_(False)
            for e, c in items:
                ok = _as_bool(ev(ast.body, {**env, ast.var: e})) & _as_bool(c)
                take = ok & ~found
                val = e if val is None else _merge(take, e, val)
                found = found | ok
            return val
        if isinstance(ast, E.Str):
            raise NotImplementedError(
                f"model-value string {ast.v!r}: bind its defining operator "
                "via consts (e.g. None -> -1)"
            )
        if isinstance(ast, E.Except):
            # nested-update semantics: each update's @ sees the result of
            # the previous one ([[f EXCEPT !p1=e1] EXCEPT !p2=e2])
            out = ev(ast.base, env)
            for path, expr in ast.updates:
                out = self._apply_patch(out, out, list(path), expr, env)
            return out
        raise NotImplementedError(type(ast).__name__)

    def _apply_patch(self, cur, orig_base, path, expr, env):
        """One EXCEPT update; @ in expr = original value at the full path."""

        def orig_at(base, p):
            if not p:
                return base
            kind, x = p[0]
            if kind == "f":
                return orig_at(base.field(x), p[1:])
            return orig_at(base.apply(IVal.of(self.eval(x, env))), p[1:])

        def patch(cur_v, base_v, p):
            if not p:
                return self.eval(expr, {**env, "@": base_v})
            kind, x = p[0]
            if kind == "f":
                return PatchRecV(
                    cur_v, x, patch(cur_v.field(x), base_v.field(x), p[1:])
                )
            i = IVal.of(self.eval(x, env))
            return PatchFunV(
                cur_v, i, patch(cur_v.apply(i), base_v.apply(i), p[1:])
            )

        return patch(cur, orig_base, path)


# ----------------------------------------------------------- normalization
def inline(ast, defs: dict, keep: set):
    """Inline applications/names of defined operators (call-by-name) and LET
    bindings, so the action body becomes a pure expression tree over state
    variables, constants and bound vars.  `keep` = names NOT to inline
    (constants, variables, bound vars are resolved by the evaluator).

    Every binder (\\E/\\A/CHOOSE/function-constructor/set-map) is α-renamed
    to a fresh name on the way down, so substituted argument expressions can
    never be captured (e.g. TruncateTo's `newEndOffset` argument named
    `offset` meeting the records constructor's own `offset` binder,
    FiniteReplicatedLog.tla:105-109)."""
    counter = [0]

    def fresh(var):
        counter[0] += 1
        return f"{var}__{counter[0]}"

    def subst(a, env):
        if isinstance(a, E.Name):
            if a.id in env:
                return env[a.id]
            if a.id in defs and a.id not in keep:
                params, body = defs[a.id]
                if not params:
                    return subst(body, {})
            return a
        if isinstance(a, E.Apply):
            if a.op in defs and a.op not in keep:
                params, body = defs[a.op]
                args = [subst(x, env) for x in a.args]
                return subst(body, dict(zip(params, args)))
            return E.Apply(a.op, tuple(subst(x, env) for x in a.args))
        if isinstance(a, E.Let):
            sub = dict(env)
            for name, params, expr in a.binds:
                sub[name] = subst(expr, sub)
            return subst(a.body, sub)
        if isinstance(a, E.Quant):
            binds, inner = [], dict(env)
            for v, d in a.binds:
                nv = fresh(v)
                binds.append((nv, subst(d, inner)))
                inner[v] = E.Name(nv)
            return E.Quant(a.kind, tuple(binds), subst(a.body, inner))
        if isinstance(a, E.FunCons):
            nv = fresh(a.var)
            return E.FunCons(
                nv,
                subst(a.domain, env),
                subst(a.body, {**env, a.var: E.Name(nv)}),
            )
        if isinstance(a, E.Choose):
            nv = fresh(a.var)
            return E.Choose(
                nv,
                subst(a.domain, env),
                subst(a.body, {**env, a.var: E.Name(nv)}),
            )
        if isinstance(a, E.SetMap):
            nv = fresh(a.var)
            return E.SetMap(
                subst(a.body, {**env, a.var: E.Name(nv)}),
                nv,
                subst(a.domain, env),
            )
        if isinstance(a, E.SetFilter):
            nv = fresh(a.var)
            return E.SetFilter(
                nv,
                subst(a.domain, env),
                subst(a.pred, {**env, a.var: E.Name(nv)}),
            )
        if isinstance(a, E.TupleCons):
            return E.TupleCons(tuple(subst(x, env) for x in a.elems))
        if isinstance(a, E.PowerSet):
            return E.PowerSet(subst(a.base, env))
        if isinstance(a, E.Binop):
            return E.Binop(a.op, subst(a.a, env), subst(a.b, env))
        if isinstance(a, E.Unop):
            return E.Unop(a.op, subst(a.a, env))
        if isinstance(a, E.Index):
            return E.Index(subst(a.base, env), subst(a.idx, env))
        if isinstance(a, E.FieldAcc):
            return E.FieldAcc(subst(a.base, env), a.name)
        if isinstance(a, E.IfThenElse):
            return E.IfThenElse(
                subst(a.cond, env), subst(a.then, env), subst(a.other, env)
            )
        if isinstance(a, E.RecordCons):
            return E.RecordCons(tuple((n, subst(x, env)) for n, x in a.fields))
        if isinstance(a, E.RecordType):
            return E.RecordType(tuple((n, subst(x, env)) for n, x in a.fields))
        if isinstance(a, E.FunType):
            return E.FunType(subst(a.dom, env), subst(a.rng, env))
        if isinstance(a, E.SetLit):
            return E.SetLit(tuple(subst(x, env) for x in a.elems))
        if isinstance(a, E.Except):
            ups = tuple(
                (
                    tuple(
                        (k, x if k == "f" else subst(x, env)) for k, x in path
                    ),
                    subst(expr, env),
                )
                for path, expr in a.updates
            )
            return E.Except(subst(a.base, env), ups)
        if isinstance(a, E.Prime):
            return E.Prime(subst(a.base, env))
        if isinstance(a, E.Domain):
            return E.Domain(subst(a.fn, env))
        return a  # Num, At

    return subst(ast, {})


def alpha_normalize(ast):
    """Canonicalize bound-variable names by binding order (β0, β1, ...).

    inline() α-renames every binder FRESH per substitution site, which is
    capture-safe but makes structurally identical subtrees (e.g. the
    `∃ record : HasEntry(r1, ...) ∧ HasEntry(r2, ...)` core shared by
    WeakIsr and StrongIsr, or a helper inlined at two call sites) differ
    in nothing but binder names.  Renaming binders to their binding DEPTH
    restores structural equality so intern_ast can share them — and the
    id-keyed trace CSE then evaluates them once."""

    def walk(a, env, depth):
        if isinstance(a, E.Name):
            return E.Name(env.get(a.id, a.id))
        if isinstance(a, E.Quant):
            binds, inner = [], dict(env)
            d = depth
            for v, dom in a.binds:
                nv = f"β{d}"
                d += 1
                # Walk each domain at the RUNNING counter d, not the
                # quantifier's entry depth: a nested binder inside a later
                # (dependent) domain must never reuse an earlier sibling
                # bind's β-name, or references to that sibling get captured
                # (e.g. {x ∈ S : x # r1} inside the r2 domain of
                # ∃ r1 ∈ S, r2 ∈ … would normalize to β0 # β0).
                binds.append((nv, walk(dom, inner, d)))
                inner[v] = nv
            return E.Quant(a.kind, tuple(binds), walk(a.body, inner, d))
        if isinstance(a, (E.Choose, E.FunCons)):
            nv = f"β{depth}"
            return type(a)(
                nv,
                walk(a.domain, env, depth),
                walk(a.body, {**env, a.var: nv}, depth + 1),
            )
        if isinstance(a, E.SetMap):
            nv = f"β{depth}"
            return E.SetMap(
                walk(a.body, {**env, a.var: nv}, depth + 1),
                nv,
                walk(a.domain, env, depth),
            )
        if isinstance(a, E.SetFilter):
            nv = f"β{depth}"
            return E.SetFilter(
                nv,
                walk(a.domain, env, depth),
                walk(a.pred, {**env, a.var: nv}, depth + 1),
            )
        if isinstance(a, E.Let):  # gone after inline(); rename defensively
            binds, inner = [], dict(env)
            for name, params, expr in a.binds:
                binds.append((name, params, walk(expr, inner, depth)))
            return E.Let(tuple(binds), walk(a.body, inner, depth))
        if isinstance(a, tuple):
            return tuple(walk(x, env, depth) for x in a)
        if hasattr(a, "__dataclass_fields__"):
            return type(a)(
                *(
                    walk(getattr(a, f), env, depth)
                    for f in a.__dataclass_fields__
                )
            )
        return a  # str/int leaves

    return walk(ast, {}, 0)


def intern_ast(ast, table: dict):
    """Hash-cons: map structurally equal subtrees to one canonical node.

    With children already canonical, structural identity reduces to child
    identity, so the table keys on (type, id-of-child...) — O(1) per node
    without recursive hashing.  Shared nodes make the Emitter's id-keyed
    CSE fire across duplicated inline sites and across invariants traced
    in one scope (run alpha_normalize first or binder names defeat it)."""
    if isinstance(ast, tuple):
        return tuple(intern_ast(x, table) for x in ast)
    if not hasattr(ast, "__dataclass_fields__"):
        return ast

    def keyof(v):
        if hasattr(v, "__dataclass_fields__"):
            return id(v)
        if isinstance(v, tuple):
            return tuple(keyof(x) for x in v)
        return v

    kids = tuple(
        intern_ast(getattr(ast, f), table) for f in ast.__dataclass_fields__
    )
    key = (type(ast),) + tuple(keyof(k) for k in kids)
    got = table.get(key)
    if got is None:
        got = type(ast)(*kids)
        table[key] = got
    return got


def contains_prime(ast) -> bool:
    if isinstance(ast, E.Prime):
        return True

    def walk(v) -> bool:
        if hasattr(v, "__dataclass_fields__"):
            if isinstance(v, E.Prime):
                return True
            return any(
                walk(getattr(v, f)) for f in v.__dataclass_fields__
            )
        if isinstance(v, tuple):
            return any(walk(x) for x in v)
        return False

    return walk(ast)


def flatten_and(ast) -> list:
    if isinstance(ast, E.Binop) and ast.op == "and":
        return flatten_and(ast.a) + flatten_and(ast.b)
    return [ast]


@dataclass
class ActionIR:
    name: str
    binds: list  # [(var, domain_ast)] — the choice space
    guards: list  # boolean ASTs
    updates: dict  # TLA var -> rhs AST


def _is_unchanged(cj) -> Optional[list]:
    """UNCHANGED <<a, b>> / UNCHANGED a -> the variable names, else None."""
    if isinstance(cj, E.Apply) and cj.op == "UNCHANGED":
        arg = cj.args[0]
        elems = arg.elems if isinstance(arg, E.TupleCons) else (arg,)
        names = []
        for e in elems:
            if not isinstance(e, E.Name):
                raise NotImplementedError("UNCHANGED of a non-variable")
            names.append(e.id)
        return names
    return None


def _dnf_branches(binds, pending, done):
    """Normalize an inlined action body to disjunctive-normal-form branches.

    Hoists prime-dominating \\E binds into the choice space and splits
    prime-carrying \\/ alternatives (ControllerShrinkIsr's three cases,
    KafkaReplication.tla:158-168) into separate branches; prime-free
    subtrees stay as ordinary guards.  Returns [(binds, conjuncts)].
    """
    pending = list(pending)
    done = list(done)
    while pending:
        cj = pending.pop(0)
        if isinstance(cj, E.Binop) and cj.op == "and":
            pending[:0] = [cj.a, cj.b]
        elif isinstance(cj, E.Quant) and cj.kind == "E" and contains_prime(cj):
            binds = list(binds) + list(cj.binds)
            pending.insert(0, cj.body)
        elif isinstance(cj, E.Binop) and cj.op == "or" and contains_prime(cj):
            return _dnf_branches(
                binds, [cj.a] + pending, done
            ) + _dnf_branches(binds, [cj.b] + pending, done)
        else:
            done.append(cj)
    return [(binds, done)]


def extract_actions(mod: TlaModule, defs: dict, keep: set) -> list[ActionIR]:
    """Next -> per-disjunct (and per DNF branch) ActionIR."""
    params, next_ast = defs["Next"]
    assert not params

    out = []

    def walk(ast, binds):
        if isinstance(ast, E.Quant) and ast.kind == "E":
            walk(ast.body, binds + list(ast.binds))
            return
        if isinstance(ast, E.Binop) and ast.op == "or":
            walk(ast.a, binds)
            walk(ast.b, binds)
            return
        # leaf: named action application (or bare name)
        if isinstance(ast, (E.Apply, E.Name)):
            name = ast.op if isinstance(ast, E.Apply) else ast.id
            body = inline(ast, defs, keep)
        else:
            raise NotImplementedError(f"unsupported Next leaf: {ast}")
        branches = _dnf_branches(list(binds), [body], [])
        for k, (b, conjs) in enumerate(branches):
            guards, updates = [], {}
            for cj in conjs:
                unch = _is_unchanged(cj)
                if unch is not None:
                    continue  # vars not in `updates` are carried through
                if (
                    isinstance(cj, E.Binop)
                    and cj.op == "="
                    and isinstance(cj.a, E.Prime)
                    and isinstance(cj.a.base, E.Name)
                ):
                    var = cj.a.base.id
                    if var in updates:
                        raise ValueError(f"{name}: duplicate update of {var}")
                    updates[var] = cj.b
                elif contains_prime(cj):
                    raise NotImplementedError(
                        f"{name}: prime in non-assignment conjunct"
                    )
                else:
                    guards.append(cj)
            bname = name if len(branches) == 1 else f"{name}~{k}"
            out.append(ActionIR(bname, b, guards, updates))

    walk(next_ast, [])
    return out


# ----------------------------------------------------------- module loading
def _rename_ast(ast, mapping: dict, bound: frozenset):
    """Substitute free Name/Apply references per `mapping` (name -> AST for
    plain names, name -> new operator name for applications), respecting
    binder shadowing.  Used for INSTANCE ... WITH substitution."""
    E_ = E

    def sub(a, bound):
        if isinstance(a, E_.Name):
            if a.id not in bound and a.id in mapping:
                m = mapping[a.id]
                return E_.Name(m) if isinstance(m, str) else m
            return a
        if isinstance(a, E_.Apply):
            op = a.op
            if op in mapping and isinstance(mapping[op], str):
                op = mapping[op]
            return E_.Apply(op, tuple(sub(x, bound) for x in a.args))
        if isinstance(a, E_.Quant):
            inner = bound | {v for v, _ in a.binds}
            return E_.Quant(
                a.kind,
                tuple((v, sub(d, bound)) for v, d in a.binds),
                sub(a.body, inner),
            )
        if isinstance(a, E_.Choose):
            return E_.Choose(
                a.var, sub(a.domain, bound), sub(a.body, bound | {a.var})
            )
        if isinstance(a, E_.FunCons):
            return E_.FunCons(
                a.var, sub(a.domain, bound), sub(a.body, bound | {a.var})
            )
        if isinstance(a, E_.SetMap):
            return E_.SetMap(
                sub(a.body, bound | {a.var}), a.var, sub(a.domain, bound)
            )
        if isinstance(a, E_.SetFilter):
            return E_.SetFilter(
                a.var, sub(a.domain, bound), sub(a.pred, bound | {a.var})
            )
        if isinstance(a, E_.Let):
            binds = []
            inner = bound
            for name, params, expr in a.binds:
                binds.append((name, params, sub(expr, inner | set(params))))
                inner = inner | {name}
            return E_.Let(tuple(binds), sub(a.body, inner))
        if isinstance(a, E_.Binop):
            return E_.Binop(a.op, sub(a.a, bound), sub(a.b, bound))
        if isinstance(a, E_.Unop):
            return E_.Unop(a.op, sub(a.a, bound))
        if isinstance(a, E_.Index):
            return E_.Index(sub(a.base, bound), sub(a.idx, bound))
        if isinstance(a, E_.FieldAcc):
            return E_.FieldAcc(sub(a.base, bound), a.name)
        if isinstance(a, E_.Prime):
            return E_.Prime(sub(a.base, bound))
        if isinstance(a, E_.IfThenElse):
            return E_.IfThenElse(
                sub(a.cond, bound), sub(a.then, bound), sub(a.other, bound)
            )
        if isinstance(a, E_.RecordCons):
            return E_.RecordCons(tuple((n, sub(x, bound)) for n, x in a.fields))
        if isinstance(a, E_.RecordType):
            return E_.RecordType(tuple((n, sub(x, bound)) for n, x in a.fields))
        if isinstance(a, E_.FunType):
            return E_.FunType(sub(a.dom, bound), sub(a.rng, bound))
        if isinstance(a, E_.SetLit):
            return E_.SetLit(tuple(sub(x, bound) for x in a.elems))
        if isinstance(a, E_.TupleCons):
            return E_.TupleCons(tuple(sub(x, bound) for x in a.elems))
        if isinstance(a, E_.PowerSet):
            return E_.PowerSet(sub(a.base, bound))
        if isinstance(a, E_.Domain):
            return E_.Domain(sub(a.fn, bound))
        if isinstance(a, E_.Except):
            ups = tuple(
                (
                    tuple((k, x if k == "f" else sub(x, bound)) for k, x in path),
                    sub(expr, bound),
                )
                for path, expr in a.updates
            )
            return E_.Except(sub(a.base, bound), ups)
        return a  # Num, Str, At

    return sub(ast, bound)


_TEMPORAL = re.compile(r"\[\]\[|\bSF_|\bWF_|<>|~>")


def _parse_module_defs(mod: TlaModule) -> dict:
    """name -> (params, ast) for every definition of one module.

    Temporal definitions (Spec-like bodies with [][Next]_vars / SF_ / WF_)
    are skipped by CONTENT, not by swallowing parse errors: a non-temporal
    definition that fails to parse raises, so an unsupported construct can
    never silently fall back to an ancestor module's same-named definition.
    """
    out = {}
    for dname, body in mod.definitions.items():
        if dname == "Spec" or _TEMPORAL.search(body):
            continue
        txt = "\n".join(
            ln
            for ln in body.splitlines()
            if not ln.strip().startswith(("THEOREM", "ASSUME"))
        )
        n, params, ast = E.parse_definition(txt)
        out[n] = (params, ast)
    return out


def load_defs(ref_dir, module: str) -> dict:
    """Parse `module` plus its EXTENDS chain and INSTANCE targets into one
    definition namespace.

    - ancestor modules contribute their non-LOCAL definitions (Kip279's
      LOCAL Next must not shadow Kip320's own Next, Kip279.tla:53);
    - `Alias == INSTANCE M WITH x <- e` (KafkaReplication.tla:77-84)
      registers every definition D of M as `Alias!D`, with M's constants
      and variables substituted per the WITH list and M-internal references
      rewritten to the aliased names.
    """
    from pathlib import Path

    from .tla_frontend import load_chain, parse_tla

    ref_dir = Path(ref_dir)
    chain = load_chain(ref_dir, module)
    if module not in chain:
        raise FileNotFoundError(f"{module}.tla not found under {ref_dir}")

    order: list[str] = []

    def visit(name):
        m = chain.get(name)
        if m is None or name in order:
            return
        for e in m.extends:
            visit(e)
        if name in chain:
            order.append(name)

    visit(module)

    defs: dict = {}
    instances: dict = {}
    for name in order:
        m = chain[name]
        parsed = _parse_module_defs(m)
        for dname, entry in parsed.items():
            if name != module and dname in m.local_defs:
                continue  # LOCAL: not visible to extending modules
            defs[dname] = entry
        instances.update(m.instances)

    for alias, (target, subs) in instances.items():
        tmod = parse_tla(ref_dir / f"{target}.tla")
        tdefs = _parse_module_defs(tmod)
        mapping: dict = {n: f"{alias}!{n}" for n in tdefs}
        for cname, expr_txt in subs.items():
            mapping[cname] = E.parse_expr(expr_txt)
        for n, (params, ast) in tdefs.items():
            defs[f"{alias}!{n}"] = (
                params,
                _rename_ast(ast, mapping, frozenset(params)),
            )
    return defs


# ------------------------------------------------------------ model builder
def _names_in(ast) -> set:
    """All Name ids appearing anywhere in `ast`.

    Used as a (sound) over-approximation of the free variables: after
    inline() every inner binder is α-renamed fresh, so a bind var's name
    can never be shadowed inside the expression — any occurrence is a
    genuine reference."""
    out = set()

    def walk(v):
        if isinstance(v, E.Name):
            out.add(v.id)
        elif isinstance(v, (tuple, list)):
            for x in v:
                walk(x)
        elif hasattr(v, "__dataclass_fields__"):
            for f in v.__dataclass_fields__:
                walk(getattr(v, f))

    walk(ast)
    return out


def _split_forced(binds, guards):
    """Forced-existential elimination (the hand kernels' key trick,
    SURVEY.md §2.3 "forced ∃").

    A bind var pinned by a top-level guard `var = expr` (or `expr = var`),
    where expr references neither the var itself nor any later bind var,
    needs no choice digit: its value is computed from the state at kernel
    time and only membership in its declared domain is checked.  This is
    what keeps e.g. LeaderWrite at N choices instead of N·R·(L+1)
    (KafkaReplication.tla:202-207: `RecordSeq!NextId(id)` pins id = nextId
    and `Append` pins offset = endOffset) and FencedFollowerFetch at N²
    instead of N²·(L+1)·R·E (ReplicateTo's offset/record, Kip320.tla:49-56
    + FiniteReplicatedLog.tla:111-113).

    Returns (entries, remaining_guards) with entries preserving bind order:
    ("choice", var, dom_ast) | ("forced", var, dom_ast, expr_ast); the
    consumed equality conjuncts are dropped from the guards.
    """
    entries = []
    remaining = list(guards)
    pending = list(binds)
    pending_vars = {v for v, _ in pending}

    def pin_of(var, placed_only: bool):
        """A guard `var = expr` (either side) usable as a pin.  With
        placed_only, expr may reference no still-pending bind var (so the
        value is computable once every placed entry is bound); otherwise
        any pin shape counts (used to decide which bind to sacrifice as a
        choice digit)."""
        for g in remaining:
            if isinstance(g, E.Binop) and g.op == "=":
                for side, other in ((g.a, g.b), (g.b, g.a)):
                    if isinstance(side, E.Name) and side.id == var:
                        names = _names_in(other)
                        if var in names:
                            continue
                        if not placed_only or not (
                            names & (pending_vars - {var})
                        ):
                            return g, other
        return None

    while pending:
        # force any pending bind whose pin references only placed binds —
        # hoisting it is sound iff its own domain references no pending
        # bind (TLA+ scoping: domains only reference earlier binds, and
        # those are either placed or pending; pending ones block the hoist)
        placed_forced = False
        for bi, (var, dom_ast) in enumerate(pending):
            pick = pin_of(var, placed_only=True)
            if pick and not (_names_in(dom_ast) & (pending_vars - {var})):
                pending.pop(bi)
                pending_vars.discard(var)
                remaining.remove(pick[0])
                entries.append(("forced", var, dom_ast, pick[1]))
                placed_forced = True
                break
        if placed_forced:
            continue
        # no bind is forcible yet: spend a choice digit.  Prefer (in
        # original order) a bind with no pin equation at all — placing it
        # may unblock pins of the others (e.g. `req.leader = leader` with
        # `leader` bound before `req`: choosing `req` first turns `leader`
        # into a forced bind instead of an N-wide digit)
        ci = next(
            (
                bi
                for bi, (var, dom) in enumerate(pending)
                if pin_of(var, placed_only=False) is None
                and not (_names_in(dom) & (pending_vars - {var}))
            ),
            0,  # first-in-order: its domain refs are all placed by scoping
        )
        var, dom_ast = pending.pop(ci)
        pending_vars.discard(var)
        entries.append(("choice", var, dom_ast, None))
    return entries, remaining


def _domain_space(emitter: Emitter, entries, spec):
    """Static choice decomposition for the bind list (post _split_forced).

    Each "choice" entry becomes one mixed-radix choice digit whose radix is
    the domain's static hull size (state-independent by construction: ranges
    unroll to schema-bound hulls, ISR bitsets to their universe, the keyed
    request set to its slot count); each "forced" entry is evaluated
    directly and guard-checked for domain membership.  Returns
    (sizes, mapper) where mapper(choice_digits, env) ->
    ({var: value}, enabled_guard): the guard masks hull slots not actually
    in the (state-dependent) domain — TLC's "branch on every witness, most
    disabled" semantics, vectorized.
    """
    dummy_state = {f.name: np.zeros(f.shape, np.int32) for f in spec.fields}

    sizes = []
    for i, (kind, var, dom_ast, _x) in enumerate(entries):
        if kind != "choice":
            continue
        # the mixed-radix digit layout requires each choice domain's static
        # hull to be independent of earlier bind values (the mapper later
        # evaluates the domain with *real* forced values/digits).  Guard by
        # sampling the hull under two stub valuations of the earlier binds
        # and rejecting on disagreement — a two-point sample, not a proof,
        # but it moves the supported-subset boundary from a silent miscount
        # to a loud build error (every corpus module passes; a domain whose
        # hull varies with a bind value lands here by design, even if the
        # concrete run would have been benign)
        per_stub = []
        for stub in (IVal(0, 0, 0), IVal(1, 1, 1)):
            env = {"__state__": dummy_state}
            for _k, v, _d, _e in entries[:i]:
                env[v] = stub
            per_stub.append(len(_set_iter_static(emitter.eval(dom_ast, env))))
        if per_stub[0] != per_stub[1]:
            raise NotImplementedError(
                f"choice domain of {var!r} has a bind-dependent static hull "
                f"({per_stub[0]} vs {per_stub[1]} slots): the digit radix "
                f"and the mapper's unroll could disagree — outside the "
                f"emitter's supported subset"
            )
        sizes.append(per_stub[0])

    def mapper(digits, env):
        vals = {}
        guard = jnp.bool_(True)
        digit_iter = iter(zip(digits, sizes))
        for kind, var, dom_ast, expr_ast in entries:
            if kind == "forced":
                val = emitter.eval(expr_ast, {**env, **vals})
                s = emitter.eval(dom_ast, {**env, **vals})
                vals[var] = val
                guard = guard & _as_bool(_value_in_type(val, s))
                continue
            d, n = next(digit_iter)
            s = emitter.eval(dom_ast, {**env, **vals})
            # fast paths: direct indexing instead of a select chain
            if isinstance(s, SetRange) and s.lo.lo == s.lo.hi and s.hi.lo == s.hi.hi:
                vals[var] = d + IVal.of(s.lo.lo)
                continue
            if isinstance(s, BitsetV):
                vals[var] = IVal(d.val, 0, s.size - 1)
                guard = guard & (((s.mask >> d.val) & 1) == 1)
                continue
            if isinstance(s, KeyedSetV):
                i = IVal(d.val, 0, s.size - 1)
                vals[var] = s.slot(i)
                guard = guard & s.present(i)
                continue
            items = _set_iter_static(s)
            assert len(items) == n, (var, len(items), n)
            elem = items[0][0]
            pres = _as_bool(items[0][1]) & (d.val == 0)
            for j in range(1, n):
                elem = _merge(d.val == j, items[j][0], elem)
                pres = pres | (_as_bool(items[j][1]) & (d.val == j))
            vals[var] = elem
            guard = guard & pres
        return vals, guard

    return sizes, mapper


def build_model(
    mod: TlaModule,
    consts: dict,
    var_schemas: dict,
    spec,
    invariant_names=("TypeOk",),
    name: Optional[str] = None,
    defs: Optional[dict] = None,
    constraint_src: Optional[str] = None,
):
    """Emit a models.base.Model mechanically from a parsed TLA+ module.

    consts: name -> int or (lo, hi) range tuple (model-value sets map to
    0..n-1 ints; overriding a defined operator name, e.g. None -> -1, pins
    its model value and blocks inlining of the definition).  var_schemas:
    TLA VARIABLE -> SInt/SBitset/SFun/SRec/SKeyedSet/SPairSet schema whose
    leaf fields name entries of `spec` (an ops.packing.StateSpec).  defs: a
    prebuilt definition namespace (load_defs) for modules with EXTENDS
    chains / INSTANCE substitutions; defaults to `mod`'s own definitions.
    constraint_src: a TLA boolean expression over the variables (TLC's
    CONSTRAINT — e.g. the authored `Bounded` for AsyncIsr's unbounded
    spec); emitted as Model.constraint so violating successors are pruned.
    """
    from ..models.base import Action, Invariant, Model

    if defs is None:
        defs = _parse_module_defs(mod)

    cvals = {}
    for k, v in consts.items():
        cvals[k] = (
            SetRange(IVal.of(v[0]), IVal.of(v[1]))
            if isinstance(v, tuple)
            else IVal.of(v)
        )
    emitter = Emitter(defs, cvals, var_schemas)
    keep = set(consts) | set(var_schemas)

    actions_ir = extract_actions(mod, defs, keep)

    # one hash-cons table per model: α-normalized, structurally equal
    # subtrees (duplicated inline sites, the invariant pair's shared
    # quantifier core) collapse to one node, so the id-keyed trace CSE
    # evaluates them once per scope
    _interns: dict = {}

    def canon(a):
        return intern_ast(alpha_normalize(a), _interns)

    def make_kernel(air: ActionIR):
        air = ActionIR(
            name=air.name,
            binds=[(v, canon(d)) for v, d in air.binds],
            guards=[canon(g) for g in air.guards],
            updates={v: canon(r) for v, r in air.updates.items()},
        )
        entries, rem_guards = _split_forced(air.binds, air.guards)
        sizes, mapper = _domain_space(emitter, entries, spec)
        n_choices = int(np.prod(sizes)) if sizes else 1

        def kernel(state, choice):
            with emitter.memo_scope():
                env = {"__state__": state}
                digits = []
                c = choice
                for n in reversed(sizes):
                    digits.append(IVal(c % n, 0, n - 1))
                    c = c // n
                digits.reverse()
                vals, ok = mapper(digits, env)
                env.update(vals)
                for g in rem_guards:
                    ok = ok & _as_bool(emitter.eval(g, env))
                new_state = dict(state)
                for var, rhs in air.updates.items():
                    val = emitter.eval(rhs, env)
                    _materialize(var_schemas[var], val, new_state, ())
                # guard-failed slots keep the (arbitrary) computed tensors;
                # the engine masks them via `ok`, but clamp indices already
                # guarded
                return ok, new_state

        return Action(air.name, n_choices, kernel)

    def _materialize(schema, val, out, idx):
        if isinstance(schema, SInt):
            arr = out[schema.field]
            v = IVal.of(val).val
            out[schema.field] = (
                arr.at[idx].set(v) if idx else jnp.asarray(v, arr.dtype)
                if hasattr(arr, "dtype")
                else v
            )
            return
        if isinstance(schema, SBitset):
            arr = out[schema.field]
            m = _mask_of(val, schema.size)
            out[schema.field] = (
                arr.at[idx].set(m) if idx else jnp.asarray(m, arr.dtype)
                if hasattr(arr, "dtype")
                else m
            )
            return
        if isinstance(schema, SRec):
            for n, s in schema.fields.items():
                _materialize(s, _rec_field(val, n), out, idx)
            return
        if isinstance(schema, SFun):
            for i in range(schema.size):
                _materialize(schema.elem, val.apply(IVal.of(i)), out, idx + (i,))
            return
        if isinstance(schema, SKeyedSet):
            if isinstance(val, KeyedSetV):
                return  # assigned unchanged (same backing arrays)
            if not isinstance(val, KeyedSetInsertV):
                raise NotImplementedError(
                    "keyed-set update must be `base \\union {records}`"
                )
            for rec in val.recs:
                key = IVal.of(_rec_field(rec, schema.key))
                for n, leaf in schema.fields.items():
                    fv = _rec_field(rec, n)
                    arr = out[leaf.field]
                    v = (
                        _mask_of(fv, leaf.size)
                        if isinstance(leaf, SBitset)
                        else IVal.of(fv).val
                    )
                    out[leaf.field] = arr.at[idx + (key.val,)].set(v)
            return
        if isinstance(schema, SPairSet):
            if isinstance(val, PairSetV):
                return  # assigned unchanged
            if not isinstance(val, PairSetInsertV):
                raise NotImplementedError(
                    "pair-set update must be `base \\union {records}`"
                )
            for rec in val.recs:
                ver = IVal.of(_rec_field(rec, schema.version_field)).val
                isr = _mask_of(_rec_field(rec, schema.isr_field), schema.n_set)
                arr = out[schema.field]
                lane = arr[idx + (ver,)]
                out[schema.field] = arr.at[idx + (ver,)].set(
                    lane | (jnp.int32(1) << isr)
                )
            return
        raise TypeError(schema)

    # Init: conjuncts `var = expr`, evaluated concretely
    from .tla_concrete import ConcreteEval

    conc = ConcreteEval(defs, _concrete_consts(consts))

    def _conc_encode(schema, val, out, idx):
        if isinstance(schema, SInt):
            out.setdefault(schema.field, {})[idx] = int(val)
            return
        if isinstance(schema, SBitset):
            mask = 0
            for e in val:
                mask |= 1 << int(e)
            out.setdefault(schema.field, {})[idx] = mask
            return
        if isinstance(schema, SRec):
            from .tla_concrete import _thaw

            val = _thaw(val)
            for n, s in schema.fields.items():
                # scalar in record position = canonical Nil (all fields -1)
                _conc_encode(s, val[n] if isinstance(val, dict) else val, out, idx)
            return
        if isinstance(schema, SFun):
            for i in range(schema.size):
                _conc_encode(schema.elem, val[i], out, idx + (i,))
            return
        if isinstance(schema, SKeyedSet):
            recs = {}
            for r in val:
                r = dict(r) if not isinstance(r, dict) else r
                recs[int(r[schema.key])] = r
            for j in range(schema.size):
                r = recs.get(j)
                for n, leaf in schema.fields.items():
                    if r is None:
                        v = schema.absent if n == schema.absent_field else 0
                        out.setdefault(leaf.field, {})[idx + (j,)] = v
                    else:
                        _conc_encode(leaf, r[n], out, idx + (j,))
            return
        if isinstance(schema, SPairSet):
            lanes = [0] * schema.n_versions
            for r in val:
                r = dict(r) if not isinstance(r, dict) else r
                mask = 0
                for e in r[schema.isr_field]:
                    mask |= 1 << int(e)
                lanes[int(r[schema.version_field])] |= 1 << mask
            for v in range(schema.n_versions):
                out.setdefault(schema.field, {})[idx + (v,)] = lanes[v]
            return
        raise TypeError(schema)

    def init_states_wrapped():
        init_ast = inline(E.Name("Init"), defs, keep)
        assigns = {}
        for cj in flatten_and(init_ast):
            assert (
                isinstance(cj, E.Binop)
                and cj.op == "="
                and isinstance(cj.a, E.Name)
            ), f"unsupported Init conjunct: {cj}"
            assigns[cj.a.id] = conc.eval(cj.b, {})
        pos = {}
        for var, schema in var_schemas.items():
            _conc_encode(schema, assigns[var], pos, ())
        state = {}
        for f in spec.fields:
            arr = np.zeros(f.shape, np.int32)
            for idx, v in pos.get(f.name, {}).items():
                arr[idx if idx else ()] = v
            state[f.name] = arr
        return [state]

    invariants = []
    inv_bodies = []
    for iname in invariant_names:
        params, ast = defs[iname]
        body = canon(
            inline(
                E.Name(iname) if not params else E.Apply(iname, ()),
                defs,
                keep,
            )
        )
        inv_bodies.append(body)

        def pred(state, body=body):
            with emitter.memo_scope():
                return _as_bool(emitter.eval(body, {"__state__": state}))

        invariants.append(Invariant(iname, pred))

    invariants_fused = None
    if len(inv_bodies) > 1:
        # one trace, one CSE scope for ALL invariant predicates: the
        # α-normalized, hash-consed bodies share their common subtrees
        # (WeakIsr and StrongIsr differ only in the ISR source set —
        # their ∃record per-(r1, r2, offset) core is one shared node)

        def invariants_fused(state):
            with emitter.memo_scope():
                return jnp.stack(
                    [
                        _as_bool(emitter.eval(b, {"__state__": state}))
                        for b in inv_bodies
                    ]
                )

    constraint = None
    if constraint_src is not None:
        c_body = canon(inline(E.parse_expr(constraint_src), defs, keep))

        def constraint(state, c_body=c_body):
            with emitter.memo_scope():
                return _as_bool(emitter.eval(c_body, {"__state__": state}))

    return Model(
        name=name or f"{mod.name}(emitted)",
        spec=spec,
        init_states=init_states_wrapped,
        actions=[make_kernel(a) for a in actions_ir],
        invariants=invariants,
        constraint=constraint,
        decode=None,
        invariants_fused=invariants_fused,
    )


def _concrete_consts(consts: dict) -> dict:
    out = {}
    for k, v in consts.items():
        out[k] = frozenset(range(v[0], v[1] + 1)) if isinstance(v, tuple) else v
    return out
