"""Mechanical kernel emission from the TLA+ expression IR.

Closes the loop utils/tla_expr.py opens: given a parsed module, constant
valuations, and a tensor-encoding schema for each VARIABLE, this module

  1. extracts the action list from `Next` (quantifier prefixes become the
     static choice lattice; each disjunct becomes one action),
  2. normalizes each action body (inline operator applications and LET
     bindings, hoist update-dominating \\E quantifiers into the choice
     space, split conjuncts into guards vs primed assignments),
  3. evaluates guards/updates SYMBOLICALLY over jnp state tensors —
     producing exactly the `(state, choice) -> (enabled, next_state)`
     kernels the engine vmaps (models/base.Action), and
  4. evaluates the same IR CONCRETELY over Python values — an independent
     successor enumerator used to cross-check both the emitted kernels and
     the hand-written models.

Integer values carry static interval bounds (IVal) so quantifiers over
data-dependent ranges (e.g. `0 .. logs[r].endOffset - 1` in TypeOk,
FiniteReplicatedLog.tla:95) unroll to masked reductions with a static trip
count — the jit-compatibility requirement.

Scope: the full expression surface of Util/IdSequence/FiniteReplicatedLog
(SURVEY.md §2.5 row 1 "hand-written kernels acceptable for v0 if
cross-validated" — this module begins retiring that caveat).  CHOOSE is
evaluated concretely (Util's Min/Max come out of their CHOOSE definitions
mechanically); symbolic CHOOSE emission is deferred with the L3 modules.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np

from . import tla_expr as E
from .tla_frontend import TlaModule


# ------------------------------------------------------------------ schemas
@dataclass(frozen=True)
class SInt:
    """Integer leaf stored in state[field][<enclosing function indices>]."""

    field: str
    lo: int
    hi: int


@dataclass(frozen=True)
class SFun:
    """Function over 0..size-1."""

    size: int
    elem: Any


@dataclass(frozen=True)
class SRec:
    fields: dict  # name -> schema


# ------------------------------------------------------- symbolic int value
class IVal:
    """Symbolic integer with static interval bounds [lo, hi]."""

    __slots__ = ("val", "lo", "hi")

    def __init__(self, val, lo: int, hi: int):
        self.val = val
        self.lo = int(lo)
        self.hi = int(hi)

    @staticmethod
    def of(x) -> "IVal":
        if isinstance(x, IVal):
            return x
        if isinstance(x, (int, np.integer)):
            return IVal(int(x), int(x), int(x))
        raise TypeError(f"not an integer value: {x!r}")

    def __add__(self, o):
        o = IVal.of(o)
        return IVal(self.val + o.val, self.lo + o.lo, self.hi + o.hi)

    def __sub__(self, o):
        o = IVal.of(o)
        return IVal(self.val - o.val, self.lo - o.hi, self.hi - o.lo)

    def __mul__(self, o):
        o = IVal.of(o)
        cs = [self.lo * o.lo, self.lo * o.hi, self.hi * o.lo, self.hi * o.hi]
        return IVal(self.val * o.val, min(cs), max(cs))

    def __neg__(self):
        return IVal(-self.val, -self.hi, -self.lo)

    def __repr__(self):
        return f"IVal({self.val!r}, [{self.lo},{self.hi}])"


def _where_ival(cond, a: IVal, b: IVal) -> IVal:
    return IVal(jnp.where(cond, a.val, b.val), min(a.lo, b.lo), max(a.hi, b.hi))


# ------------------------------------------------------ symbolic set values
@dataclass
class SetRange:
    lo: IVal
    hi: IVal  # inclusive; may be symbolic (bounds give the static trip count)


@dataclass
class SetLitV:
    elems: list  # of IVal


@dataclass
class SetUnion:
    parts: list


@dataclass
class SetDiffV:
    base: Any
    excl: list  # of IVal


@dataclass
class SetCondV:  # IF cond THEN s1 ELSE s2 (data-dependent set)
    cond: Any
    a: Any
    b: Any


@dataclass
class FunTypeV:
    dom: Any  # set value
    rng: Any  # set value


@dataclass
class RecTypeV:
    fields: dict  # name -> set value


def _set_member(x: IVal, s) -> Any:
    if isinstance(s, SetRange):
        return (x.val >= s.lo.val) & (x.val <= s.hi.val)
    if isinstance(s, SetLitV):
        r = False
        for e in s.elems:
            r = r | (x.val == e.val) if r is not False else (x.val == e.val)
        return r if r is not False else jnp.bool_(False)
    if isinstance(s, SetUnion):
        r = jnp.bool_(False)
        for p in s.parts:
            r = r | _set_member(x, p)
        return r
    if isinstance(s, SetDiffV):
        r = _set_member(x, s.base)
        for e in s.excl:
            r = r & (x.val != e.val)
        return r
    if isinstance(s, SetCondV):
        c = _as_bool(s.cond)
        return (c & _set_member(x, s.a)) | (~c & _set_member(x, s.b))
    raise NotImplementedError(f"membership in {type(s).__name__}")


def _value_in_type(v, t) -> Any:
    """`v \\in T` for function/record types and integer sets."""
    if isinstance(t, RecTypeV):
        r = jnp.bool_(True)
        for name, fs in t.fields.items():
            r = r & _value_in_type(v.field(name), fs)
        return r
    if isinstance(t, FunTypeV):
        r = jnp.bool_(True)

        def chk(i):
            return _value_in_type(v.apply(IVal.of(i)), t.rng)

        r_all = _set_forall(t.dom, chk)
        return r & r_all
    return _set_member(IVal.of(v), t)


def _set_iter_static(s):
    """Static unroll list [(concrete_or_IVal elem, present_cond)]."""
    if isinstance(s, SetRange):
        # unroll over the static hull [lo.lo, hi.hi]; mask each slot by the
        # (possibly symbolic) actual bounds — the static-trip-count form of
        # a data-dependent range
        out = []
        for i in range(s.lo.lo, s.hi.hi + 1):
            cond = True
            if i < s.lo.hi:  # may fall below the actual lower bound
                cond = cond & (IVal.of(i).val >= s.lo.val)
            if i > s.hi.lo:  # may exceed the actual upper bound
                cond = cond & (IVal.of(i).val <= s.hi.val)
            out.append((IVal.of(i), cond))
        return out
    if isinstance(s, SetLitV):
        return [(e, True) for e in s.elems]
    if isinstance(s, SetUnion):
        out = []
        for p in s.parts:
            out.extend(_set_iter_static(p))
        return out
    if isinstance(s, SetDiffV):
        out = []
        for e, c in _set_iter_static(s.base):
            for x in s.excl:
                c = c & (e.val != x.val)
            out.append((e, c))
        return out
    if isinstance(s, SetCondV):
        c = _as_bool(s.cond)
        out = [(e, p & c) for e, p in _set_iter_static(s.a)]
        out += [(e, p & ~c) for e, p in _set_iter_static(s.b)]
        return out
    raise NotImplementedError(f"cannot unroll {type(s).__name__}")


def _set_forall(s, pred: Callable) -> Any:
    r = jnp.bool_(True)
    for e, present in _set_iter_static(s):
        p = pred(e)
        r = r & (p | ~_as_bool(present))
    return r


def _set_exists(s, pred: Callable) -> Any:
    r = jnp.bool_(False)
    for e, present in _set_iter_static(s):
        r = r | (pred(e) & _as_bool(present))
    return r


def _as_bool(x):
    return jnp.bool_(x) if isinstance(x, bool) else x


# ----------------------------------------------- function / record values
class RecV:
    """Record value protocol: .field(name) -> value."""

    def __init__(self, fields: dict):
        self._f = fields

    def field(self, name):
        v = self._f[name]
        return v() if callable(v) else v


class FunV:
    """Function value protocol: .apply(IVal) -> value; .size for unrolls."""

    def __init__(self, size: int, fn: Callable):
        self.size = size
        self._fn = fn

    def apply(self, i):
        return self._fn(IVal.of(i))


def _state_value(schema, state: dict, idx: tuple):
    """Wrap live state tensors in the value protocol per the schema."""
    if isinstance(schema, SInt):
        v = state[schema.field]
        for k in idx:
            v = v[k.val if isinstance(k, IVal) else k]
        return IVal(v, schema.lo, schema.hi)
    if isinstance(schema, SRec):
        return RecV(
            {
                n: (lambda s=s: _state_value(s, state, idx))
                for n, s in schema.fields.items()
            }
        )
    if isinstance(schema, SFun):
        return FunV(schema.size, lambda i: _state_value(schema.elem, state, idx + (i,)))
    raise TypeError(schema)


class CondV:
    """IF-merged structured value."""

    def __init__(self, cond, a, b):
        self.cond, self.a, self.b = cond, a, b
        self.size = getattr(a, "size", None)

    def field(self, name):
        return _merge(self.cond, self.a.field(name), self.b.field(name))

    def apply(self, i):
        return _merge(self.cond, self.a.apply(i), self.b.apply(i))


_SET_TYPES = (SetRange, SetLitV, SetUnion, SetDiffV, SetCondV)


def _merge(cond, a, b):
    if isinstance(a, IVal) or isinstance(b, IVal):
        return _where_ival(cond, IVal.of(a), IVal.of(b))
    if isinstance(a, _SET_TYPES) or isinstance(b, _SET_TYPES):
        return SetCondV(cond, a, b)
    return CondV(cond, a, b)


class PatchFunV:
    """base with index `at` replaced by sub-value `val`."""

    def __init__(self, base, at: IVal, val):
        self.base, self.at, self.val = base, at, val
        self.size = getattr(base, "size", None)

    def apply(self, i):
        i = IVal.of(i)
        return _merge(i.val == self.at.val, self.val, self.base.apply(i))


class PatchRecV:
    def __init__(self, base, name: str, val):
        self.base, self.name, self.val = base, name, val

    def field(self, name):
        return self.val if name == self.name else self.base.field(name)


# ------------------------------------------------------- symbolic evaluator
class Emitter:
    """Evaluates IR symbolically over jnp state tensors.

    env value kinds: IVal | bool-ish | RecV/FunV/... | set values.
    """

    def __init__(self, defs: dict, consts: dict, var_schemas: dict):
        self.defs = defs  # name -> (params, ast)
        self.consts = consts  # name -> IVal | set value
        self.var_schemas = var_schemas  # TLA variable -> schema

    def eval(self, ast, env: dict):
        ev = self.eval
        if isinstance(ast, E.Num):
            return IVal.of(ast.v)
        if isinstance(ast, E.At):
            return env["@"]
        if isinstance(ast, E.Name):
            if ast.id in env:
                return env[ast.id]
            if ast.id in self.consts:
                return self.consts[ast.id]
            if ast.id in self.var_schemas:
                return _state_value(
                    self.var_schemas[ast.id], env["__state__"], ()
                )
            if ast.id in self.defs:
                params, body = self.defs[ast.id]
                if params:
                    raise TypeError(f"{ast.id} needs arguments")
                return ev(body, env)
            raise NameError(ast.id)
        if isinstance(ast, E.Apply):
            params, body = self.defs[ast.op]
            args = [ev(a, env) for a in ast.args]
            sub = dict(env)
            sub.update(zip(params, args))
            return ev(body, sub)
        if isinstance(ast, E.Let):
            sub = dict(env)
            for name, params, expr in ast.binds:
                if params:
                    raise NotImplementedError("parameterized LET")
                sub[name] = ev(expr, sub)
            return ev(ast.body, sub)
        if isinstance(ast, E.Unop):
            if ast.op == "not":
                return ~_as_bool(ev(ast.a, env))
            if ast.op == "neg":
                return -ev(ast.a, env)
        if isinstance(ast, E.Binop):
            op = ast.op
            if op == "and":
                return _as_bool(ev(ast.a, env)) & _as_bool(ev(ast.b, env))
            if op == "or":
                return _as_bool(ev(ast.a, env)) | _as_bool(ev(ast.b, env))
            if op == "\\in":
                return _value_in_type(ev(ast.a, env), ev(ast.b, env))
            if op == "\\notin":
                return ~_value_in_type(ev(ast.a, env), ev(ast.b, env))
            if op == "..":
                return SetRange(IVal.of(ev(ast.a, env)), IVal.of(ev(ast.b, env)))
            if op == "\\union":
                return SetUnion([ev(ast.a, env), ev(ast.b, env)])
            if op == "\\":
                b = ev(ast.b, env)
                excl = (
                    b.elems if isinstance(b, SetLitV) else [IVal.of(b)]
                )
                return SetDiffV(ev(ast.a, env), excl)
            a, b = ev(ast.a, env), ev(ast.b, env)
            if op in ("+", "-", "*"):
                a, b = IVal.of(a), IVal.of(b)
                return {"+": a + b, "-": a - b, "*": a * b}[op]
            av = a.val if isinstance(a, IVal) else a
            bv = b.val if isinstance(b, IVal) else b
            if op == "=":
                return av == bv
            if op == "#":
                return av != bv
            return {"<": av < bv, ">": av > bv, "<=": av <= bv, ">=": av >= bv}[op]
        if isinstance(ast, E.Index):
            return ev(ast.base, env).apply(IVal.of(ev(ast.idx, env)))
        if isinstance(ast, E.FieldAcc):
            return ev(ast.base, env).field(ast.name)
        if isinstance(ast, E.IfThenElse):
            c = _as_bool(ev(ast.cond, env))
            return _merge(c, ev(ast.then, env), ev(ast.other, env))
        if isinstance(ast, E.Quant):
            def q(binds, body, env):
                if not binds:
                    return _as_bool(ev(body, env))
                (var, dom), rest = binds[0], binds[1:]
                s = ev(dom, env)
                red = _set_forall if ast.kind == "A" else _set_exists
                return red(
                    s, lambda e: q(rest, body, {**env, var: e})
                )
            return q(list(ast.binds), ast.body, env)
        if isinstance(ast, E.FunCons):
            dom = ev(ast.domain, env)
            if not isinstance(dom, SetRange) or dom.lo.lo != dom.lo.hi or dom.hi.lo != dom.hi.hi:
                raise NotImplementedError("function domain must be a static range")
            size = dom.hi.hi - dom.lo.lo + 1
            return FunV(
                size,
                lambda i: self.eval(ast.body, {**env, ast.var: i}),
            )
        if isinstance(ast, E.RecordCons):
            return RecV({n: ev(x, env) for n, x in ast.fields})
        if isinstance(ast, E.RecordType):
            return RecTypeV({n: ev(x, env) for n, x in ast.fields})
        if isinstance(ast, E.FunType):
            return FunTypeV(ev(ast.dom, env), ev(ast.rng, env))
        if isinstance(ast, E.SetLit):
            return SetLitV([IVal.of(ev(x, env)) for x in ast.elems])
        if isinstance(ast, E.Except):
            # nested-update semantics: each update's @ sees the result of
            # the previous one ([[f EXCEPT !p1=e1] EXCEPT !p2=e2])
            out = ev(ast.base, env)
            for path, expr in ast.updates:
                out = self._apply_patch(out, out, list(path), expr, env)
            return out
        raise NotImplementedError(type(ast).__name__)

    def _apply_patch(self, cur, orig_base, path, expr, env):
        """One EXCEPT update; @ in expr = original value at the full path."""

        def orig_at(base, p):
            if not p:
                return base
            kind, x = p[0]
            if kind == "f":
                return orig_at(base.field(x), p[1:])
            return orig_at(base.apply(IVal.of(self.eval(x, env))), p[1:])

        def patch(cur_v, base_v, p):
            if not p:
                return self.eval(expr, {**env, "@": base_v})
            kind, x = p[0]
            if kind == "f":
                return PatchRecV(
                    cur_v, x, patch(cur_v.field(x), base_v.field(x), p[1:])
                )
            i = IVal.of(self.eval(x, env))
            return PatchFunV(
                cur_v, i, patch(cur_v.apply(i), base_v.apply(i), p[1:])
            )

        return patch(cur, orig_base, path)


# ----------------------------------------------------------- normalization
def inline(ast, defs: dict, keep: set):
    """Inline applications/names of defined operators (call-by-name) and LET
    bindings, so the action body becomes a pure expression tree over state
    variables, constants and bound vars.  `keep` = names NOT to inline
    (constants, variables, bound vars are resolved by the evaluator).

    Every binder (\\E/\\A/CHOOSE/function-constructor/set-map) is α-renamed
    to a fresh name on the way down, so substituted argument expressions can
    never be captured (e.g. TruncateTo's `newEndOffset` argument named
    `offset` meeting the records constructor's own `offset` binder,
    FiniteReplicatedLog.tla:105-109)."""
    counter = [0]

    def fresh(var):
        counter[0] += 1
        return f"{var}__{counter[0]}"

    def subst(a, env):
        if isinstance(a, E.Name):
            if a.id in env:
                return env[a.id]
            if a.id in defs and a.id not in keep:
                params, body = defs[a.id]
                if not params:
                    return subst(body, {})
            return a
        if isinstance(a, E.Apply):
            if a.op in defs and a.op not in keep:
                params, body = defs[a.op]
                args = [subst(x, env) for x in a.args]
                return subst(body, dict(zip(params, args)))
            return E.Apply(a.op, tuple(subst(x, env) for x in a.args))
        if isinstance(a, E.Let):
            sub = dict(env)
            for name, params, expr in a.binds:
                sub[name] = subst(expr, sub)
            return subst(a.body, sub)
        if isinstance(a, E.Quant):
            binds, inner = [], dict(env)
            for v, d in a.binds:
                nv = fresh(v)
                binds.append((nv, subst(d, inner)))
                inner[v] = E.Name(nv)
            return E.Quant(a.kind, tuple(binds), subst(a.body, inner))
        if isinstance(a, E.FunCons):
            nv = fresh(a.var)
            return E.FunCons(
                nv,
                subst(a.domain, env),
                subst(a.body, {**env, a.var: E.Name(nv)}),
            )
        if isinstance(a, E.Choose):
            nv = fresh(a.var)
            return E.Choose(
                nv,
                subst(a.domain, env),
                subst(a.body, {**env, a.var: E.Name(nv)}),
            )
        if isinstance(a, E.SetMap):
            nv = fresh(a.var)
            return E.SetMap(
                subst(a.body, {**env, a.var: E.Name(nv)}),
                nv,
                subst(a.domain, env),
            )
        if isinstance(a, E.Binop):
            return E.Binop(a.op, subst(a.a, env), subst(a.b, env))
        if isinstance(a, E.Unop):
            return E.Unop(a.op, subst(a.a, env))
        if isinstance(a, E.Index):
            return E.Index(subst(a.base, env), subst(a.idx, env))
        if isinstance(a, E.FieldAcc):
            return E.FieldAcc(subst(a.base, env), a.name)
        if isinstance(a, E.IfThenElse):
            return E.IfThenElse(
                subst(a.cond, env), subst(a.then, env), subst(a.other, env)
            )
        if isinstance(a, E.RecordCons):
            return E.RecordCons(tuple((n, subst(x, env)) for n, x in a.fields))
        if isinstance(a, E.RecordType):
            return E.RecordType(tuple((n, subst(x, env)) for n, x in a.fields))
        if isinstance(a, E.FunType):
            return E.FunType(subst(a.dom, env), subst(a.rng, env))
        if isinstance(a, E.SetLit):
            return E.SetLit(tuple(subst(x, env) for x in a.elems))
        if isinstance(a, E.Except):
            ups = tuple(
                (
                    tuple(
                        (k, x if k == "f" else subst(x, env)) for k, x in path
                    ),
                    subst(expr, env),
                )
                for path, expr in a.updates
            )
            return E.Except(subst(a.base, env), ups)
        if isinstance(a, E.Prime):
            return E.Prime(subst(a.base, env))
        if isinstance(a, E.Domain):
            return E.Domain(subst(a.fn, env))
        return a  # Num, At

    return subst(ast, {})


def contains_prime(ast) -> bool:
    if isinstance(ast, E.Prime):
        return True

    def walk(v) -> bool:
        if hasattr(v, "__dataclass_fields__"):
            if isinstance(v, E.Prime):
                return True
            return any(
                walk(getattr(v, f)) for f in v.__dataclass_fields__
            )
        if isinstance(v, tuple):
            return any(walk(x) for x in v)
        return False

    return walk(ast)


def flatten_and(ast) -> list:
    if isinstance(ast, E.Binop) and ast.op == "and":
        return flatten_and(ast.a) + flatten_and(ast.b)
    return [ast]


@dataclass
class ActionIR:
    name: str
    binds: list  # [(var, domain_ast)] — the choice space
    guards: list  # boolean ASTs
    updates: dict  # TLA var -> rhs AST


def extract_actions(mod: TlaModule, defs: dict, keep: set) -> list[ActionIR]:
    """Next -> per-disjunct ActionIR with hoisted quantifier binds."""
    params, next_ast = defs["Next"]
    assert not params

    out = []

    def walk(ast, binds):
        if isinstance(ast, E.Quant) and ast.kind == "E":
            walk(ast.body, binds + list(ast.binds))
            return
        if isinstance(ast, E.Binop) and ast.op == "or":
            walk(ast.a, binds)
            walk(ast.b, binds)
            return
        # leaf: named action application (or bare name)
        if isinstance(ast, E.Apply):
            name = ast.op
            body = inline(ast, defs, keep)
        elif isinstance(ast, E.Name):
            name = ast.id
            body = inline(ast, defs, keep)
        else:
            raise NotImplementedError(f"unsupported Next leaf: {ast}")
        b = list(binds)
        while isinstance(body, E.Quant) and body.kind == "E" and contains_prime(body.body):
            b += list(body.binds)
            body = body.body
        guards, updates = [], {}
        for cj in flatten_and(body):
            if (
                isinstance(cj, E.Binop)
                and cj.op == "="
                and isinstance(cj.a, E.Prime)
                and isinstance(cj.a.base, E.Name)
            ):
                var = cj.a.base.id
                if var in updates:
                    raise ValueError(f"{name}: duplicate update of {var}")
                updates[var] = cj.b
            elif contains_prime(cj):
                raise NotImplementedError(f"{name}: prime in non-assignment conjunct")
            else:
                guards.append(cj)
        out.append(ActionIR(name, b, guards, updates))

    walk(next_ast, [])
    return out


# ------------------------------------------------------------ model builder
def _domain_space(emitter: Emitter, binds, env_builder):
    """Static choice decomposition for the bind list.

    Returns (sizes, mapper) where mapper(choice_digits, state_env) -> dict
    var -> IVal.  Supported domains: static ranges / constant sets and
    `<static set> \\ {<earlier bind var>}` (index remap, the corpus's
    `Replicas \\ {replica}` case)."""
    sizes = []
    specs = []
    for var, dom_ast in binds:
        dom_ast = dom_ast
        specs.append((var, dom_ast))
    # sizes must be static: evaluate domains with dummy env for earlier vars
    def static_size(dom_ast):
        # evaluate with every prior var bound to its range minimum — sizes
        # of the supported domain forms don't depend on the binding
        env = {"__state__": {}}
        dummy = {}
        for v, _ in specs:
            dummy[v] = IVal(0, 0, 0)
        s = emitter.eval(dom_ast, {**env, **dummy})
        if isinstance(s, SetRange):
            if s.lo.lo != s.lo.hi or s.hi.lo != s.hi.hi:
                raise NotImplementedError("choice domain must be static")
            return s.hi.hi - s.lo.lo + 1, ("range", s.lo.lo)
        if isinstance(s, SetDiffV):
            base = s.base
            if not isinstance(base, SetRange) or len(s.excl) != 1:
                raise NotImplementedError("unsupported choice domain difference")
            return base.hi.hi - base.lo.lo + 1 - 1, ("diff", base.lo.lo)
        raise NotImplementedError(f"choice domain {type(s).__name__}")

    kinds = []
    for var, dom_ast in specs:
        n, kind = static_size(dom_ast)
        sizes.append(n)
        kinds.append(kind)

    def mapper(digits, env):
        vals = {}
        for (var, dom_ast), d, (kind, lo) in zip(specs, digits, kinds):
            if kind == "range":
                vals[var] = d + IVal.of(lo)
            else:  # diff: re-evaluate the excluded element with current binds
                s = emitter.eval(dom_ast, {**env, **vals})
                excl = s.excl[0]
                base_lo = s.base.lo
                cand = d + base_lo
                vals[var] = IVal(
                    jnp.where(cand.val >= excl.val, cand.val + 1, cand.val),
                    cand.lo,
                    cand.hi + 1,
                )
        return vals

    return sizes, mapper


def build_model(
    mod: TlaModule,
    consts: dict,
    var_schemas: dict,
    spec,
    invariant_names=("TypeOk",),
    name: Optional[str] = None,
):
    """Emit a models.base.Model mechanically from a parsed TLA+ module.

    consts: name -> int or (lo, hi) range tuple (model-value sets map to
    0..n-1 ints).  var_schemas: TLA VARIABLE -> SInt/SFun/SRec schema whose
    leaf fields name entries of `spec` (an ops.packing.StateSpec).
    """
    from ..models.base import Action, Invariant, Model

    defs = {}
    for dname, body in mod.definitions.items():
        if dname in ("Spec",):
            continue
        txt = "\n".join(
            ln
            for ln in body.splitlines()
            if not ln.strip().startswith(("THEOREM", "ASSUME"))
        )
        n, params, ast = E.parse_definition(txt)
        defs[n] = (params, ast)

    cvals = {}
    for k, v in consts.items():
        cvals[k] = (
            SetRange(IVal.of(v[0]), IVal.of(v[1]))
            if isinstance(v, tuple)
            else IVal.of(v)
        )
    emitter = Emitter(defs, cvals, var_schemas)
    keep = set(consts) | set(var_schemas)

    actions_ir = extract_actions(mod, defs, keep)

    def make_kernel(air: ActionIR):
        sizes, mapper = _domain_space(emitter, air.binds, None)
        n_choices = int(np.prod(sizes)) if sizes else 1

        def kernel(state, choice):
            env = {"__state__": state}
            digits = []
            c = choice
            for n in reversed(sizes):
                digits.append(IVal(c % n, 0, n - 1))
                c = c // n
            digits.reverse()
            env.update(mapper(digits, env))
            ok = jnp.bool_(True)
            for g in air.guards:
                ok = ok & _as_bool(emitter.eval(g, env))
            new_state = dict(state)
            for var, rhs in air.updates.items():
                val = emitter.eval(rhs, env)
                _materialize(var_schemas[var], val, new_state, ())
            # guard-failed slots keep the (arbitrary) computed tensors; the
            # engine masks them via `ok`, but clamp indices already guarded
            return ok, new_state

        return Action(air.name, n_choices, kernel)

    def _materialize(schema, val, out, idx):
        if isinstance(schema, SInt):
            arr = out[schema.field]
            v = IVal.of(val).val
            out[schema.field] = (
                arr.at[idx].set(v) if idx else jnp.asarray(v, arr.dtype)
                if hasattr(arr, "dtype")
                else v
            )
            return
        if isinstance(schema, SRec):
            for n, s in schema.fields.items():
                _materialize(s, val.field(n), out, idx)
            return
        if isinstance(schema, SFun):
            for i in range(schema.size):
                _materialize(schema.elem, val.apply(IVal.of(i)), out, idx + (i,))
            return
        raise TypeError(schema)

    # Init: conjuncts `var = expr`, evaluated concretely
    from .tla_concrete import ConcreteEval

    conc = ConcreteEval(defs, _concrete_consts(consts))

    def _conc_encode(schema, val, out, idx):
        if isinstance(schema, SInt):
            out.setdefault(schema.field, {})[idx] = int(val)
            return
        if isinstance(schema, SRec):
            for n, s in schema.fields.items():
                _conc_encode(s, val[n], out, idx)
            return
        if isinstance(schema, SFun):
            for i in range(schema.size):
                _conc_encode(schema.elem, val[i], out, idx + (i,))
            return

    def init_states_wrapped():
        _, init_ast = defs["Init"]
        assigns = {}
        for cj in flatten_and(init_ast):
            assigns[cj.a.id] = conc.eval(cj.b, {})
        pos = {}
        for var, schema in var_schemas.items():
            _conc_encode(schema, assigns[var], pos, ())
        state = {}
        for f in spec.fields:
            arr = np.zeros(f.shape, np.int32)
            for idx, v in pos.get(f.name, {}).items():
                arr[idx if idx else ()] = v
            state[f.name] = arr
        return [state]

    invariants = []
    for iname in invariant_names:
        params, ast = defs[iname]
        body = inline(
            E.Name(iname) if not params else E.Apply(iname, ()), defs, keep
        )

        def pred(state, body=body):
            return _as_bool(emitter.eval(body, {"__state__": state}))

        invariants.append(Invariant(iname, pred))

    return Model(
        name=name or f"{mod.name}(emitted)",
        spec=spec,
        init_states=init_states_wrapped,
        actions=[make_kernel(a) for a in actions_ir],
        invariants=invariants,
        decode=None,
    )


def _concrete_consts(consts: dict) -> dict:
    out = {}
    for k, v in consts.items():
        out[k] = frozenset(range(v[0], v[1] + 1)) if isinstance(v, tuple) else v
    return out
