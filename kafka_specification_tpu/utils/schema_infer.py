"""Generic tensor-schema inference from a module's TypeOk.

The north star wants stock specs to drive the checker with no per-module
mapping code (BASELINE.json "module-override hook" is the escape hatch,
not the default).  This module derives the packed tensor schema — the
(variable -> SInt/SRec/SFun/SBitset) map plus the matching StateSpec —
mechanically from the parsed TypeOk:

    TypeOk == /\\ nextId \\in IdSet \\union {MaxId + 1}
              /\\ \\A r \\in Replicas : logs[r] \\in [endOffset: ..., ...]

Procedure (SURVEY.md §2.5 "Spec parsing" row; round-5 verdict item 7):

1. inline() TypeOk over the module's definitions, so named type sets
   (IdSet, ReplicaLogTypeOk, ...) become structural type expressions.
2. Flatten the conjunction and collect membership facts:
   - `var \\in T`                      -> schema[var] = infer(T)
   - `\\A x \\in D : ... var[x] \\in T ...` with D a 0-based index range
                                       -> schema[var] = SFun(|D|, infer(T))
   (non-membership conjuncts — e.g. FiniteReplicatedLog's Nil-fill
   canonicality clauses — bound *values*, not shapes, and are skipped.)
3. infer(T) structurally:
   - RecordType  -> SRec of inferred fields
   - FunType     -> SFun(|dom|, infer(rng)) for a 0-based int-range dom
   - PowerSet(S) -> SBitset(|S|)
   - anything that evaluates concretely to a finite int set (ranges,
     unions with sentinels like {Nil}, named constant sets) -> SInt with
     that set's [min, max] bounds.
4. Emit the StateSpec: one Field per SInt/SBitset leaf, shaped by the
   enclosing SFun sizes, named by its path — names agree between schema
   and spec by construction, which is all the emitter requires.

Model-value strings (e.g. None == "NONE") must already be pinned to ints
in `consts`, exactly as the emitted model builders do (models/emitted).
Anything outside the supported shape raises SchemaInferenceError — the
caller falls back to its curated schema (the documented override hook;
the corpus' message-set encodings SKeyedSet/SPairSet are representation
*choices* justified in PARITY.md, not inferable bounds).
"""

from __future__ import annotations

from . import tla_expr as E
from .tla_concrete import ConcreteEval
from .tla_emit import SBitset, SFun, SInt, SRec, inline
from ..ops.packing import Field, StateSpec


class SchemaInferenceError(ValueError):
    pass


def _as_int_set(val, what):
    if not isinstance(val, frozenset) or not all(
        isinstance(x, int) for x in val
    ):
        raise SchemaInferenceError(f"{what} is not a finite int set: {val!r}")
    return val


def _index_size(val, what) -> int:
    """A function/quantifier domain must be 0..n-1 to become an axis."""
    s = _as_int_set(val, what)
    n = len(s)
    if s != frozenset(range(n)):
        raise SchemaInferenceError(
            f"{what} must be a 0-based contiguous index range, got {sorted(s)}"
        )
    return n


def _norm_consts(consts: dict) -> dict:
    """Accept the emitted builders' consts convention ((lo, hi) tuples for
    index sets) and normalize for ConcreteEval."""
    out = {}
    for k, v in consts.items():
        if isinstance(v, tuple) and len(v) == 2:
            out[k] = frozenset(range(v[0], v[1] + 1))
        else:
            out[k] = v
    return out


def infer_schemas(defs: dict, consts: dict, variables) -> dict:
    """(module defs, consts, declared VARIABLES) -> {var: schema}.

    Raises SchemaInferenceError when TypeOk is absent or any variable's
    type expression falls outside the supported structural subset.
    """
    if "TypeOk" not in defs:
        raise SchemaInferenceError("module has no TypeOk")
    ev = ConcreteEval({}, _norm_consts(consts))

    def ev_int_set(t, what):
        try:
            return _as_int_set(ev.eval(t, {}), what)
        except SchemaInferenceError:
            raise
        except Exception as e:
            raise SchemaInferenceError(f"cannot evaluate {what}: {e}") from e

    def infer(t, path: str):
        if isinstance(t, E.RecordType):
            return SRec(
                {n: infer(x, f"{path}_{n}") for n, x in t.fields}
            )
        if isinstance(t, E.FunType):
            n = _index_size(
                ev_int_set(t.dom, f"{path} function domain"),
                f"{path} function domain",
            )
            return SFun(n, infer(t.rng, path))
        if isinstance(t, E.PowerSet):
            n = _index_size(
                ev_int_set(t.base, f"{path} SUBSET base"),
                f"{path} SUBSET base",
            )
            return SBitset(path, n)
        s = ev_int_set(t, f"{path} type set")
        if not s:
            raise SchemaInferenceError(f"{path} type set is empty")
        return SInt(path, min(s), max(s))

    body = inline(defs["TypeOk"][1], defs, keep=set())
    facts = []  # (var, n_outer or None, type-expr)

    def collect(a):
        if isinstance(a, E.Binop) and a.op == "and":
            collect(a.a)
            collect(a.b)
            return
        if isinstance(a, E.Binop) and a.op == "\\in":
            if isinstance(a.a, E.Name):
                facts.append((a.a.id, None, a.b))
            return
        if isinstance(a, E.Quant) and a.kind == "A" and len(a.binds) == 1:
            var, dom = a.binds[0]

            def inner(b):
                if isinstance(b, E.Binop) and b.op == "and":
                    inner(b.a)
                    inner(b.b)
                    return
                if (
                    isinstance(b, E.Binop)
                    and b.op == "\\in"
                    and isinstance(b.a, E.Index)
                    and isinstance(b.a.base, E.Name)
                    and isinstance(b.a.idx, E.Name)
                    and b.a.idx.id == var
                ):
                    n = _index_size(
                        ev_int_set(dom, f"\\A {var} domain"),
                        f"\\A {var} domain",
                    )
                    facts.append((b.a.base.id, n, b.b))

            inner(a.body)

    collect(body)
    by_var = {}
    for var, n_outer, texpr in facts:
        if var in by_var:
            continue  # first membership fact wins (TypeOk order)
        s = infer(texpr, var)
        by_var[var] = SFun(n_outer, s) if n_outer is not None else s
    missing = [v for v in variables if v not in by_var]
    if missing:
        raise SchemaInferenceError(
            f"TypeOk states no membership bound for variable(s) {missing}"
        )
    return {v: by_var[v] for v in variables}


def spec_from_schemas(schemas: dict) -> StateSpec:
    """Flatten inferred schemas into the packed StateSpec.

    Field order follows the schemas dict (VARIABLES declaration order) and
    record-field order within; shapes stack the enclosing SFun sizes.
    Field names are the schema leaves' path names, so the emitter's
    name-keyed lane writes line up by construction.
    """
    fields = []

    def walk(s, dims):
        if isinstance(s, SFun):
            walk(s.elem, dims + (s.size,))
        elif isinstance(s, SRec):
            for sub in s.fields.values():
                walk(sub, dims)
        elif isinstance(s, SBitset):
            fields.append(Field(s.field, dims, 0, (1 << s.size) - 1))
        elif isinstance(s, SInt):
            fields.append(Field(s.field, dims, s.lo, s.hi))
        else:  # pragma: no cover - guarded by infer_schemas
            raise SchemaInferenceError(f"unsupported schema node {s!r}")

    for s in schemas.values():
        walk(s, ())
    return StateSpec(fields)
