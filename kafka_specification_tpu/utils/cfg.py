"""TLC-compatible .cfg parsing and model instantiation.

The reference corpus shipped no TLC configs (`*.toolbox` is gitignored,
/root/reference/.gitignore:1), so this framework authors its own (configs/)
in stock TLC .cfg syntax — the north-star requirement is that existing .cfg
files drive the TPU engine unchanged (BASELINE.json "north_star").

Supported subset (what TLC configs for this corpus need):
  CONSTANT / CONSTANTS   name = value   (ints, model-value sets {a, b, c})
  INVARIANT / INVARIANTS name...
  CONSTRAINT name                        (AsyncIsr's bound; see below)
  SPECIFICATION / INIT / NEXT            (parsed, informational — each module
                                          has exactly one Spec shape)
  CHECK_DEADLOCK TRUE|FALSE              (default FALSE: the bounded models
                                          deadlock by design, SURVEY.md §2.4)
  \\* and (* ... *) comments

Replica sets are given as model-value sets ({r1, r2, r3}); the engine maps
them to indices 0..N-1.  AsyncIsr's CONSTRAINT references bounds that TLC
would read from the constraint's definition in a .tla override; here the
bounds come from the MaxVersion constant (an authored extension, documented
in configs/AsyncIsr.cfg).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class TlcConfig:
    constants: dict = field(default_factory=dict)  # name -> int | list[str]
    invariants: list = field(default_factory=list)
    constraints: list = field(default_factory=list)
    specification: str | None = None
    check_deadlock: bool = False


_SECTIONS = {
    "CONSTANT": "constants",
    "CONSTANTS": "constants",
    "INVARIANT": "invariants",
    "INVARIANTS": "invariants",
    "CONSTRAINT": "constraints",
    "CONSTRAINTS": "constraints",
    "SPECIFICATION": "specification",
    "INIT": "init",
    "NEXT": "next",
    "CHECK_DEADLOCK": "check_deadlock",
    "SYMMETRY": "symmetry",
}


def _strip_comments(text: str) -> str:
    text = re.sub(r"\(\*.*?\*\)", " ", text, flags=re.S)
    return "\n".join(line.split("\\*")[0] for line in text.splitlines())


def parse_cfg(path_or_text) -> TlcConfig:
    if isinstance(path_or_text, Path):
        text = path_or_text.read_text()
    elif "\n" not in str(path_or_text) and Path(str(path_or_text)).exists():
        text = Path(str(path_or_text)).read_text()
    else:
        text = str(path_or_text)
    cfg = TlcConfig()
    section = None
    for raw in _strip_comments(text).splitlines():
        line = raw.strip()
        if not line:
            continue
        head = line.split()[0].upper()
        if head in _SECTIONS:
            section = _SECTIONS[head]
            rest = line[len(line.split()[0]) :].strip()
            if not rest:
                continue
            line = rest
        if section == "constants":
            m = re.match(r"(\w+)\s*(?:=|<-)\s*(.+)", line)
            if not m:
                raise ValueError(f"cannot parse constant assignment: {line!r}")
            name, val = m.group(1), m.group(2).strip()
            if val.startswith("{"):
                cfg.constants[name] = [
                    v.strip() for v in val.strip("{} ").split(",") if v.strip()
                ]
            elif re.fullmatch(r"-?\d+", val):
                cfg.constants[name] = int(val)
            else:
                cfg.constants[name] = val  # model value (e.g. Leader = r1)
        elif section == "invariants":
            cfg.invariants.extend(line.split())
        elif section == "constraints":
            cfg.constraints.extend(line.split())
        elif section == "specification":
            cfg.specification = line.split()[0]
        elif section == "check_deadlock":
            cfg.check_deadlock = line.strip().upper() == "TRUE"
        # INIT/NEXT/SYMMETRY: parsed and ignored (corpus uses SPECIFICATION)
    return cfg


# --------------------------------------------------------------------------
# module registry: .cfg + module name -> Model / OracleModel factories
# --------------------------------------------------------------------------

KAFKA_VARIANTS = ("KafkaTruncateToHighWatermark", "Kip101", "Kip279")


def _setlen(v) -> int:
    return len(v) if isinstance(v, list) else int(v)


def resolved_invariants(module: str, cfg) -> tuple:
    """The invariant names, in order, the model built by :func:`build_model`
    for this module+cfg will check — the .cfg order, per-module defaults
    when the .cfg names none, and the fixed built-in TypeOk for the small
    models whose builders take no invariant selection.  The serving path's
    batched verdict replay (service/batch.py) keys on exactly this set, so
    it lives here next to build_model's own resolution rather than as a
    second table that could drift.  Unknown modules raise KeyError, the
    same loud failure build_model gives them."""
    if module in ("IdSequence", "FiniteReplicatedLog"):
        return ("TypeOk",)  # fixed by the builders; cfg selection ignored
    if module in KAFKA_VARIANTS or module in ("Kip320", "Kip320FirstTry"):
        return tuple(cfg.invariants) or ("TypeOk",)
    if module == "AsyncIsr":
        return tuple(cfg.invariants) or ("TypeOk", "ValidHighWatermark")
    raise KeyError(f"unknown module {module!r}")


def _with_names(built, constants):
    """Record the .cfg's replica model-value names (`Replicas = {b1, b2,
    b3}`) in the model's meta so counterexample traces render with the
    config's own vocabulary (utils/pretty), the way TLC echoes the model
    values it was given."""
    names = constants.get("Replicas")
    if isinstance(names, list) and hasattr(built, "meta"):
        built.meta.setdefault("replica_names", list(names))
    return built


def build_model(
    module: str,
    cfg: TlcConfig,
    oracle: bool = False,
    emitted: bool = False,
    reference=None,
    analysis_gate: bool = True,
):
    """Instantiate the tensor model (or its oracle twin) for a TLA+ module
    name under a parsed TLC config.

    reference: explicit reference-checkout path for the emitted builders
    (default: KSPEC_REFERENCE env var, resolved lazily — models/emitted
    .ref_path); `cli validate --reference` threads through here so one
    knob controls both resolutions.

    CONSTRAINT is only meaningful for AsyncIsr in this corpus (its bound is
    driven by the MaxOffset/MaxVersion constants); naming one for any other
    module is rejected rather than silently ignored.

    emitted=True builds the model mechanically from the reference TLA+ text
    (models/emitted — no hand-translated kernels).  Invariant names resolve
    to the corpus-wide intent readings on both paths (LeaderInIsr guarded
    on leader # None, AsyncIsr TypeOk admitting pendingVersion = Nil); the
    literal reference predicates — False at Init — remain available as
    LeaderInIsrLiteral / TypeOkLiteral (PARITY.md)."""
    if emitted and oracle:
        raise ValueError("emitted models have no oracle twin (the oracle IS "
                         "an independent path; use oracle=False)")
    def _sound(built):
        # build-time encoding-soundness gate (analysis; KSPEC_ANALYZE=0
        # disables): an unsound (config, schema) pair refuses to build —
        # `cli check` then exits 2 with the interval counterexample
        # instead of exploring to a wrong verdict (docs/analysis.md).
        # Oracle twins carry no tensor schema and are exempt (their
        # entry points share the AsyncIsr cliff check directly).
        # analysis_gate=False is for callers that run the FULL analysis
        # themselves (`cli analyze` wants the finding list, not the
        # first-HIGH refusal).
        if analysis_gate and not oracle:
            from ..analysis import require_encoding_sound

            require_encoding_sound(built)
        return built

    if cfg.constraints and module != "AsyncIsr":
        raise ValueError(
            f"CONSTRAINT {cfg.constraints} is not supported for module "
            f"{module!r} (only AsyncIsr's bound is defined in this corpus)"
        )
    c = cfg.constants
    if module == "IdSequence":
        if emitted:
            return _sound(_emitted_id_sequence(int(c["MaxId"]), reference))
        from ..models import id_sequence as m

        return _sound((m.make_oracle if oracle else m.make_model)(int(c["MaxId"])))
    if module == "FiniteReplicatedLog":
        if emitted:
            return _sound(_emitted_frl(
                _setlen(c["Replicas"]),
                int(c["LogSize"]),
                _setlen(c["LogRecords"]),
                reference,
            ))
        from ..models import finite_replicated_log as m

        return _sound((m.make_oracle if oracle else m.make_model)(
            _setlen(c["Replicas"]), int(c["LogSize"]), _setlen(c["LogRecords"])
        ))
    if module in KAFKA_VARIANTS or module in ("Kip320", "Kip320FirstTry"):
        from ..models.kafka_replication import Config

        kcfg = Config(
            n_replicas=_setlen(c["Replicas"]),
            log_size=int(c["LogSize"]),
            max_records=int(c["MaxRecords"]),
            max_leader_epoch=int(c["MaxLeaderEpoch"]),
        )
        invs = resolved_invariants(module, cfg)
        if emitted:
            from ..models.emitted import make_emitted_model

            built = make_emitted_model(
                module, kcfg, invariants=invs, reference=reference
            )
        elif module in KAFKA_VARIANTS:
            from ..models import variants as m

            built = (m.make_oracle if oracle else m.make_model)(module, kcfg, invs)
        else:
            from ..models import kip320 as m

            if module == "Kip320":
                built = (m.make_oracle if oracle else m.make_model)(kcfg, invs)
            else:
                built = (
                    m.make_first_try_oracle if oracle else m.make_first_try_model
                )(kcfg, invs)
        # Partitions = K (authored constant, not in the reference): the
        # K-partition product space — the reading of the "5 brokers /
        # 3 partitions" stretch workload (BASELINE.md note; models/product.py)
        built = _with_names(built, c)
        k = _setlen(c.get("Partitions", 1))
        if k > 1:
            from ..models.product import product_model, product_oracle

            built = (product_oracle if oracle else product_model)(built, k)
        return _sound(built)
    if module == "AsyncIsr":
        from ..models import async_isr as m

        acfg = m.AsyncIsrConfig(
            n_replicas=_setlen(c["Replicas"]),
            max_offset=int(c["MaxOffset"]),
            max_version=int(c.get("MaxVersion", c["MaxOffset"])),
        )
        invs = resolved_invariants(module, cfg)
        if emitted:
            from ..models.emitted import make_emitted_async_isr

            return _sound(_with_names(
                make_emitted_async_isr(
                    acfg, invariants=invs, reference=reference
                ),
                c,
            ))
        return _sound(
            _with_names((m.make_oracle if oracle else m.make_model)(acfg, invs), c)
        )
    raise KeyError(f"unknown module {module!r}")


def _emitted_inferred(module: str, consts: dict, name: str, reference=None):
    """Emit a module whose tensor schema is INFERRED from its TypeOk
    (utils/schema_infer) — no per-module mapping code (round-5 verdict
    item 7).  Modules whose state needs a representation choice beyond
    bounds (the message-set encodings of L3/AsyncIsr, PARITY.md) keep
    their curated schemas in models/emitted — the documented override
    hook, not this path."""
    from ..models.emitted import ref_path
    from .schema_infer import infer_schemas, spec_from_schemas
    from .tla_emit import build_model as emit, load_defs
    from .tla_frontend import parse_tla

    ref = ref_path(reference)
    mod = parse_tla(ref / f"{module}.tla")
    defs = load_defs(ref, module)
    schemas = infer_schemas(defs, consts, mod.variables)
    return emit(
        mod, consts, schemas, spec_from_schemas(schemas), name=name
    )


def _emitted_id_sequence(max_id: int, reference=None):
    return _emitted_inferred(
        "IdSequence",
        {"MaxId": max_id},
        f"IdSequence(emitted,{max_id})",
        reference,
    )


def _emitted_frl(n: int, log_size: int, n_records: int, reference=None):
    return _emitted_inferred(
        "FiniteReplicatedLog",
        {
            "Replicas": (0, n - 1),
            "LogRecords": (0, n_records - 1),
            "Nil": -1,
            "LogSize": log_size,
        },
        f"FiniteReplicatedLog(emitted,{n}x{log_size})",
        reference,
    )
