"""TLA+ expression parser -> typed IR (the expression-level front-end).

This is the second half of the front-end (utils/tla_frontend.py parses
module *structure*): a tokenizer and Pratt parser for the TLA+ expression
subset the reference corpus actually uses, producing a small dataclass AST
that utils/tla_emit.py evaluates — concretely (an independent successor
enumerator) and symbolically over jnp arrays (mechanical kernel emission).

Subset covered (everything in Util.tla / IdSequence.tla /
FiniteReplicatedLog.tla, which is also the bulk of the upper layers'
syntax):

  /\\ \\/ ~  = # < > <= >= \\leq \\geq  + - * ..  \\in \\notin \\union \\ (diff)
  \\subseteq  SUBSET S  \\E \\A CHOOSE  IF/THEN/ELSE  LET..IN  DOMAIN
  f[x]  r.field  x'  Op(args)  Alias!Op / Alias!Op(args)  "string"
  [x \\in S |-> e]  [f1 |-> e1, ...]  [f1 : S1, ...]  [S -> T]
  [f EXCEPT ![i].g[j] = e, ...] with @
  {} {e, ...} {e : x \\in S}  {x \\in S : p}  <<e, ...>> (UNCHANGED/vars)

Junction lists (/\\ and \\/ bullet lists) follow the real TLA+
column-fencing rule: a list is identified by the column of its bullets; a
bullet at the same column continues the list, and every token of an item
must sit strictly right of that column — a token at or left of the fence
terminates the item (and the list).  This is what makes
`/\\ \\A f \\in isr : /\\ P /\\ Q` followed by a sibling `/\\ state' = ...`
parse correctly (LeaderIncHighWatermark, KafkaReplication.tla:264-271):
the quantifier body's deeper-indented list cannot absorb the sibling
conjunct.  Tokens carry their source column for this purpose
(parse_definition pads the `Name ==` head with spaces so columns match the
original module text).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Optional


# ---------------------------------------------------------------- AST nodes
@dataclass(frozen=True)
class Num:
    v: int


@dataclass(frozen=True)
class Str:  # "NONE" — model-value strings
    v: str


@dataclass(frozen=True)
class Name:
    id: str


@dataclass(frozen=True)
class Prime:
    base: Any  # Name


@dataclass(frozen=True)
class At:  # EXCEPT's @
    pass


@dataclass(frozen=True)
class Apply:
    op: str
    args: tuple


@dataclass(frozen=True)
class Binop:
    op: str
    a: Any
    b: Any


@dataclass(frozen=True)
class Unop:
    op: str
    a: Any


@dataclass(frozen=True)
class Index:  # f[x]
    base: Any
    idx: Any


@dataclass(frozen=True)
class FieldAcc:  # r.field
    base: Any
    name: str


@dataclass(frozen=True)
class Quant:  # \E / \A  [(var, domain), ...] : body
    kind: str  # "E" | "A"
    binds: tuple
    body: Any


@dataclass(frozen=True)
class Choose:
    var: str
    domain: Any
    body: Any


@dataclass(frozen=True)
class IfThenElse:
    cond: Any
    then: Any
    other: Any


@dataclass(frozen=True)
class Let:  # LET name == e  name2(p) == e2 IN body
    binds: tuple  # ((name, params, expr), ...)
    body: Any


@dataclass(frozen=True)
class FunCons:  # [x \in S |-> e]
    var: str
    domain: Any
    body: Any


@dataclass(frozen=True)
class RecordCons:  # [f |-> e, ...]
    fields: tuple  # ((name, expr), ...)


@dataclass(frozen=True)
class RecordType:  # [f : S, ...]
    fields: tuple


@dataclass(frozen=True)
class FunType:  # [S -> T]
    dom: Any
    rng: Any


@dataclass(frozen=True)
class SetLit:  # {e, ...} ({} = empty)
    elems: tuple


@dataclass(frozen=True)
class SetMap:  # {e : x \in S}
    body: Any
    var: str
    domain: Any


@dataclass(frozen=True)
class SetFilter:  # {x \in S : p}
    var: str
    domain: Any
    pred: Any


@dataclass(frozen=True)
class TupleCons:  # <<e, ...>> — used by UNCHANGED and vars lists
    elems: tuple


@dataclass(frozen=True)
class PowerSet:  # SUBSET S (type positions only in the corpus)
    base: Any


@dataclass(frozen=True)
class Except:  # [f EXCEPT !path = e, ...]
    base: Any
    updates: tuple  # ((path, expr), ...); path = (('f', name)|('i', expr), ...)


@dataclass(frozen=True)
class Domain:  # DOMAIN f
    fn: Any


# ------------------------------------------------- temporal formulas (Spec)
# The corpus states no liveness *properties* (SURVEY.md §2.4): temporal
# syntax appears only inside `Spec` definitions, as `Init /\ [][Next]_vars`
# plus SF_/WF_ fairness conjuncts.  These nodes make that syntax parse (and
# let spec_structure() extract/ignore it per TLC semantics for safety
# checking); nothing evaluates them.
@dataclass(frozen=True)
class ActionSub:  # [A]_sub — action A or stuttering on sub
    action: Any
    sub: str  # subscript text ("vars", "nextId", "logs")


@dataclass(frozen=True)
class Box:  # []F — temporal always
    body: Any


@dataclass(frozen=True)
class Fairness:  # SF_sub(A) / WF_sub(A)
    kind: str  # "SF" | "WF"
    sub: str
    action: Any


# ---------------------------------------------------------------- tokenizer
_TOKEN = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+)
  | (?P<str>"[^"]*")
  | (?P<landop>/\\)
  | (?P<lorop>\\/)
  | (?P<sym>\\leq|\\geq|\\subseteq\b|\\in\b|\\notin\b|\\union\b|\\E\b|\\A\b)
  | (?P<setdiff>\\(?![a-zA-Z]))
  | (?P<dots>\.\.)
  | (?P<arrow>\|->)
  | (?P<funarrow>->)
  | (?P<tup><<|>>)
  | (?P<op><=|>=|\#|=|<|>|\+|-|\*|~|')
  | (?P<punct>[\[\]\(\)\{\},:\.!@])
  | (?P<name>[A-Za-z_]\w*)
    """,
    re.X,
)

_KEYWORDS = {
    "IF",
    "THEN",
    "ELSE",
    "LET",
    "IN",
    "CHOOSE",
    "EXCEPT",
    "DOMAIN",
    "UNCHANGED",
    "SUBSET",
    "TRUE",
    "FALSE",
}


def tokenize(text: str) -> list[tuple[str, str, int]]:
    """-> [(kind, lexeme, column)]; kind in num/name/kw or the lexeme itself.
    Columns are 0-based within the source line (junction-list fencing)."""
    out = []
    pos = 0
    line_start = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m:
            raise SyntaxError(f"cannot tokenize at: {text[pos:pos+40]!r}")
        kind = m.lastgroup
        lex = m.group()
        if kind == "ws":
            nl = lex.rfind("\n")
            if nl >= 0:
                line_start = m.start() + nl + 1
            pos = m.end()
            continue
        col = m.start() - line_start
        pos = m.end()
        if kind == "num":
            out.append(("num", lex, col))
        elif kind == "str":
            out.append(("str", lex[1:-1], col))
        elif kind == "tup":
            out.append((lex, lex, col))
        elif kind == "name":
            out.append(("kw" if lex in _KEYWORDS else "name", lex, col))
        elif kind == "landop":
            out.append(("/\\", lex, col))
        elif kind == "lorop":
            out.append(("\\/", lex, col))
        elif kind == "setdiff":
            out.append(("\\", lex, col))
        elif kind == "sym":
            out.append((lex, lex, col))
        elif kind == "dots":
            out.append(("..", lex, col))
        elif kind == "arrow":
            out.append(("|->", lex, col))
        elif kind == "funarrow":
            out.append(("->", lex, col))
        else:
            out.append((lex, lex, col))
    return out


# ------------------------------------------------------------------- parser
# binding powers (higher binds tighter)
_BP = {
    "\\/": 10,
    "/\\": 20,  # (junction lists are handled by column fences, not BP)
    "=": 30,
    "#": 30,
    "<": 30,
    ">": 30,
    "<=": 30,
    ">=": 30,
    "\\leq": 30,
    "\\geq": 30,
    "\\in": 30,
    "\\notin": 30,
    "\\subseteq": 30,
    "\\union": 40,
    "\\": 40,
    "..": 50,
    "+": 60,
    "-": 60,
    "*": 70,
}
_CANON = {"\\leq": "<=", "\\geq": ">=", "#": "#"}


class _Parser:
    def __init__(self, toks: list[tuple[str, str, int]]):
        self.toks = toks
        self.i = 0
        # column fences of the enclosing junction lists: a token at column
        # <= fence belongs to an enclosing list and is invisible here
        self.fence = [-1]

    def _raw(self, k=0) -> tuple[str, str, int]:
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else ("<eof>", "", -1)

    def peek(self, k=0) -> tuple[str, str]:
        t = self._raw(k)
        if t[2] >= 0 and t[2] <= self.fence[-1]:
            return ("<eof>", "")
        return (t[0], t[1])

    def next(self) -> tuple[str, str]:
        t = self.peek()
        if t[0] != "<eof>":
            self.i += 1
        return t

    def expect(self, kind: str) -> tuple[str, str]:
        t = self.next()
        if t[0] != kind:
            raise SyntaxError(f"expected {kind!r}, got {t} at {self.i}")
        return t

    # -- entry: full expression (handles leading junction lists)
    def parse(self, min_bp: int = 0):
        t = self._raw()
        if t[0] in ("/\\", "\\/") and self.peek()[0] == t[0]:
            op, col = t[0], t[2]
            items = []
            while True:
                t = self._raw()
                if t[0] == op and t[2] == col and self.peek()[0] == op:
                    self.i += 1
                    self.fence.append(col)
                    try:
                        items.append(self.parse(0))
                    finally:
                        self.fence.pop()
                else:
                    break
            lhs = items[0]
            for it in items[1:]:
                lhs = Binop("and" if op == "/\\" else "or", lhs, it)
            # the folded list may itself be an operand (e.g. of an outer \/)
            return self._climb(lhs, min_bp)
        lhs = self.parse_unary()
        return self._climb(lhs, min_bp)

    def _climb(self, lhs, min_bp: int):
        while True:
            kind = self.peek()[0]
            bp = _BP.get(kind)
            if bp is None or bp < min_bp:
                return lhs
            self.next()
            rhs = self.parse(bp + 1)
            op = {"/\\": "and", "\\/": "or"}.get(kind, _CANON.get(kind, kind))
            lhs = Binop(op, lhs, rhs)

    # body of a quantifier / CHOOSE / LET / IF-arm: the column fences make
    # a plain full-expression parse correct (a deeper junction list is
    # terminated by any token at or left of its own bullet column)
    def parse_body(self):
        return self.parse(0)

    def parse_unary(self):
        kind, lex = self.peek()
        if kind == "~":
            self.next()
            return Unop("not", self.parse_unary_postfix())
        if kind == "-":
            self.next()
            return Unop("neg", self.parse_unary_postfix())
        if kind in ("\\E", "\\A"):
            self.next()
            binds = self._parse_binds()
            self.expect(":")
            return Quant(kind[-1], tuple(binds), self.parse_body())
        if kind == "kw" and lex == "CHOOSE":
            self.next()
            var = self.expect("name")[1]
            self.expect("\\in")
            dom = self.parse(0)
            self.expect(":")
            return Choose(var, dom, self.parse_body())
        if kind == "kw" and lex == "IF":
            self.next()
            cond = self.parse_body()
            if self.peek() == ("kw", "THEN"):
                self.next()
            then = self.parse_body()
            if self.peek() == ("kw", "ELSE"):
                self.next()
            other = self.parse_body()
            return IfThenElse(cond, then, other)
        if kind == "kw" and lex == "LET":
            self.next()
            binds = []
            while True:
                nm = self.expect("name")[1]
                params = ()
                if self.peek()[0] == "(":
                    self.next()
                    ps = [self.expect("name")[1]]
                    while self.peek()[0] == ",":
                        self.next()
                        ps.append(self.expect("name")[1])
                    self.expect(")")
                    params = tuple(ps)
                self.expect("=")
                self.expect("=")
                binds.append((nm, params, self.parse(0)))
                nxt = self.peek()
                if nxt == ("kw", "IN"):
                    self.next()
                    break
                if nxt[0] != "name" or self.peek(1)[0] not in ("=", "("):
                    # robustness: treat anything else as the IN body start
                    break
            return Let(tuple(binds), self.parse_body())
        return self.parse_unary_postfix()

    def _parse_binds(self):
        binds = []
        while True:
            # one group: `v1, v2, ... \in Domain` (vars share the domain)
            names = [self.expect("name")[1]]
            while self.peek()[0] == ",":
                self.next()
                names.append(self.expect("name")[1])
            self.expect("\\in")
            dom = self.parse(0)
            binds.extend((v, dom) for v in names)
            if self.peek()[0] == ",":
                self.next()
                continue
            return binds

    def parse_unary_postfix(self):
        return self._postfix(self.parse_primary())

    def _postfix(self, e):
        while True:
            kind = self.peek()[0]
            if kind == ".":
                # field access — but `..` is tokenized separately already
                self.next()
                e = FieldAcc(e, self.expect("name")[1])
            elif kind == "[":
                self.next()
                idx = self.parse(0)
                # f[i, j] — not used by the corpus, keep single index
                self.expect("]")
                e = Index(e, idx)
            elif kind == "'":
                self.next()
                e = Prime(e)
            else:
                return e

    def parse_primary(self):
        kind, lex = self.next()
        if kind == "num":
            return Num(int(lex))
        if kind == "str":
            return Str(lex)
        if kind == "@":
            return At()
        if kind == "kw" and lex in ("TRUE", "FALSE"):
            return Num(1 if lex == "TRUE" else 0)
        if kind == "kw" and lex == "DOMAIN":
            return Domain(self.parse_unary_postfix())
        if kind == "kw" and lex == "SUBSET":
            return PowerSet(self.parse_unary_postfix())
        if kind == "kw" and lex == "UNCHANGED":
            return Apply("UNCHANGED", (self.parse_unary_postfix(),))
        if kind == "name":
            # instance-qualified operator: Alias!Op / Alias!Op(args)
            if self.peek()[0] == "!" and self.peek(1)[0] in ("name", "kw"):
                self.next()
                lex = f"{lex}!{self.next()[1]}"
            if self.peek()[0] == "(":
                self.next()
                args = [self.parse(0)]
                while self.peek()[0] == ",":
                    self.next()
                    args.append(self.parse(0))
                self.expect(")")
                # fairness conjuncts: SF_vars(A) / WF_nextId(A) lex as one
                # name token ("SF_vars") applied to the action
                m = re.match(r"(SF|WF)_(\w+)$", lex)
                if m and len(args) == 1:
                    return Fairness(m.group(1), m.group(2), args[0])
                return Apply(lex, tuple(args))
            return Name(lex)
        if kind == "(":
            e = self.parse(0)
            self.expect(")")
            return e
        if kind == "<<":
            if self.peek()[0] == ">>":
                self.next()
                return TupleCons(())
            elems = [self.parse(0)]
            while self.peek()[0] == ",":
                self.next()
                elems.append(self.parse(0))
            self.expect(">>")
            return TupleCons(tuple(elems))
        if kind == "{":
            if self.peek()[0] == "}":
                self.next()
                return SetLit(())
            # {x \in S : p} — filter form (x must be a bare variable)
            if self.peek()[0] == "name" and self.peek(1)[0] == "\\in":
                var = self.next()[1]
                self.next()
                dom = self.parse(0)
                self.expect(":")
                pred = self.parse(0)
                self.expect("}")
                return SetFilter(var, dom, pred)
            first = self.parse(0)
            if self.peek()[0] == ":":
                # {body : x \in S}
                self.next()
                var = self.expect("name")[1]
                self.expect("\\in")
                dom = self.parse(0)
                self.expect("}")
                return SetMap(first, var, dom)
            elems = [first]
            while self.peek()[0] == ",":
                self.next()
                elems.append(self.parse(0))
            self.expect("}")
            return SetLit(tuple(elems))
        if kind == "[":
            # temporal always: [] F (in the corpus only as [][Next]_vars)
            if self.peek()[0] == "]":
                self.next()
                return Box(self.parse_unary_postfix())
            return self._parse_bracket()
        raise SyntaxError(f"unexpected token {kind!r} {lex!r}")

    def _parse_bracket(self):
        # disambiguate [x \in S |-> e] / [f |-> e, ...] / [f : S, ...]
        # / [S -> T] / [f EXCEPT !... = e]
        if self.peek()[0] == "name":
            nxt = self.peek(1)[0]
            if nxt == "\\in":
                var = self.next()[1]
                self.next()
                dom = self.parse(0)
                self.expect("|->")
                body = self.parse(0)
                self.expect("]")
                return FunCons(var, dom, body)
            if nxt == "|->":
                fields = []
                while True:
                    nm = self.expect("name")[1]
                    self.expect("|->")
                    fields.append((nm, self.parse(0)))
                    if self.peek()[0] == ",":
                        self.next()
                        continue
                    break
                self.expect("]")
                return RecordCons(tuple(fields))
            if nxt == ":":
                fields = []
                while True:
                    nm = self.expect("name")[1]
                    self.expect(":")
                    fields.append((nm, self.parse(0)))
                    if self.peek()[0] == ",":
                        self.next()
                        continue
                    break
                self.expect("]")
                return RecordType(tuple(fields))
        # general expression, then EXCEPT or ->
        e = self.parse(0)
        if self.peek() == ("kw", "EXCEPT"):
            self.next()
            updates = []
            while True:
                self.expect("!")
                path = []
                while True:
                    k = self.peek()[0]
                    if k == ".":
                        self.next()
                        path.append(("f", self.expect("name")[1]))
                    elif k == "[":
                        self.next()
                        path.append(("i", self.parse(0)))
                        self.expect("]")
                    else:
                        break
                self.expect("=")
                updates.append((tuple(path), self.parse(0)))
                if self.peek()[0] == ",":
                    self.next()
                    continue
                break
            self.expect("]")
            return Except(e, tuple(updates))
        if self.peek()[0] == "->":
            self.next()
            rng = self.parse(0)
            self.expect("]")
            return FunType(e, rng)
        self.expect("]")
        # action with stuttering subscript: [A]_vars (Spec bodies)
        nk, nlex = self.peek()
        if nk == "name" and nlex.startswith("_") and len(nlex) > 1:
            self.next()
            return ActionSub(e, nlex[1:])
        raise SyntaxError("unsupported bracket expression")


def spec_structure(ast) -> dict:
    """Decompose a parsed Spec body `Init /\\ [][Next]_sub /\\ SF_/WF_...`
    into {"init": ast, "next": ast, "sub": str,
    "fairness": [(kind, sub, action_ast), ...]}.

    Raises ValueError on a conjunct that is neither the init predicate, the
    boxed next-action, nor a fairness operator — the corpus has no such
    Spec (and a new one should be looked at by a human)."""
    conj = []

    def flat(e):
        if isinstance(e, Binop) and e.op == "and":
            flat(e.a)
            flat(e.b)
        else:
            conj.append(e)

    flat(ast)
    out = {"init": None, "next": None, "sub": None, "fairness": []}
    for c in conj:
        if isinstance(c, Box) and isinstance(c.body, ActionSub):
            out["next"] = c.body.action
            out["sub"] = c.body.sub
        elif isinstance(c, Fairness):
            out["fairness"].append((c.kind, c.sub, c.action))
        elif out["init"] is None and not isinstance(c, (Box, ActionSub)):
            out["init"] = c
        else:
            raise ValueError(f"unrecognized Spec conjunct: {c!r}")
    return out


def parse_expr(text: str):
    """Parse one TLA+ expression into the IR."""
    p = _Parser(tokenize(text))
    e = p.parse(0)
    if p.peek()[0] != "<eof>":
        raise SyntaxError(f"trailing tokens from {p.peek()!r}")
    return e


def parse_definition(body: str):
    """Parse a `Name(params) == expr` definition body (as captured by
    utils/tla_frontend.parse_tla) -> (name, params, ast)."""
    head, expr = body.split("==", 1)
    m = re.match(r"\s*(?:LOCAL\s+)?(\w+)\s*(?:\((.*?)\))?\s*$", head, re.S)
    if not m:
        raise SyntaxError(f"bad definition head: {head!r}")
    name = m.group(1)
    params = tuple(
        x.strip() for x in (m.group(2) or "").split(",") if x.strip()
    )
    # pad the head with spaces so first-line token columns match the module
    # text (junction-list fencing is column-sensitive)
    return name, params, parse_expr(" " * (len(head) + 2) + expr)
