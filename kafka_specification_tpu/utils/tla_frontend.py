"""TLA+ structural front-end: parse module structure, validate the models.

The corpus is only 10 modules, so the kernel layer is hand-translated with
file:line citations (SURVEY.md §7 step 2 explicitly defers a full TLA+
expression parser).  What this module provides is the *auditable* half of a
front-end: a tokenizer/parser for TLA+ module structure —

    module name, EXTENDS, CONSTANTS, VARIABLES,
    top-level operator definitions (`Name == ...` / `Name(args) == ...`),
    the disjunct list of each `Next` definition,
    INSTANCE ... WITH substitutions,

— plus `validate_model`, which cross-checks a tensor model's action list
against the `Next` disjuncts of the reference module it claims to implement
(following the EXTENDS chain for inherited definitions).  This runs in the
test suite against /root/reference, so any drift between the reference corpus
and the hand-translated kernels is caught mechanically.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class TlaModule:
    name: str
    extends: list = field(default_factory=list)
    constants: list = field(default_factory=list)
    variables: list = field(default_factory=list)
    definitions: dict = field(default_factory=dict)  # name -> body text
    instances: dict = field(default_factory=dict)  # alias -> (module, {subs})
    local_defs: set = field(default_factory=set)  # LOCAL names (not inherited)
    theorems: list = field(default_factory=list)  # THEOREM statement texts

    def spec_structure(self, name: str = "Spec") -> dict | None:
        """Parsed temporal structure of this module's Spec definition:
        {"init", "next", "sub", "fairness": [(SF|WF, sub, action_ast)]}.

        TLC ignores fairness for safety checking (SURVEY.md §2.4: every
        Spec in the corpus carries SF/WF conjuncts but no liveness property
        is ever stated) — this parses and records them so the front-end
        reads the whole corpus; nothing evaluates them.
        """
        if name not in self.definitions:
            return None
        from .tla_expr import parse_definition, spec_structure

        _, _, ast = parse_definition(self.definitions[name])
        return spec_structure(ast)

    def liveness_theorems(self) -> list[str]:
        """THEOREM statements that assert anything beyond `Spec => []Inv` /
        `Spec => Inv` (an invariant under the standard safety reading).
        Empty for the whole reference corpus — asserted by tests, making
        SURVEY.md §2.4's 'safety-only checker suffices' claim checkable."""
        out = []
        for t in self.theorems:
            if not re.match(r"\s*Spec\s*=>\s*(\[\])?\w+\s*$", t):
                out.append(t)
        return out


_COMMENT_BLOCK = re.compile(r"\(\*.*?\*\)", re.S)
_COMMENT_LINE = re.compile(r"\\\*.*")
_MODULE_HEAD = re.compile(r"-{4,}\s*MODULE\s+(\w+)\s*-{4,}")
_DEF_HEAD = re.compile(
    r"^(?:LOCAL\s+)?(\w+)(?:\((.*?)\))?\s*==", re.M
)
_INSTANCE = re.compile(
    r"^(?:LOCAL\s+)?(\w+)\s*==\s*INSTANCE\s+(\w+)(?:\s+WITH\s+(.*))?", re.M
)


def _parse_withs(withs: str) -> dict:
    """`x <- expr, y <- expr` -> {name: rhs_text}, splitting only at
    top-level commas (an RHS like `{1, 2}` or `Max(a, b)` stays whole)."""
    parts = []
    depth = 0
    cur = []
    i = 0
    while i < len(withs):
        two = withs[i : i + 2]
        if two in ("<<", ">>"):
            depth += 1 if two == "<<" else -1
            cur.append(two)
            i += 2
            continue
        ch = withs[i]
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    if cur:
        parts.append("".join(cur))
    subs = {}
    for p in parts:
        m = re.match(r"\s*(\w+)\s*<-\s*(.+?)\s*$", p, re.S)
        if not m:
            raise ValueError(f"malformed WITH substitution: {p!r}")
        subs[m.group(1)] = m.group(2)
    return subs


def parse_tla(path_or_text) -> TlaModule:
    text = (
        Path(path_or_text).read_text()
        if isinstance(path_or_text, Path)
        or ("\n" not in str(path_or_text) and Path(str(path_or_text)).exists())
        else str(path_or_text)
    )
    # blank comments to spaces (not empty) — expression parsing is
    # column-sensitive (junction-list fencing, tla_expr) and definition
    # bodies are sliced by character offset
    text = _COMMENT_BLOCK.sub(lambda m: re.sub(r"[^\n]", " ", m.group(0)), text)
    text = _COMMENT_LINE.sub(lambda m: " " * len(m.group(0)), text)

    m = _MODULE_HEAD.search(text)
    if not m:
        raise ValueError("no MODULE header found")
    mod = TlaModule(name=m.group(1))
    body = text[m.end() :].split("====")[0]

    ext = re.search(r"\bEXTENDS\s+([\w,\s]+?)(?:\n\s*\n|\n(?=\S))", body)
    if ext:
        mod.extends = [x.strip() for x in ext.group(1).split(",") if x.strip()]

    for kw, target in (("CONSTANTS?", mod.constants), ("VARIABLES?", mod.variables)):
        km = re.search(rf"\b{kw}\b\s*((?:\w+\s*,\s*)*\w+)", body)
        if km:
            target.extend(
                x.strip() for x in km.group(1).replace("\n", " ").split(",") if x.strip()
            )

    # top-level definitions: find each `Name ==` at line start, body runs to
    # the next definition head, truncated at any declaration block (ASSUME /
    # THEOREM / VARIABLES / CONSTANTS) that sits between two definitions
    decl = re.compile(
        r"^\s*(?:ASSUME|ASSUMPTION|AXIOM|THEOREM|VARIABLES?|CONSTANTS?)\b", re.M
    )
    heads = [(m.start(), m.group(1)) for m in _DEF_HEAD.finditer(body)]
    for (start, name), nxt in zip(heads, heads[1:] + [(len(body), None)]):
        text = body[start : nxt[0]]
        dm = decl.search(text, re.match(r"\s*(?:LOCAL\s+)?\w+", text).end())
        mod.definitions[name] = text[: dm.start()] if dm else text
        if re.match(r"\s*LOCAL\b", text):
            mod.local_defs.add(name)

    for im in _INSTANCE.finditer(body):
        alias, target, withs = im.group(1), im.group(2), im.group(3) or ""
        mod.instances[alias] = (target, _parse_withs(withs))
        mod.definitions.pop(alias, None)

    # THEOREM statements (single-line in the corpus): the module's stated
    # correctness claims, e.g. `Spec => []StrongIsr` (Kip320.tla:168-171)
    for tm in re.finditer(r"^\s*THEOREM\s+(.+?)\s*$", body, re.M):
        mod.theorems.append(tm.group(1))

    return mod


def next_disjuncts(mod: TlaModule, name: str = "Next", known: set | None = None) -> list[str]:
    """Action operator names of a Next definition.

    Primary form: top-level disjuncts `\\/ Name` (all Kafka-family variants).
    Fallback for quantified bodies (`Next == \\E x \\in S : Action(x)`, as in
    IdSequence/FiniteReplicatedLog): every applied/bare operator name in the
    body that is a known module definition, in order of first appearance.
    """
    body = mod.definitions.get(name)
    if body is None:
        raise KeyError(f"{mod.name} has no definition {name}")
    body = body.split("==", 1)[1]
    # top-level disjuncts: plain `\/ Name` or quantified
    # `\/ \E x \in S, ... : Name(args)` (mixed forms supported)
    names = [
        m.group(1) or m.group(2)
        for m in re.finditer(
            r"\\/\s*(?:(\w+)|\\E[^:]*:\s*(\w+)\s*\()", body
        )
    ]
    if names:
        return names
    known = known if known is not None else set(mod.definitions)
    known = known - {name}
    out = []
    # applications only — bare known names in quantifier domains (`\in IdSet`)
    # are value operators, not actions
    for tok in re.findall(r"\b(\w+)\s*\(", body):
        if tok in known and tok not in out:
            out.append(tok)
    return out


def load_chain(ref_dir, module: str) -> dict[str, TlaModule]:
    """Parse `module` and its EXTENDS ancestors from ref_dir."""
    ref_dir = Path(ref_dir)
    seen: dict[str, TlaModule] = {}

    def visit(name):
        if name in seen or not (ref_dir / f"{name}.tla").exists():
            return
        m = parse_tla(ref_dir / f"{name}.tla")
        seen[name] = m
        for e in m.extends:
            visit(e)

    visit(module)
    return seen


def defined_names(chain: dict[str, TlaModule]) -> set[str]:
    out = set()
    for m in chain.values():
        out.update(m.definitions)
    return out


# constants a .cfg may set that are authored by this framework rather than
# declared by the reference modules (documented in configs/)
AUTHORED_CONSTANTS = {"Partitions", "MaxVersion"}


def validate_cfg_constants(tlc_cfg, ref_dir, module: str) -> list[str]:
    """TLC refuses to run with unassigned CONSTANTS; mirror that check.

    Returns discrepancies: declared-but-unassigned constants (following the
    EXTENDS chain; INSTANCE-substituted constants of instanced modules are
    bound inside the spec and not required), and assigned names that are
    neither declared nor framework-authored (likely typos).
    """
    chain = load_chain(ref_dir, module)
    if module not in chain:
        return [f"reference module {module} not found under {ref_dir}"]
    declared = set()
    for m in chain.values():
        declared.update(m.constants)
    # constants of INSTANCE'd modules are bound by WITH substitution
    instanced = set()
    for m in chain.values():
        for target, _subs in m.instances.values():
            if target in chain:
                instanced.update(chain[target].constants)
    required = declared - instanced
    assigned = set(tlc_cfg.constants)
    problems = []
    for name in sorted(required - assigned):
        problems.append(f"CONSTANT {name} is declared by {module}'s chain but unassigned")
    for name in sorted(assigned - declared - AUTHORED_CONSTANTS):
        problems.append(f"cfg assigns {name}, which no module in the chain declares")
    return problems


def validate_model(model, ref_dir, module: str) -> list[str]:
    """Cross-check a tensor model's actions against the reference module's
    Next disjuncts.  Returns a list of discrepancy strings (empty = clean).

    The model's action names must cover exactly the reference Next
    disjuncts (order preserved is not required by TLC semantics and not
    enforced); every disjunct must resolve to a definition somewhere in
    the EXTENDS chain.  Mechanically emitted models split a disjunct's
    top-level nondeterminism into DNF branches named `Name~k`
    (utils/tla_emit); each branch maps back to its source disjunct, so
    both the hand and the emitted action inventories validate against the
    same reference Next.
    """
    chain = load_chain(ref_dir, module)
    if module not in chain:
        return [f"reference module {module} not found under {ref_dir}"]
    names = defined_names(chain)
    disjuncts = next_disjuncts(chain[module], known=names)
    problems = []
    for d in disjuncts:
        if d not in names:
            problems.append(f"Next disjunct {d} has no definition in the chain")
    names_raw = [a.name for a in model.actions]
    if any("~" in n for n in names_raw):
        # emitted model: `Name~k` DNF branches -> source disjunct `Name`;
        # several branches per disjunct are expected, so compare coverage
        model_actions = {n.split("~")[0] for n in names_raw}
        mismatch = model_actions != set(disjuncts)
    else:
        # hand model: exact multiset — a duplicated or missing action name
        # is a defect even when the name set still matches
        model_actions = set(names_raw)
        mismatch = sorted(names_raw) != sorted(disjuncts)
    if mismatch:
        missing = set(disjuncts) - model_actions
        extra = model_actions - set(disjuncts)
        if missing:
            problems.append(f"model lacks reference actions: {sorted(missing)}")
        if extra:
            problems.append(f"model has non-reference actions: {sorted(extra)}")
        if not missing and not extra:
            problems.append(
                f"action multiset differs from Next disjuncts: "
                f"{sorted(names_raw)} vs {sorted(disjuncts)}"
            )
    return problems
