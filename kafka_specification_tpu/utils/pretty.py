"""TLA-style rendering of canonical states for counterexample traces.

The engines' decoded states are canonical Python tuples (compact, hashable,
comparable with the oracle); this module renders them the way TLC prints a
state — named records, one variable per line — so a counterexample reads
like the reference spec's own vocabulary.  When the driving .cfg declared
replica model values (`Replicas = {b1, b2, b3}`), those exact names are
used (meta["replica_names"], plumbed by utils/cfg.build_model); otherwise
replicas render as b0..bN-1.
"""

from __future__ import annotations


def _namer(model_meta: dict):
    """replica index -> display name, honouring the .cfg's model values."""
    names = model_meta.get("replica_names")
    if names:
        return lambda r: names[r] if 0 <= r < len(names) else f"b{r}"
    return lambda r: f"b{r}"


def _set(s, nm):
    return "{" + ", ".join(nm(r) for r in sorted(s)) + "}"


def _opt(v, nm):
    return "None" if v == -1 else nm(v)


def render_kafka_state(state, nm=None) -> str:
    """Canonical KafkaReplication-family state -> TLA-like record text
    (field names per /root/reference/KafkaReplication.tla:45-75)."""
    nm = nm or (lambda r: f"b{r}")
    logs, rstates, nrid, nep, reqs, (qep, qldr, qisr) = state
    lines = []
    log_txt = ", ".join(
        f"{nm(r)} :> <<"
        + ", ".join(f"[id|->{i}, epoch|->{e}]" for i, e in log)
        + ">>"
        for r, log in enumerate(logs)
    )
    lines.append(f"replicaLog = ({log_txt})")
    rs_txt = ", ".join(
        f"{nm(r)} :> [hw|->{hw}, leaderEpoch|->{ep}, leader|->{_opt(ldr, nm)}, isr|->{_set(isr, nm)}]"
        for r, (hw, ep, ldr, isr) in enumerate(rstates)
    )
    lines.append(f"replicaState = ({rs_txt})")
    lines.append(f"nextRecordId = {nrid}")
    lines.append(f"nextLeaderEpoch = {nep}")
    req_txt = ", ".join(
        f"[leaderEpoch|->{e}, leader|->{_opt(l, nm)}, isr|->{_set(isr, nm)}]"
        for e, l, isr in sorted(reqs)
    )
    lines.append(f"leaderAndIsrRequests = {{{req_txt}}}")
    lines.append(
        f"quorumState = [leaderEpoch|->{qep}, leader|->{_opt(qldr, nm)}, isr|->{_set(qisr, nm)}]"
    )
    return "\n".join("  " + ln for ln in lines)


def render_async_isr_state(state, nm=None) -> str:
    """Canonical AsyncIsr state -> TLA-like record text (AsyncIsr.tla:31-56)."""
    nm = nm or (lambda r: f"b{r}")
    (c_isr, c_ver), (l_isr, l_ver, pend, pver, offs), reqs, upds = state
    lines = [
        f"controllerState = [isr|->{_set(c_isr, nm)}, version|->{c_ver}]",
        f"leaderState = [isr|->{_set(l_isr, nm)}, version|->{l_ver}, "
        f"pendingIsr|->{_set(pend, nm)}, pendingVersion|->{pver}, "
        f"offsets|->({', '.join(f'{nm(r)} :> {o}' for r, o in enumerate(offs))})]",
        "requests = {"
        + ", ".join(
            f"[isr|->{_set(isr, nm)}, version|->{v}]"
            for isr, v in sorted(reqs, key=str)
        )
        + "}",
        "updates = {"
        + ", ".join(
            f"[isr|->{_set(isr, nm)}, version|->{v}]"
            for isr, v in sorted(upds, key=str)
        )
        + "}",
    ]
    return "\n".join("  " + ln for ln in lines)


def render_state(model_meta: dict, state) -> str:
    """Dispatch on the model family; fall back to repr."""
    variant = model_meta.get("variant", "")
    nm = _namer(model_meta)
    try:
        if "partitions" in model_meta:
            sub_meta = {
                k: v for k, v in model_meta.items() if k != "partitions"
            }
            parts = [
                f"  partition {p}:\n" + render_state(sub_meta, sub)
                for p, sub in enumerate(state)
            ]
            return "\n".join(parts)
        if variant == "AsyncIsr":
            return render_async_isr_state(state, nm)
        if variant in (
            "KafkaTruncateToHighWatermark",
            "Kip101",
            "Kip279",
            "Kip320",
            "Kip320FirstTry",
        ):
            return render_kafka_state(state, nm)
    except Exception:
        pass
    return "  " + repr(state)


def render_trace(model_meta: dict, trace) -> str:
    """Numbered TLC-style counterexample trace."""
    out = []
    for i, (action, state) in enumerate(trace):
        head = "Initial predicate" if action == "<init>" else f"Action {action}"
        out.append(f"State {i + 1}: <{head}>")
        out.append(render_state(model_meta, state))
    return "\n".join(out)
