"""TLA-style rendering of canonical states for counterexample traces.

The engines' decoded states are canonical Python tuples (compact, hashable,
comparable with the oracle); this module renders them the way TLC prints a
state — named records, one variable per line — so a counterexample reads
like the reference spec's own vocabulary.
"""

from __future__ import annotations


def _set(s):
    return "{" + ", ".join(f"b{r}" for r in sorted(s)) + "}"


def _opt(v, prefix="b"):
    return "None" if v == -1 else f"{prefix}{v}"


def render_kafka_state(state) -> str:
    """Canonical KafkaReplication-family state -> TLA-like record text
    (field names per /root/reference/KafkaReplication.tla:45-75)."""
    logs, rstates, nrid, nep, reqs, (qep, qldr, qisr) = state
    lines = []
    log_txt = ", ".join(
        f"b{r} :> <<"
        + ", ".join(f"[id|->{i}, epoch|->{e}]" for i, e in log)
        + ">>"
        for r, log in enumerate(logs)
    )
    lines.append(f"replicaLog = ({log_txt})")
    rs_txt = ", ".join(
        f"b{r} :> [hw|->{hw}, leaderEpoch|->{ep}, leader|->{_opt(ldr)}, isr|->{_set(isr)}]"
        for r, (hw, ep, ldr, isr) in enumerate(rstates)
    )
    lines.append(f"replicaState = ({rs_txt})")
    lines.append(f"nextRecordId = {nrid}")
    lines.append(f"nextLeaderEpoch = {nep}")
    req_txt = ", ".join(
        f"[leaderEpoch|->{e}, leader|->{_opt(l)}, isr|->{_set(isr)}]"
        for e, l, isr in sorted(reqs)
    )
    lines.append(f"leaderAndIsrRequests = {{{req_txt}}}")
    lines.append(
        f"quorumState = [leaderEpoch|->{qep}, leader|->{_opt(qldr)}, isr|->{_set(qisr)}]"
    )
    return "\n".join("  " + ln for ln in lines)


def render_async_isr_state(state) -> str:
    """Canonical AsyncIsr state -> TLA-like record text (AsyncIsr.tla:31-56)."""
    (c_isr, c_ver), (l_isr, l_ver, pend, pver, offs), reqs, upds = state
    lines = [
        f"controllerState = [isr|->{_set(c_isr)}, version|->{c_ver}]",
        f"leaderState = [isr|->{_set(l_isr)}, version|->{l_ver}, "
        f"pendingIsr|->{_set(pend)}, pendingVersion|->{pver}, "
        f"offsets|->({', '.join(f'b{r} :> {o}' for r, o in enumerate(offs))})]",
        "requests = {"
        + ", ".join(
            f"[isr|->{_set(isr)}, version|->{v}]" for isr, v in sorted(reqs, key=str)
        )
        + "}",
        "updates = {"
        + ", ".join(
            f"[isr|->{_set(isr)}, version|->{v}]" for isr, v in sorted(upds, key=str)
        )
        + "}",
    ]
    return "\n".join("  " + ln for ln in lines)


def render_state(model_meta: dict, state) -> str:
    """Dispatch on the model family; fall back to repr."""
    variant = model_meta.get("variant", "")
    try:
        if "partitions" in model_meta:
            parts = [
                f"  partition {p}:\n" + render_state({"variant": variant}, sub)
                for p, sub in enumerate(state)
            ]
            return "\n".join(parts)
        if variant == "AsyncIsr":
            return render_async_isr_state(state)
        if variant in (
            "KafkaTruncateToHighWatermark",
            "Kip101",
            "Kip279",
            "Kip320",
            "Kip320FirstTry",
        ):
            return render_kafka_state(state)
    except Exception:
        pass
    return "  " + repr(state)


def render_trace(model_meta: dict, trace) -> str:
    """Numbered TLC-style counterexample trace."""
    out = []
    for i, (action, state) in enumerate(trace):
        head = "Initial predicate" if action == "<init>" else f"Action {action}"
        out.append(f"State {i + 1}: <{head}>")
        out.append(render_state(model_meta, state))
    return "\n".join(out)
