"""Concrete (set-semantics) evaluator for the TLA+ expression IR.

The third, fully independent execution path for the parsed modules (next to
the hand-written kernels/oracles and the mechanically emitted kernels of
utils/tla_emit.py): evaluates the IR directly over Python values the way
TLC's interpreter does — records as dicts, functions as {index: value}
dicts, sets as frozensets, CHOOSE by deterministic search — and enumerates
action successors by trying every witness of every existential.

Used by tests to cross-check all three paths on exact state sets; also
demonstrates Util's Min/Max/Range working straight from their CHOOSE-based
definitions (Util.tla:22-24) with no hand translation at all.
"""

from __future__ import annotations

from typing import Any, Iterator

from . import tla_expr as E


def _freeze(v):
    """Hashable canonical form of a concrete TLA value (for state sets)."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, frozenset):
        return frozenset(_freeze(x) for x in v)
    return v


def _thaw(v):
    """Undo _freeze on record values pulled back out of sets (frozen records
    are (name, value) pair tuples; field access needs dicts again)."""
    if (
        isinstance(v, tuple)
        and v
        and all(
            isinstance(x, tuple) and len(x) == 2 and isinstance(x[0], str)
            for x in v
        )
    ):
        return {k: _thaw(x) for k, x in v}
    return v


class ConcreteEval:
    def __init__(self, defs: dict, consts: dict):
        self.defs = defs  # name -> (params, ast)
        self.consts = consts  # name -> int | frozenset

    def eval(self, ast, env: dict) -> Any:
        ev = self.eval
        if isinstance(ast, E.Num):
            return ast.v
        if isinstance(ast, E.Str):
            return ast.v
        if isinstance(ast, E.TupleCons):
            return tuple(ev(x, env) for x in ast.elems)
        if isinstance(ast, E.At):
            return env["@"]
        if isinstance(ast, E.Name):
            if ast.id in env:
                return env[ast.id]
            if ast.id in self.consts:
                return self.consts[ast.id]
            params, body = self.defs[ast.id]
            if params:
                raise TypeError(f"{ast.id} needs arguments")
            return ev(body, env)
        if isinstance(ast, E.Apply):
            params, body = self.defs[ast.op]
            sub = dict(env)
            sub.update(zip(params, (ev(a, env) for a in ast.args)))
            return ev(body, sub)
        if isinstance(ast, E.Let):
            sub = dict(env)
            for name, params, expr in ast.binds:
                if params:
                    raise NotImplementedError("parameterized LET")
                sub[name] = ev(expr, sub)
            return ev(ast.body, sub)
        if isinstance(ast, E.Unop):
            a = ev(ast.a, env)
            return (not a) if ast.op == "not" else -a
        if isinstance(ast, E.Binop):
            op = ast.op
            if op == "and":
                return bool(ev(ast.a, env)) and bool(ev(ast.b, env))
            if op == "or":
                return bool(ev(ast.a, env)) or bool(ev(ast.b, env))
            a = ev(ast.a, env)
            if op == "\\in":
                return self._member(a, ev(ast.b, env))
            if op == "\\notin":
                return not self._member(a, ev(ast.b, env))
            b = ev(ast.b, env)
            if op == "..":
                return frozenset(range(a, b + 1))
            if op == "\\union":
                return frozenset(_freeze(x) for x in a) | frozenset(
                    _freeze(x) for x in b
                )
            if op == "\\":
                return frozenset(_freeze(x) for x in a) - frozenset(
                    _freeze(x) for x in b
                )
            if op == "\\subseteq":
                return all(self._member(x, b) for x in a)
            if op == "=":
                return _freeze(a) == _freeze(b)
            if op == "#":
                return _freeze(a) != _freeze(b)
            return {
                "+": lambda: a + b,
                "-": lambda: a - b,
                "*": lambda: a * b,
                "<": lambda: a < b,
                ">": lambda: a > b,
                "<=": lambda: a <= b,
                ">=": lambda: a >= b,
            }[op]()
        if isinstance(ast, E.Index):
            return ev(ast.base, env)[ev(ast.idx, env)]
        if isinstance(ast, E.FieldAcc):
            return ev(ast.base, env)[ast.name]
        if isinstance(ast, E.IfThenElse):
            return (
                ev(ast.then, env) if ev(ast.cond, env) else ev(ast.other, env)
            )
        if isinstance(ast, E.Quant):
            def q(binds, env):
                if not binds:
                    return bool(ev(ast.body, env))
                (var, dom), rest = binds[0], binds[1:]
                elems = ev(dom, env)
                if ast.kind == "A":
                    return all(q(rest, {**env, var: _thaw(e)}) for e in elems)
                return any(q(rest, {**env, var: _thaw(e)}) for e in elems)

            return q(list(ast.binds), env)
        if isinstance(ast, E.Choose):
            dom = ev(ast.domain, env)
            for e in sorted(dom, key=_freeze):
                if ev(ast.body, {**env, ast.var: _thaw(e)}):
                    return _thaw(e)
            raise ValueError("CHOOSE: no witness")
        if isinstance(ast, E.FunCons):
            dom = ev(ast.domain, env)
            return {e: ev(ast.body, {**env, ast.var: e}) for e in dom}
        if isinstance(ast, E.RecordCons):
            return {n: ev(x, env) for n, x in ast.fields}
        if isinstance(ast, E.RecordType):
            return ("__rectype__", {n: ev(x, env) for n, x in ast.fields})
        if isinstance(ast, E.FunType):
            return ("__funtype__", ev(ast.dom, env), ev(ast.rng, env))
        if isinstance(ast, E.SetLit):
            return frozenset(_freeze(ev(x, env)) for x in ast.elems)
        if isinstance(ast, E.SetMap):
            dom = ev(ast.domain, env)
            return frozenset(
                _freeze(ev(ast.body, {**env, ast.var: _thaw(e)})) for e in dom
            )
        if isinstance(ast, E.SetFilter):
            dom = ev(ast.domain, env)
            return frozenset(
                _freeze(e)
                for e in dom
                if ev(ast.pred, {**env, ast.var: _thaw(e)})
            )
        if isinstance(ast, E.PowerSet):
            from itertools import combinations

            base = [_freeze(x) for x in ev(ast.base, env)]
            return frozenset(
                frozenset(c)
                for k in range(len(base) + 1)
                for c in combinations(base, k)
            )
        if isinstance(ast, E.Domain):
            return frozenset(ev(ast.fn, env).keys())
        if isinstance(ast, E.Except):
            # [f EXCEPT !p1 = e1, !p2 = e2] is nested single updates
            # ([[f EXCEPT !p1 = e1] EXCEPT !p2 = e2]), so each update's @
            # (and old value) sees the result of the previous one
            out = _deep_copy(ev(ast.base, env))
            for path, expr in ast.updates:
                orig = out
                steps = []
                for kind, x in path:
                    key = x if kind == "f" else ev(x, env)
                    steps.append(key)
                    orig = orig[key]
                tgt = out
                for key in steps[:-1]:
                    tgt = tgt[key]
                tgt[steps[-1]] = ev(expr, {**env, "@": orig})
            return out
        raise NotImplementedError(type(ast).__name__)

    def _member(self, v, s) -> bool:
        if isinstance(s, tuple) and s and s[0] == "__rectype__":
            return isinstance(v, dict) and all(
                self._member(v[n], fs) for n, fs in s[1].items()
            )
        if isinstance(s, tuple) and s and s[0] == "__funtype__":
            return (
                isinstance(v, dict)
                and frozenset(v.keys()) == frozenset(s[1])
                and all(self._member(x, s[2]) for x in v.values())
            )
        return _freeze(v) in frozenset(_freeze(x) for x in s)

    # ------------------------------------------------ successor enumeration
    def successors(self, action_ast, env: dict) -> Iterator[dict]:
        """All {var: value} primed assignments for which the action body can
        hold, one per existential-witness combination that satisfies it."""
        yield from self._sat(action_ast, env, {})

    def _sat(self, ast, env, primes) -> Iterator[dict]:
        if isinstance(ast, E.Binop) and ast.op == "and":
            for p1 in self._sat(ast.a, env, primes):
                yield from self._sat(ast.b, env, p1)
            return
        if isinstance(ast, E.Binop) and ast.op == "or":
            yield from self._sat(ast.a, env, primes)
            yield from self._sat(ast.b, env, primes)
            return
        if isinstance(ast, E.Quant) and ast.kind == "E":
            def q(binds, env):
                if not binds:
                    yield from self._sat(ast.body, env, primes)
                    return
                (var, dom), rest = binds[0], binds[1:]
                for e in sorted(self.eval(dom, env), key=_freeze):
                    yield from q(rest, {**env, var: _thaw(e)})

            yield from q(list(ast.binds), env)
            return
        if (
            isinstance(ast, E.Binop)
            and ast.op == "="
            and isinstance(ast.a, E.Prime)
            and isinstance(ast.a.base, E.Name)
        ):
            var = ast.a.base.id
            val = self.eval(ast.b, env)
            if var in primes:
                if _freeze(primes[var]) == _freeze(val):
                    yield primes
                return
            yield {**primes, var: val}
            return
        if isinstance(ast, E.Apply):
            params, body = self.defs[ast.op]
            sub = dict(env)
            sub.update(zip(params, (self.eval(a, env) for a in ast.args)))
            yield from self._sat(body, sub, primes)
            return
        if isinstance(ast, E.Let):
            sub = dict(env)
            for name, params, expr in ast.binds:
                sub[name] = self.eval(expr, sub)
            yield from self._sat(ast.body, sub, primes)
            return
        # plain boolean conjunct
        if self.eval(ast, env):
            yield primes


def _deep_copy(v):
    if isinstance(v, dict):
        return {k: _deep_copy(x) for k, x in v.items()}
    return v
