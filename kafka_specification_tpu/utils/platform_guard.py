"""Single source of truth for this environment's platform-selection quirks.

Three facts every entry point (CLI, bench.py, scripts/) must know:

1. sitecustomize force-registers the `axon` TPU plugin whenever
   PALLAS_AXON_POOL_IPS is set, and force-sets jax_platforms="axon,cpu" at
   the *config* level — so the JAX_PLATFORMS env var alone cannot pin CPU.
2. The axon tunnel can wedge PJRT client init indefinitely, and (observed
   round 2) can also pass a quick `jax.devices()` probe and then hang the
   very next operation — so a guard must cover the first real computation,
   not just backend init.
3. Import of jax is safe (no backend init); `jax.devices()` / the first
   dispatch is where a wedge bites.

Keep every copy of this knowledge here; cli.py and bench.py both build
their guarded children from these helpers.
"""

from __future__ import annotations

import os


def cpu_env(base_env=None) -> dict:
    """A child-process environment pinned to CPU and kept off the tunnel.

    Popping PALLAS_AXON_POOL_IPS makes sitecustomize skip axon plugin
    registration entirely, at which point JAX_PLATFORMS=cpu is honored.
    """
    env = dict(os.environ if base_env is None else base_env)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def pin_cpu_in_process() -> None:
    """Pin the CURRENT process to CPU (for --cpu flags / scripts).

    Must run before anything initializes an XLA backend; works even when
    sitecustomize already forced jax_platforms="axon,cpu" (the config
    update wins as long as no backend exists yet).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")


def reassert_env_pin() -> None:
    """Re-assert a JAX_PLATFORMS env pin at the config level (fact 1)."""
    pinned = os.environ.get("JAX_PLATFORMS")
    if pinned:
        import jax

        jax.config.update("jax_platforms", pinned)


def platform_ready_probe() -> str:
    """Force backend init AND one tiny end-to-end computation; returns the
    platform name.  A wedged tunnel hangs in here — callers run this in a
    killable child (fact 2: `jax.devices()` alone is not a sufficient
    probe; the first compile/execute must also survive)."""
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    jax.block_until_ready(jax.jit(lambda x: x + 1)(jnp.zeros((8,), jnp.int32)))
    return platform
