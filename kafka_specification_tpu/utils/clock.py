"""The one injectable clock/sleep boundary for the jax-free control plane.

Every timing decision the service plane makes — lease stamps and expiry
math, heartbeat freshness, retry backoff, poll sleeps, supervisor stall
detection — used to call ``time.time()`` / ``time.monotonic()`` /
``time.sleep()`` raw, which made that plane untestable except in real
time: a lease-expiry race takes ``lease_ttl`` wall seconds to stage, and
an interleaving that needs a 40-second clock jump cannot be staged at
all.  This module is the seam that fixes it, the clock twin of
``durable_io``'s recorder: control-plane modules call
:func:`now`/:func:`monotonic`/:func:`sleep` here, and a harness
(``resilience/simfleet``) installs a virtual clock that owns time
wholesale — same production code, simulated schedule.

With the default :class:`SystemClock` installed every call is a direct
pass-through to ``time`` — one attribute hop of overhead, zero behavior
change.  The pass-through resolves ``time.time``/``time.sleep`` at call
time, so tests that monkeypatch attributes on the ``time`` module keep
working unchanged through the shim.

The ``raw-clock`` lint (``analysis/clock_lint.py``, wired into ``cli
analyze``) pins the boundary: a raw ``time.time()``/``time.sleep()``/
``time.monotonic()`` in a clock-migrated module is a HIGH finding unless
the site carries a reasoned ``# kspec: allow(raw-clock)`` tag.

Clock contract:

``now()``        wall-clock seconds (the thing cross-host metadata
                 stamps carry: lease_unix, heartbeat unix, route `at`)
``monotonic()``  monotonic seconds for local deadlines and durations
                 (never compared across processes or hosts)
``sleep(s)``     blocks for ``s`` seconds — a virtual clock advances
                 its own time instead, so a retry backoff or a poll
                 loop costs simulated time, not wall time

Leaf contract: stdlib-only, zero intra-package imports (imported by
``durable_io``-adjacent leaves like ``resilience/heartbeat.py``).
"""

from __future__ import annotations

import time as _time

__all__ = [
    "Clock", "SystemClock", "SYSTEM",
    "install", "get", "now", "monotonic", "sleep",
]


class Clock:
    """The interface a virtual clock implements (duck-typed; this base
    doubles as documentation).  All three methods are required."""

    def now(self) -> float:
        raise NotImplementedError

    def monotonic(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class SystemClock(Clock):
    """Real time.  Late-bound lookups on the ``time`` module so test
    monkeypatching of ``time.sleep``/``time.time`` still intercepts."""

    def now(self) -> float:
        return _time.time()

    def monotonic(self) -> float:
        return _time.monotonic()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)


#: the production default — also importable directly for code that must
#: read REAL time regardless of any installed virtual clock (e.g. the
#: simfleet runner's own wall-time budget accounting)
SYSTEM = SystemClock()

_CLOCK = SYSTEM


def install(clock):
    """Install a clock (``None`` restores :data:`SYSTEM`).  Returns the
    previous clock so callers can restore it — the ``durable_io.install``
    idiom.  Process-global by design: the control plane under simulation
    is single-threaded, and the production default is never installed
    over."""
    global _CLOCK
    prev = _CLOCK
    _CLOCK = SYSTEM if clock is None else clock
    return prev


def get() -> Clock:
    return _CLOCK


def now() -> float:
    """Wall-clock seconds via the installed clock."""
    return _CLOCK.now()


def monotonic() -> float:
    """Monotonic seconds via the installed clock."""
    return _CLOCK.monotonic()


def sleep(seconds: float) -> None:
    """Sleep via the installed clock (virtual clocks advance instead)."""
    _CLOCK.sleep(seconds)
