"""CLI: the TLC-equivalent front door.

    python -m kafka_specification_tpu.utils.cli check configs/Kip320.cfg
    python -m kafka_specification_tpu.utils.cli check configs/AsyncIsr.cfg \\
        --sharded --progress
    python -m kafka_specification_tpu.utils.cli oracle configs/Kip101.cfg

`check` runs the TPU/JAX engine (single-device by default, --sharded for the
mesh engine); `oracle` runs the pure-Python reference interpreter on the same
config (the golden cross-check).  The module name defaults to the .cfg file
stem, mirroring how TLC pairs Model.cfg with Model.tla.

Output mirrors TLC's closing summary: distinct states, diameter, and on
violation the invariant name plus a numbered counterexample trace.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ..pipeline_registry import pipeline_names as _pipeline_names
from .cfg import build_model, parse_cfg

# Platform-init watchdog (see _guarded_reexec): a wedged accelerator tunnel
# (observed: axon TPU) can hang PJRT client creation indefinitely — or pass
# a quick jax.devices() probe and wedge on the very next operation (both
# modes observed round 2) — turning `cli check` on the *default* platform
# into an unbounded hang.  The guard re-execs the command in a child that
# writes a two-phase marker file: "init" once jax.devices() returns, then
# "compute" once one tiny jitted computation has executed end-to-end.  The
# parent bounds each phase separately (the checking run itself is never
# time-limited) and on either timeout kills the child and retries pinned
# to CPU with a warning.
_CLI_CHILD_ENV = "KSPEC_CLI_CHILD"
_CLI_MARKER_ENV = "KSPEC_CLI_PLATFORM_MARKER"
# phase budgets: healthy tunnel init ~20s; first tiny compile through the
# tunnel 20-40s (bench.py's budget for the same ops)
_INIT_TIMEOUT = int(os.environ.get("KSPEC_CLI_PLATFORM_TIMEOUT", "45"))
_COMPUTE_TIMEOUT = int(os.environ.get("KSPEC_CLI_COMPUTE_TIMEOUT", "90"))

# typed resource exit (resilience.resources) — duplicated as a literal for
# help strings; asserted equal at the use site
_EXIT_RESOURCE_EXHAUSTED = 75
# typed integrity exit (resilience.integrity): a state-integrity check
# tripped; resume skips chain-failed generations automatically
_EXIT_INTEGRITY = 76


def _enable_compile_cache():
    """Persistent XLA compilation cache for the CLI's engine paths.

    The emitted default path pays tens of seconds of trace+compile cold;
    with the disk cache, the second-ever run of the same (module,
    constants, engine shapes) reuses the compiled executables and a toy
    config lands in seconds (round-5 verdict item 10).  Keyed by XLA on
    the HLO + compile-options hash, so engine/code changes miss cleanly.
    KSPEC_XLA_CACHE=0 disables; KSPEC_XLA_CACHE_DIR redirects.
    """
    if os.environ.get("KSPEC_XLA_CACHE", "1") == "0":
        return
    cache_dir = os.environ.get("KSPEC_XLA_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "kafka_specification_tpu", "xla"
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # small jitted programs dominate toy configs — cache everything
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception as e:  # cache is an optimization, never a failure
        print(f"note: compile cache disabled ({e})", file=sys.stderr)


def _platform_is_pinned() -> bool:
    """True when the platform choice can't hang: pinned to CPU via env.

    Anything else — unset (default discovery), or pinned to an accelerator
    ("tpu", "axon", a mixed list) — can wedge in PJRT client init and goes
    through the guarded child instead.  (This environment exports
    JAX_PLATFORMS=axon, the tunnel platform that motivated the guard.)
    """
    pinned = os.environ.get("JAX_PLATFORMS", "")
    names = {p.strip().lower() for p in pinned.split(",") if p.strip()}
    return names == {"cpu"}


def _mark_platform_ready():
    """Child half of the watchdog: force backend init + one end-to-end
    computation, signalling the parent after each phase."""
    from .platform_guard import platform_ready_probe

    marker = os.environ.get(_CLI_MARKER_ENV)

    def write(stage):
        if marker:
            # kspec: allow(durable-io) watchdog IPC marker — ephemeral
            # parent/child handshake, deleted after probe; not durable state
            with open(marker, "a") as fh:
                fh.write(stage + "\n")

    import jax

    jax.devices()
    write("init")
    platform_ready_probe()
    write("compute")


def _guarded_reexec(argv) -> int:
    """Parent half: run this CLI in a child, bounding only platform
    init + first computation.

    Returns the child's exit code; on a wedge in either phase, retries
    with the CPU platform (and no accelerator plugin) pinned in the
    child's environment.
    """
    import subprocess
    import tempfile

    from .platform_guard import cpu_env

    def run(env):
        """-> ("ok", rc) | ("initfail", rc) | ("timeout", None)."""
        marker = tempfile.NamedTemporaryFile(delete=False, suffix=".ready")
        marker.close()
        os.unlink(marker.name)
        env = dict(env)
        env[_CLI_CHILD_ENV] = "1"
        env[_CLI_MARKER_ENV] = marker.name
        p = subprocess.Popen(
            [sys.executable, "-m", "kafka_specification_tpu.utils.cli"]
            + list(argv)
        , env=env)

        def marker_stages():
            try:
                with open(marker.name) as fh:
                    return fh.read().split()
            except OSError:
                return []

        try:
            deadline = time.monotonic() + _INIT_TIMEOUT
            compute_deadline = None
            while time.monotonic() < deadline:
                stages = marker_stages()
                if "compute" in stages:
                    return "ok", p.wait()  # platform live: no further limit
                if "init" in stages and compute_deadline is None:
                    compute_deadline = time.monotonic() + _COMPUTE_TIMEOUT
                    deadline = compute_deadline
                rc = p.poll()
                if rc == 0:
                    return "ok", 0  # finished clean before marking
                if rc is not None:
                    # nonzero before the marker: init (or pre-init) failure
                    return "initfail", rc
                time.sleep(0.2)
            p.kill()
            p.wait()
            return "timeout", None
        finally:
            try:
                os.unlink(marker.name)
            except OSError:
                pass

    kind, rc = run(os.environ)
    if kind != "ok":
        print(
            f"warning: default platform failed to come up "
            f"({'wedged — killed' if kind == 'timeout' else f'exited {rc}'}); "
            f"retrying on CPU (pass --cpu to skip the probe)",
            file=sys.stderr,
        )
        kind, rc = run(cpu_env())
        if kind == "timeout":  # CPU init can't hang in practice, but be safe
            print("error: CPU platform init timed out", file=sys.stderr)
            return 3
        # "initfail" on CPU = a real (non-platform) failure that reproduced
        # there — propagate the child's actual exit code
    return rc


def _print_result(res, as_json: bool, model_meta=None, run_id=None):
    if as_json:
        # the STABLE machine-readable verdict (kspec-verdict/1): the same
        # record the service's `cli result` returns, so clients switch
        # between local runs and submitted jobs without re-parsing
        # (service/verdict.py; docs/service.md)
        from ..service.verdict import verdict_from_result

        print(json.dumps(verdict_from_result(res, run_id=run_id)))
        return
    print(f"Model: {res.model}")
    print(
        f"{res.total} distinct states found, diameter {res.diameter}, "
        f"{res.seconds:.2f}s ({res.states_per_sec:,.0f} states/sec)"
    )
    if res.violation is None:
        print("No invariant violations. Exhaustive check complete.")
    else:
        v = res.violation
        print(f"Invariant {v.invariant} is VIOLATED at depth {v.depth}.")
        from .pretty import render_state, render_trace

        meta = model_meta or {}
        if v.trace:
            print("Counterexample trace:")
            print(render_trace(meta, v.trace))
        else:
            print("Violating state:")
            print(render_state(meta, v.state))


def main(argv=None):
    p = argparse.ArgumentParser(prog="kafka_specification_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    pc = sub.add_parser("check", help="run the TPU/JAX engine on a TLC .cfg")
    pc.add_argument("cfg")
    pc.add_argument("--module", help="TLA+ module (default: cfg file stem)")
    pc.add_argument(
        "--run-dir",
        help="run directory for this invocation's manifest + stats + spans "
        "+ metrics (default: runs/<run_id>/ under $KSPEC_RUNS_ROOT or the "
        "cwd; reopening an existing run dir resumes its run_id — "
        "docs/observability.md).  Render it later with `cli report`",
    )
    pc.add_argument("--sharded", action="store_true", help="mesh-sharded engine")
    pc.add_argument("--max-depth", type=int)
    pc.add_argument("--max-states", type=int)
    pc.add_argument("--no-trace", action="store_true", help="skip trace storage")
    pc.add_argument("--min-bucket", type=int, default=256)
    pc.add_argument(
        "--chunk-size",
        type=int,
        default=None,
        help="max frontier rows per compiled step call (bounds compiles + "
        "memory); defaults to each engine's own default",
    )
    pc.add_argument("--progress", action="store_true")
    pc.add_argument("--json", action="store_true")
    pc.add_argument(
        "--checkpoint", help="directory for level-synchronous checkpoint/resume"
    )
    pc.add_argument(
        "--checkpoint-every",
        type=int,
        default=1,
        help="persist a checkpoint every N BFS levels (default 1)",
    )
    pc.add_argument(
        "--checkpoint-keep",
        type=int,
        default=3,
        help="rotated checkpoint generations to keep (default 3; corrupt "
        "newest falls back to the next verifying one)",
    )
    pc.add_argument(
        "--stats", help="append per-level JSONL stats (e.g. PROGRESS.jsonl)"
    )
    pc.add_argument(
        "--fault",
        metavar="PLAN",
        help="deterministic fault injection plan (sets KSPEC_FAULT; e.g. "
        "'crash@level:7', 'corrupt_ckpt', 'flip@frontier:3', "
        "'transient_device_err:2' — `cli faults --list` enumerates every "
        "injectable site; grammar in docs/resilience.md)",
    )
    pc.add_argument(
        "--integrity-shadow",
        type=float,
        metavar="RATE",
        help="sampled shadow re-execution rate in [0,1] "
        "(KSPEC_INTEGRITY_SHADOW is the env twin): deterministically "
        "sampled chunks re-run through an independent path (the legacy "
        "pipeline / host fingerprint oracle) and must match the primary "
        "result bit-for-bit; a mismatch exits typed "
        f"INTEGRITY_VIOLATION (code {_EXIT_INTEGRITY}).  The per-level "
        "digest chain and storage read-side checksums are always on "
        "regardless (KSPEC_INTEGRITY=0 disables; docs/resilience.md).  "
        "Single-device engine only",
    )
    pc.add_argument(
        "--resilient",
        action="store_true",
        help="run under the auto-resume supervisor: spawn the check as a "
        "child, watch the --stats heartbeat, kill on stall, restart from "
        "--checkpoint with a bounded budget (scripts/resilient_run.py is "
        "the standalone form)",
    )
    pc.add_argument(
        "--stall-timeout",
        type=float,
        default=1800.0,
        help="[--resilient] kill the child after this many seconds "
        "without heartbeat growth (default 1800)",
    )
    pc.add_argument(
        "--max-restarts",
        type=int,
        default=8,
        help="[--resilient] restart budget (default 8)",
    )
    pc.add_argument(
        "--events",
        help="[--resilient] supervisor JSONL event log (default: "
        "<checkpoint>/supervisor_events.jsonl)",
    )
    pc.add_argument(
        "--visited-backend",
        choices=["device", "device-hash", "host"],
        default="device",
        help="fingerprint set: 'device' = sorted pair set in HBM, "
        "'device-hash' = open-addressing hash table in HBM (O(batch) per "
        "level instead of O(capacity) — ops/hashset), 'host' = the native "
        "C++ FpSet (spill mode for huge state spaces)",
    )
    pc.add_argument(
        "--mem-budget",
        metavar="BYTES",
        help="host fingerprint-set byte budget before spilling to the "
        "disk tier (suffixes K/M/G, e.g. 4G).  Setting this activates "
        "--store=auto's disk tier: sorted bloom-gated runs + spilled "
        "frontier + on-disk parent log under --spill-dir (docs/storage.md)",
    )
    pc.add_argument(
        "--spill-dir",
        metavar="DIR",
        help="directory for the disk tier's runs/frontier/parent log "
        "(default: <--checkpoint>/spill, else a temp dir)",
    )
    pc.add_argument(
        "--store",
        choices=["auto", "ram", "disk"],
        default="auto",
        help="state-storage tier: 'ram' = in-memory only, 'disk' = tiered "
        "out-of-core store (implies the host fingerprint backend), 'auto' "
        "= disk exactly when --mem-budget is set (default)",
    )
    pc.add_argument(
        "--disk-budget",
        metavar="BYTES",
        help="byte budget for the spill + checkpoint directories "
        "(suffixes K/M/G).  Crossing the soft fraction triggers "
        "reclamation (eager merges, generation pruning); a hard breach "
        "checkpoints and exits with the typed RESOURCE_EXHAUSTED status "
        f"(exit code {_EXIT_RESOURCE_EXHAUSTED}), resumable after space "
        "is freed (docs/resilience.md).  KSPEC_DISK_BUDGET is the env "
        "twin; KSPEC_RSS_BUDGET / KSPEC_LEVEL_DEADLINE arm the RSS and "
        "per-level-deadline watchdogs",
    )
    pc.add_argument(
        "--reclaim",
        action="store_true",
        help="[--resilient] on a RESOURCE_EXHAUSTED child exit, prune "
        "stale tmp files + rotated checkpoint generations and retry "
        "exactly once (default: halt with an actionable verdict; the "
        "supervisor never restarts into an unreclaimed full disk)",
    )
    pc.add_argument(
        "--profile",
        metavar="DIR",
        help="wrap the run in a jax.profiler trace (TensorBoard format)",
    )
    pc.add_argument(
        "--pipeline",
        choices=list(_pipeline_names()),
        default=None,
        help="level-pipeline implementation (engine/pipeline.py; "
        "`cli pipelines --list` shows the registry incl. the per-ENGINE "
        "support matrix): 'fused' (default; $KSPEC_PIPELINE overrides) "
        "= successor mega-kernels — one guard-predicate-matrix launch "
        "+ one update-skeleton launch per chunk; 'device' = the "
        "device-resident level pipeline — a bounded lax.while_loop runs "
        "every gated chunk of a level in ONE dispatched program (<=2 "
        "successor launches per LEVEL single-device; with --sharded, "
        "per-SHARD one-dispatch level programs with the exchange inside "
        "the loop — O(1) collective-bearing launches per level per "
        "shard; needs the sorted-set device visited backend + "
        "analyzer-proven field hulls, degrades per-chunk otherwise); "
        "'legacy' = the historical per-action step (the bit-identity "
        "oracle; with --sharded, the per-chunk sharded step).  "
        "Bit-identical results in every case (counts, duplicate "
        "accounting, first-violation rule, trace values, digest "
        "chains).  Unknown names are rejected here and by the engine's "
        "registry — a typo can never silently select a different "
        "implementation",
    )
    pc.add_argument(
        "--overlap",
        choices=["on", "off"],
        default=None,
        help="async level-pipelined execution (engine + sharded; "
        "$KSPEC_OVERLAP is the env twin; default on): two-slot staged "
        "chunk pipeline (host assembly drains behind the in-flight "
        "update-skeleton launch), background spill-run merges, "
        "checkpoint writes on a writer thread, and — sharded — the "
        "staged exchange commit + bit-packed/delta-encoded fingerprint "
        "payload compression (codec defaults on for real accelerator "
        "fabrics; KSPEC_EXCHANGE_COMPRESS=1/0 forces).  'off' restores "
        "the exact serial "
        "behavior (the bit-identity oracle): counts, traces and digest "
        "chains are identical either way (docs/engine.md § Async "
        "execution)",
    )
    pc.add_argument("--cpu", action="store_true", help="force the CPU platform")
    pc.add_argument(
        "--emitted",
        action="store_true",
        default=None,
        help="build the model mechanically from the reference TLA+ text "
        "(utils/tla_emit — no hand-translated kernels).  This is the "
        "DEFAULT when the reference checkout is present (KSPEC_REFERENCE, "
        "/root/reference); the hand-translated kernels remain as the "
        "cross-check path (--hand)",
    )
    pc.add_argument(
        "--hand",
        action="store_true",
        help="use the hand-translated kernels (models/*.py) instead of the "
        "emitted ones — the independent cross-check path (also the "
        "fallback when no reference checkout exists)",
    )

    pvc = sub.add_parser(
        "verify-checkpoint",
        help="offline integrity check of a checkpoint directory: per-array "
        "CRC manifests of every generation/part, cross-shard depth+mesh "
        "consistency, and storage-manifest resolvability (disk-tier run "
        "files).  Never imports jax — usable from CI or an operator shell "
        "on a box whose accelerator stack is wedged.  Exit 0 iff every "
        "checkpoint chain has a resumable generation",
    )
    pvc.add_argument("ckpt_dir")
    pvc.add_argument(
        "--spill-dir",
        help="disk-tier directory the storage manifests resolve against "
        "(default: <ckpt_dir>/spill, the engines' default placement)",
    )
    pvc.add_argument("--json", action="store_true",
                     help="machine-readable report")

    pf = sub.add_parser(
        "faults",
        help="enumerate every injectable fault site (the KSPEC_FAULT / "
        "--fault grammar) from the single registry the parser validates "
        "against — never imports jax",
    )
    pf.add_argument(
        "--list", action="store_true", dest="list_faults",
        help="list the fault registry (the default action)",
    )
    pf.add_argument("--json", action="store_true")

    pcc = sub.add_parser(
        "crashcheck",
        help="crash-consistency torture harness (docs/resilience.md "
        "§ Crash consistency): record every durable filesystem op each "
        "recovery protocol issues, enumerate every legal post-crash "
        "state (torn writes, reverted renames, lost journal tails), and "
        "run the protocol's own recovery against each one — never "
        "imports jax.  Exits 1 on any non-convergent state; findings "
        "carry the op-log prefix and crash state as a machine-readable "
        "repro.  --json emits the schema-versioned kspec-crashcheck/1 "
        "record",
    )
    pcc.add_argument(
        "--protocol", action="append", dest="protocols", metavar="P",
        help="restrict to one protocol or scenario name (repeatable; "
        "see `cli faults --list` for the scenario registry)",
    )
    pcc.add_argument("--json", action="store_true",
                     help="machine-readable kspec-crashcheck/1 record")

    psf = sub.add_parser(
        "simfleet",
        help="deterministic fleet simulation (docs/resilience.md "
        "§ Deterministic simulation): run the REAL router/queue/daemon/"
        "cache control plane under a virtual clock and a seeded "
        "scheduler, search interleavings across seeds (kill, partition, "
        "clock skew, flaky fs), judge every run with invariant oracles, "
        "and shrink any violation to a minimal kspec-simfleet/1 repro — "
        "never imports jax.  `run` exits 1 on any violation; `replay` "
        "re-runs a repro and exits 0 if it still reproduces, 2 if stale",
    )
    sfsub = psf.add_subparsers(dest="sf_cmd", required=True)
    psr = sfsub.add_parser("run", help="sweep seeds, shrink violations")
    psr.add_argument("--seeds", type=int, default=50,
                     help="how many seeds to run (default 50)")
    psr.add_argument("--start-seed", type=int, default=0,
                     help="first seed (default 0)")
    psr.add_argument("--hosts", type=int, default=2)
    psr.add_argument("--jobs", type=int, default=4)
    psr.add_argument("--steps", type=int, default=60,
                     help="schedule length per seed (default 60)")
    psr.add_argument(
        "--coverage", action="store_true",
        help="coverage-guided: seeds that reach new adjacent event-type "
        "pairs queue derived seeds behind them",
    )
    psr.add_argument(
        "--out", default="simfleet-repros", metavar="DIR",
        help="directory violations' shrunk repros are banked in "
        "(default ./simfleet-repros)",
    )
    psr.add_argument("--json", action="store_true")
    psp = sfsub.add_parser("replay",
                           help="replay a kspec-simfleet/1 repro")
    psp.add_argument("repro", help="kspec-simfleet/1 file")
    psp.add_argument(
        "--trace", action="store_true",
        help="assemble the violating job's fleet trace from the "
        "simulated run and render the same waterfall `cli trace` "
        "gives real runs",
    )
    psp.add_argument("--json", action="store_true")

    pp = sub.add_parser(
        "pipelines",
        help="enumerate the registered level-pipeline implementations "
        "(the --pipeline / $KSPEC_PIPELINE registry, "
        "kafka_specification_tpu/pipeline_registry.py) with their launch "
        "contracts and degradation ladder — never imports jax",
    )
    pp.add_argument(
        "--list", action="store_true", dest="list_pipelines",
        help="list the pipeline registry (the default action)",
    )
    pp.add_argument("--json", action="store_true")

    pan = sub.add_parser(
        "analyze",
        help="static analysis of the specs and the engine (docs/"
        "analysis.md): encoding-soundness proofs (interval abstract "
        "interpretation of every action kernel against its packed field "
        "ranges), action/guard lint (vacuous guards, frame violations, "
        "dead fields), and the concurrency-ownership + purity checks "
        "over the engine sources.  NEVER imports jax (the model modules "
        "load under a stub; kernels run abstractly) — usable on a box "
        "with no accelerator stack.  Exits non-zero on any HIGH finding; "
        "--json emits the schema-versioned kspec-analysis/1 record",
    )
    pan.add_argument(
        "cfgs", nargs="*",
        help="TLC .cfg files to analyze (default: every configs/*.cfg "
        "— the full shipped-model matrix)",
    )
    pan.add_argument(
        "--module",
        help="TLA+ module for a single .cfg (default: the cfg stem)",
    )
    pan.add_argument(
        "--no-models", action="store_true",
        help="skip the per-model encoding/lint passes",
    )
    pan.add_argument(
        "--no-engine", action="store_true",
        help="skip the engine ownership/purity passes",
    )
    pan.add_argument(
        "--info", action="store_true",
        help="also print INFO findings (suppressions, skips)",
    )
    pan.add_argument("--json", action="store_true",
                     help="machine-readable kspec-analysis/1 record")

    pr = sub.add_parser(
        "report",
        help="render a run directory (manifest + stats + spans + metrics + "
        "events) into a human summary: per-level throughput, action "
        "enablement, spill accounting, restart timeline, ETA, stall "
        "verdict.  Works on live and crashed-mid-run directories; never "
        "touches an accelerator.  With no run dir: index the recent runs "
        "under --root (the service multiplies run dirs; this is the "
        "operator's ls)",
    )
    pr.add_argument(
        "run_dir", nargs="?",
        help="run directory to render (omit to list recent runs)",
    )
    pr.add_argument(
        "--latest", action="store_true",
        help="render the newest run under --root instead of listing",
    )
    pr.add_argument(
        "--root",
        help="runs root for the no-argument index / --latest "
        "(default: $KSPEC_RUNS_ROOT or ./runs)",
    )
    pr.add_argument("--json", action="store_true",
                    help="machine-readable report")

    # --- checking-as-a-service (docs/service.md) -------------------------
    svc_help = (
        "service directory (queue + results + run dirs; default: "
        "$KSPEC_SERVICE_DIR or ./service)"
    )

    pserve = sub.add_parser(
        "serve",
        help="run the checking-as-a-service daemon: import jax once, hold "
        "jitted engine kernels in a shape-keyed compile cache, drain the "
        "durable job queue under per-tenant resource budgets, coalesce "
        "jobs sharing a schema shape into one batched engine run "
        "(docs/service.md)",
    )
    pserve.add_argument("service_dir", nargs="?", help=svc_help)
    pserve.add_argument("--poll", type=float, default=0.2,
                        help="queue poll interval seconds (default 0.2)")
    pserve.add_argument(
        "--max-jobs", type=int,
        help="exit after this many verdicts (benchmarks / tests)",
    )
    pserve.add_argument(
        "--idle-exit", type=float,
        help="exit after this many seconds with an empty queue "
        "(default: serve forever)",
    )
    pserve.add_argument("--min-bucket", type=int, default=256)
    pserve.add_argument(
        "--chunk-size", type=int, default=32768,
        help="engine streaming chunk (one value for the whole daemon: "
        "batched verdict derivation depends on chunk boundaries)",
    )
    pserve.add_argument(
        "--visited-backend",
        choices=["device", "device-hash", "host"],
        default="device",
    )
    pserve.add_argument(
        "--no-batching", action="store_true",
        help="disable multi-config coalescing (every job runs solo; the "
        "compile cache still amortizes)",
    )
    pserve.add_argument(
        "--cache-entries", type=int, default=32,
        help="kernel-cache LRU capacity (distinct schema shapes held "
        "warm; default 32)",
    )
    pserve.add_argument(
        "--supervised", action="store_true",
        help="run the daemon under the auto-restart supervisor (heartbeat "
        "stall-kill + bounded restarts; resilience.supervisor)",
    )
    pserve.add_argument(
        "--stall-timeout", type=float, default=120.0,
        help="[--supervised] kill the daemon after this many seconds "
        "without a heartbeat tick (default 120; an idle daemon still "
        "ticks every --poll)",
    )
    pserve.add_argument(
        "--max-restarts", type=int, default=8,
        help="[--supervised] restart budget (default 8)",
    )
    pserve.add_argument(
        "--no-state-cache", action="store_true",
        help="disable the persistent state-space cache (default on: "
        "repeat checks of an unchanged config become chain-verified "
        "cache hits, config-delta checks seed from the cached boundary; "
        "every artifact problem degrades to a cold run with a typed "
        "cache-fallback event — docs/service.md § State-space cache)",
    )
    pserve.add_argument(
        "--state-cache-dir", metavar="DIR",
        help="shared state-space cache root (default: <service_dir>/"
        "state-cache).  Point every host of a fleet at one directory to "
        "federate the cache: entries are content-addressed and "
        "self-verifying, so a hit published by another host is "
        "chain-verified before it is served (docs/service.md § "
        "Cross-host deployment)",
    )
    pserve.add_argument("--cpu", action="store_true",
                        help="force the CPU platform")

    pfleet = sub.add_parser(
        "serve-fleet",
        help="run an N-daemon serving fleet over one service directory: "
        "per-daemon heartbeat supervision (death/wedge/rc-75/rc-76 "
        "taxonomy, bounded jittered restarts), queue-depth autoscaling "
        "between --min/--max with graceful drain, lease-based takeover "
        "of a dead or wedged daemon's claims (docs/service.md § Fleet "
        "lifecycle).  The parent never imports jax",
    )
    pfleet.add_argument("service_dir", nargs="?", help=svc_help)
    pfleet.add_argument(
        "--daemons", type=int, default=2,
        help="initial fleet size (default 2)",
    )
    pfleet.add_argument(
        "--min", type=int, default=None, dest="min_daemons",
        help="autoscale floor (default: --daemons)",
    )
    pfleet.add_argument(
        "--max", type=int, default=None, dest="max_daemons",
        help="autoscale ceiling (default: --daemons)",
    )
    pfleet.add_argument("--poll", type=float, default=0.5)
    pfleet.add_argument(
        "--stall-timeout", type=float, default=120.0,
        help="kill + restart a daemon whose own heartbeat file freezes "
        "for this long (an idle daemon still ticks every few seconds, "
        "so frozen means wedged; default 120)",
    )
    pfleet.add_argument(
        "--max-restarts", type=int, default=8,
        help="per-daemon restart budget (default 8)",
    )
    pfleet.add_argument("--backoff-base", type=float, default=1.0)
    pfleet.add_argument(
        "--scale-up-pending", type=int, default=4,
        help="pending jobs per live daemon that triggers a scale-up "
        "(default 4)",
    )
    pfleet.add_argument("--scale-interval", type=float, default=5.0)
    pfleet.add_argument(
        "--scale-down-idle", type=float, default=60.0,
        help="seconds of empty queue before one daemon is gracefully "
        "drained (finishes claimed jobs, takes no new ones, exits 0; "
        "default 60)",
    )
    pfleet.add_argument("--min-bucket", type=int, default=256)
    pfleet.add_argument("--chunk-size", type=int, default=32768)
    pfleet.add_argument(
        "--visited-backend", choices=["device", "device-hash", "host"],
        default="device",
    )
    pfleet.add_argument("--no-batching", action="store_true")
    pfleet.add_argument("--cache-entries", type=int, default=32)
    pfleet.add_argument("--no-state-cache", action="store_true")
    pfleet.add_argument(
        "--state-cache-dir", metavar="DIR",
        help="shared state-space cache root for every daemon (see "
        "`serve --state-cache-dir`; point multiple hosts' fleets at one "
        "directory to federate the cache)",
    )
    pfleet.add_argument(
        "--host-instance", type=int, metavar="I",
        help="this fleet's host index in a cross-host deployment "
        "(exported as KSPEC_HOST_INSTANCE to every daemon; scopes "
        "host-targeted faults like kill@host<i> and skew@host<i>)",
    )
    pfleet.add_argument("--cpu", action="store_true",
                        help="force the CPU platform in every daemon")

    psub = sub.add_parser(
        "submit",
        help="submit a check to the service queue and return the job id — "
        "NEVER imports jax (the tenant side pays no cold start); the .cfg "
        "travels inline in the job spec",
    )
    psub.add_argument("cfg")
    psub.add_argument("--module", help="TLA+ module (default: cfg stem)")
    psub.add_argument("--service-dir", help=svc_help)
    psub.add_argument(
        "--router", metavar="DIR",
        help="submit through a cross-host router directory (`cli route`) "
        "instead of a single service dir: the router places the job on "
        "the healthiest live host and enforces the tenant's max_pending "
        "cap fleet-WIDE",
    )
    psub.add_argument("--tenant", default="default")
    psub.add_argument("--max-depth", type=int)
    psub.add_argument("--max-states", type=int)
    psub.add_argument(
        "--emitted", action="store_true", default=None,
        help="force the mechanically emitted kernels (default: auto — "
        "emitted when the daemon's reference checkout has the module)",
    )
    psub.add_argument(
        "--hand", action="store_true",
        help="force the hand-translated kernels",
    )
    psub.add_argument(
        "--fault", metavar="PLAN",
        help="deterministic fault plan for THIS job (testing/ops; the "
        "daemon scopes it to the job's run)",
    )
    psub.add_argument(
        "--wait", action="store_true",
        help="block until the verdict and exit with its exit code",
    )
    psub.add_argument(
        "--timeout", type=float, default=300.0,
        help="[--wait] give up after this many seconds (default 300)",
    )
    psub.add_argument("--json", action="store_true")

    pst = sub.add_parser(
        "status",
        help="job state (pending/claimed/done) or, with no job id, the "
        "queue overview — never imports jax",
    )
    pst.add_argument("job_id", nargs="?")
    pst.add_argument("--service-dir", help=svc_help)
    pst.add_argument(
        "--router", metavar="DIR",
        help="resolve the job through a router directory (locates the "
        "host it was routed to, following reroutes)",
    )
    pst.add_argument("--json", action="store_true")

    pres = sub.add_parser(
        "result",
        help="fetch a job's verdict (kspec-verdict/1, the same record "
        "`cli check --json` prints) and exit with its exit code — never "
        "imports jax",
    )
    pres.add_argument("job_id")
    pres.add_argument("--service-dir", help=svc_help)
    pres.add_argument(
        "--router", metavar="DIR",
        help="fetch the verdict through a router directory (checks the "
        "routed host first, then every host — a rerouted job's verdict "
        "is found wherever it landed)",
    )
    pres.add_argument(
        "--wait", action="store_true",
        help="block until the verdict exists",
    )
    pres.add_argument("--timeout", type=float, default=300.0)
    pres.add_argument("--json", action="store_true")

    proute = sub.add_parser(
        "route",
        help="run the cross-host router over N per-host service "
        "directories: health-aware placement (heartbeat freshness, queue "
        "depth), fleet-wide tenant admission, dead-host detection with "
        "exactly-once re-routing of pending jobs to survivors — never "
        "imports jax (docs/service.md § Cross-host deployment)",
    )
    proute.add_argument(
        "router_dir",
        help="router state directory (created on first run; holds "
        "router.json, route records, and the router event log)",
    )
    proute.add_argument(
        "--hosts", nargs="+", metavar="DIR",
        help="per-host service directories to front (required on first "
        "run; persisted in router.json and optional afterwards)",
    )
    proute.add_argument(
        "--dead-after", type=float, default=None,
        help="seconds without a daemon heartbeat before a host is "
        "declared dead and its pending jobs re-route (default 30; the "
        "comparison tolerates KSPEC_CLOCK_SKEW)",
    )
    proute.add_argument(
        "--poll", type=float, default=1.0,
        help="sweep interval seconds (default 1.0)",
    )
    proute.add_argument(
        "--once", action="store_true",
        help="run a single sweep (takeover + re-route pass) and exit",
    )
    proute.add_argument(
        "--status", action="store_true",
        help="print per-host health and queue depths, run no sweep",
    )
    proute.add_argument("--json", action="store_true")

    ptr = sub.add_parser(
        "trace",
        help="render one job's fleet-wide distributed trace "
        "(submit -> placement -> claim -> run -> publish) as a "
        "skew-normalized cross-host span waterfall with the typed "
        "stage decomposition — never imports jax "
        "(docs/observability.md § Fleet traces)",
    )
    ptr.add_argument("job_id")
    ptr.add_argument(
        "--service-dir", action="append", metavar="DIR",
        help="service root(s) whose traces/ to read (repeatable; "
        "default: $KSPEC_SERVICE_DIR or ./service)",
    )
    ptr.add_argument(
        "--router", metavar="DIR",
        help="read the router dir's traces/ plus every fronted host's "
        "(a re-routed job's spans live on both sides)",
    )
    ptr.add_argument("--json", action="store_true")

    ptop = sub.add_parser(
        "top",
        help="live fleet view from on-disk state only: queue depths, "
        "daemon heartbeats, per-stage p50/p95, cache hit ratio, sweep "
        "progress — never imports jax",
    )
    ptop.add_argument("--service-dir", action="append", metavar="DIR",
                      help="service root(s) to watch (repeatable)")
    ptop.add_argument("--router", metavar="DIR",
                      help="watch every host behind a router directory")
    ptop.add_argument("--once", action="store_true",
                      help="print one frame and exit")
    ptop.add_argument("--interval", type=float, default=2.0,
                      help="refresh seconds (default 2.0)")
    ptop.add_argument("--json", action="store_true",
                      help="print one JSON frame and exit (implies --once)")

    pfr = sub.add_parser(
        "fleet-report",
        help="SLO artifact over every completed trace: per-stage "
        "latency histograms (p50/p95), cache hit ratio, slowest-job "
        "exemplars, chaos annotations (re-routes, requeues) — never "
        "imports jax; nightly_sweep.sh banks it per night",
    )
    pfr.add_argument("--service-dir", action="append", metavar="DIR",
                     help="service root(s) whose traces/ to aggregate")
    pfr.add_argument("--router", metavar="DIR",
                     help="aggregate the router dir plus every fronted host")
    pfr.add_argument("--exemplars", type=int, default=5,
                     help="slowest-job exemplar count (default 5)")
    pfr.add_argument("--json", action="store_true")

    psw = sub.add_parser(
        "sweep",
        help="coverage sweeps over a config lattice (kspec-sweep-lattice/1"
        "): enumerate canonical points, skip statically-vacuous configs, "
        "predict cost from the standing corpus, schedule the portfolio "
        "through the service queue or a router (cheap points batch, "
        "expensive points run solo, repeats are cache hits), and report "
        "coverage / violation frontiers / scaling laws — never imports "
        "jax (docs/sweep.md)",
    )
    swsub = psw.add_subparsers(dest="sweep_cmd", required=True)
    swp = swsub.add_parser(
        "plan",
        help="enumerate + annotate + predict, dispatch nothing: the "
        "dry-run view of what a sweep would do (point count, vacuous "
        "skips with their findings, predicted cost, solo/batch split)",
    )
    swp.add_argument("lattice", help="kspec-sweep-lattice/1 JSON file")
    swp.add_argument("--state-cache-dir", metavar="DIR",
                     help="corpus root for the cost-model fit (default: "
                     "$KSPEC_STATE_CACHE_DIR or <service>/state-cache)")
    swp.add_argument("--service-dir", help=svc_help)
    swp.add_argument("--json", action="store_true")
    swr = swsub.add_parser(
        "run",
        help="run (or crash-resume — only incomplete points re-submit) "
        "one sweep to completion against a live daemon/fleet; the "
        "durable kspec-sweep/1 manifest lands in --sweep-dir",
    )
    swr.add_argument("lattice", help="kspec-sweep-lattice/1 JSON file")
    swr.add_argument("--sweep-dir", required=True,
                     help="sweep state directory (sweep.json manifest; "
                     "reuse to crash-resume, use a fresh one to re-run)")
    swr.add_argument("--service-dir", help=svc_help)
    swr.add_argument(
        "--router", metavar="DIR",
        help="dispatch through a cross-host router directory instead of "
        "one service dir",
    )
    swr.add_argument("--tenant", default="sweep")
    swr.add_argument("--max-inflight", type=int, default=64,
                     help="portfolio submit-window width (default 64; "
                     "clamped under the tenant's max_pending cap)")
    swr.add_argument(
        "--solo-threshold", type=int, default=200_000,
        help="predicted distinct-states at/past which a point submits "
        "solo instead of joining a batched group (default 200000)",
    )
    swr.add_argument("--timeout", type=float, default=900.0,
                     help="give up after this many seconds without a "
                     "verdict landing (default 900; resume later)")
    swr.add_argument("--state-cache-dir", metavar="DIR")
    swr.add_argument("--json", action="store_true",
                     help="print the final manifest record")
    swrep = swsub.add_parser(
        "report",
        help="render a sweep directory's manifest: coverage (done/hit/"
        "seeded/skipped/pending), the typed vacuous-skip rows, the "
        "minimal-violating-config frontier per invariant, scaling-law "
        "curves (states vs axis value), estimator accuracy",
    )
    swrep.add_argument("sweep_dir")
    swrep.add_argument("--json", action="store_true")
    swb = swsub.add_parser(
        "bisect",
        help="witness the minimal-violating-config frontier: check every "
        "frontier point's lower neighbors from the manifest, and "
        "(with --service-dir/--router) actually RUN the neighbors the "
        "sweep never ran — the frontier is witnessed, not guessed",
    )
    swb.add_argument("sweep_dir")
    swb.add_argument("--invariant", help="restrict to one invariant")
    swb.add_argument("--service-dir", help=svc_help)
    swb.add_argument("--router", metavar="DIR")
    swb.add_argument("--tenant", default="sweep")
    swb.add_argument("--max-probes", type=int, default=64,
                     help="budget of neighbor runs (default 64)")
    swb.add_argument("--timeout", type=float, default=300.0,
                     help="per-probe verdict timeout (default 300)")
    swb.add_argument("--json", action="store_true")

    po = sub.add_parser("oracle", help="run the Python reference interpreter")
    po.add_argument("cfg")
    po.add_argument("--module")
    po.add_argument("--max-depth", type=int)
    po.add_argument("--max-states", type=int)

    ps = sub.add_parser(
        "simulate", help="random-walk checking (TLC -simulate equivalent)"
    )
    ps.add_argument("cfg")
    ps.add_argument("--module")
    ps.add_argument("--walks", type=int, default=100)
    ps.add_argument("--depth", type=int, default=100)
    ps.add_argument("--seed", type=int, default=0)
    ps.add_argument("--cpu", action="store_true", help="force the CPU platform")
    ps.add_argument("--json", action="store_true")
    ps.add_argument(
        "--emitted",
        action="store_true",
        default=None,
        help="simulate the mechanically emitted model (the default when "
        "the reference checkout is present — see `check --emitted`)",
    )
    ps.add_argument(
        "--hand",
        action="store_true",
        help="simulate the hand-translated kernels (see `check --hand`)",
    )

    pv = sub.add_parser(
        "validate",
        help="cross-check a model's action inventory against the reference "
        "TLA+ module's Next disjuncts (structural front-end)",
    )
    pv.add_argument("cfg")
    pv.add_argument("--module")
    pv.add_argument(
        "--reference",
        default=os.environ.get("KSPEC_REFERENCE", "/root/reference"),
        help="reference checkout to validate against (default: "
        "$KSPEC_REFERENCE or /root/reference — same resolution as the "
        "emitted model builder)",
    )
    pv.add_argument(
        "--emitted",
        action="store_true",
        help="validate the mechanically emitted model's action inventory "
        "(its `Name~k` DNF branches map back to their source disjunct)",
    )

    args = p.parse_args(argv)

    if args.cmd == "faults":
        # pure registry dump (resilience.faults.FAULT_REGISTRY): jax-free
        from ..resilience.crashcheck import list_scenarios
        from ..resilience.faults import list_faults

        entries = list_faults()
        scenarios = list_scenarios()
        if args.json:
            # scenario rows ride along as extra entries (same flat-list
            # shape every existing consumer parses), tagged by kind
            print(json.dumps(entries + [
                {"kind": "crashcheck-scenario",
                 "grammar": f"crashcheck --protocol {s['protocol']}",
                 "sites": [s["name"]],
                 "description": s["description"],
                 "scopeable": False}
                for s in scenarios
            ]))
            return 0
        print("Injectable faults (KSPEC_FAULT / --fault; comma-separate "
              "to compose; every fault takes a `shard<d>:` scope after "
              "the '@'):")
        for e in entries:
            print(f"  {e['grammar']}")
            print(f"      {e['description']}")
        print("Examples: crash@level:7   enospc@spill:2   "
              "flip@shard1:exchange:3   corrupt_ckpt@ckpt:4")
        print()
        print("Crashcheck scenarios (`cli crashcheck --protocol P`; "
              "enumerated crash states, not injected faults):")
        for s in scenarios:
            print(f"  {s['protocol']}: {s['name']}")
            print(f"      {s['description']}")
        return 0

    if args.cmd == "crashcheck":
        # crash-consistency torture harness: jax-free by construction
        # (queue/router/cache/checkpoint recovery paths never touch the
        # accelerator stack)
        from ..resilience.crashcheck import run_crashcheck

        try:
            rec = run_crashcheck(protocols=args.protocols)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(rec))
        else:
            print(f"kspec crashcheck: {rec['states']} crash states / "
                  f"{len(rec['protocols'])} protocol(s) in "
                  f"{rec['seconds']}s — "
                  f"{rec['non_convergent']} non-convergent")
            for s in rec["scenarios"]:
                print(f"  {s['protocol']:<8} {s['name']:<18} "
                      f"{s['states']:>4} states  "
                      f"{s['non_convergent']} non-convergent")
            for f in rec["findings"]:
                print(f"  FINDING {f['scenario']} prefix={f['prefix']} "
                      f"degraded={f['degraded']} "
                      f"state={f['state_digest']}")
                for v in f["violations"]:
                    print(f"    {v}")
        return 0 if rec["ok"] else 1

    if args.cmd == "simfleet":
        # deterministic fleet simulation: jax-free by construction (the
        # whole simulated plane is the jax-free control plane)
        return _run_simfleet(args)

    if args.cmd == "pipelines":
        # pure registry dump (pipeline_registry.PIPELINE_REGISTRY, the
        # fault-registry pattern): jax-free, the same source the
        # --pipeline parser and the engine's resolve_pipeline validate
        # against — a typo'd name is rejected loudly at parse time, it
        # can never silently fall back to a different implementation
        from ..pipeline_registry import list_pipelines

        entries = list_pipelines()
        if args.json:
            print(json.dumps(entries))
            return 0
        print("Registered level pipelines (--pipeline / $KSPEC_PIPELINE; "
              "engine/pipeline.py):")
        for e in entries:
            tag = " (default)" if e["default"] else ""
            fb = (f" -> degrades to '{e['fallback']}'"
                  if e["fallback"] else " (the bit-identity oracle)")
            print(f"  {e['name']}{tag}: {e['launches']}{fb}")
            print(f"      {e['description']}")
            # per-engine support matrix: which engine (plain vs
            # --sharded) serves this name, and why a combination
            # degrades — the sharded engine used to silently ignore
            # --pipeline; every cell is now stated
            for eng, cell in e.get("engines", {}).items():
                mark = "supported" if cell["supported"] else "degrades"
                print(f"      [{eng}] {mark}: {cell['detail']}")
            # per-BACKEND support matrix: which visited backends the
            # pipeline serves natively vs degrades from — the detail of
            # an unsupported cell is the exact fallback reason
            # stats['device']['fallback'] records (one source,
            # pipeline_registry.backend_fallback_reason)
            for be, cell in e.get("backends", {}).items():
                mark = "native" if cell["supported"] else "degrades"
                print(f"      [backend {be}] {mark}: {cell['detail']}")
        return 0

    if args.cmd == "analyze":
        # the static-analysis front door: jax-free by contract (the
        # model modules import under analysis.install_jax_stub and the
        # kernels execute abstractly) — it must run in CI and on
        # operator boxes whose accelerator stack is wedged
        return _run_analyze(args)

    if args.cmd == "verify-checkpoint":
        # like `report`, this must run on a box whose accelerator is
        # wedged (that is when an operator reaches for it): jax-free
        from ..resilience.checkpoints import verify_checkpoint_dir

        rep = verify_checkpoint_dir(args.ckpt_dir, spill_dir=args.spill_dir)
        if args.json:
            print(json.dumps(rep, default=str))
        else:
            _print_verify_checkpoint(rep)
        return 0 if rep["ok"] else 1

    if args.cmd == "report":
        # a report must render on a box whose accelerator is wedged (that
        # is when you want it most): obs never imports jax
        from ..obs.report import (
            list_runs,
            render_report,
            render_run_index,
            report_data,
        )

        run_dir = args.run_dir
        if run_dir is not None and os.path.isfile(
            os.path.join(run_dir, "router.json")
        ):
            # a router directory: render the cross-host rollup instead
            # of a (nonexistent) single-run report
            from ..obs.report import render_router_report, router_report_data

            data = router_report_data(run_dir)
            print(json.dumps(data) if args.json
                  else render_router_report(data))
            return 0
        if run_dir is not None and os.path.isfile(
            os.path.join(run_dir, "sweep.json")
        ):
            # a sweep directory (kspec-sweep/1 manifest): render the
            # sweep beat — same detection pattern as router.json above
            from ..obs.report import render_sweep_report, sweep_report_data

            data = sweep_report_data(run_dir)
            print(json.dumps(data) if args.json
                  else render_sweep_report(data))
            return 0
        if run_dir is None:
            root = args.root or os.environ.get("KSPEC_RUNS_ROOT", "runs")
            if args.latest:
                runs = list_runs(root, limit=1)
                if not runs:
                    print(f"no runs under {root}", file=sys.stderr)
                    return 1
                run_dir = runs[0]["dir"]
            else:
                runs = list_runs(root)
                if args.json:
                    print(json.dumps(runs, default=str))
                else:
                    print(render_run_index(root, runs))
                return 0
        if args.json:
            print(json.dumps(report_data(run_dir), default=str))
        else:
            print(render_report(run_dir))
        return 0

    if args.cmd == "route":
        # the router is operator infrastructure for a degraded fleet:
        # jax-free by contract, like the clients it fronts
        return _run_router(args)

    if args.cmd in ("trace", "top", "fleet-report"):
        # fleet observability reads side-channel files only (traces/,
        # heartbeats, metrics.prom): jax-free by contract — it is the
        # view an operator opens WHILE the fleet is degraded
        return _run_fleet_obs(args)

    if args.cmd == "sweep":
        # sweep planning/dispatch/reporting is a queue/router CLIENT:
        # jax-free by contract — the only engine work a sweep causes
        # happens inside serving daemons
        return _run_sweep(args)

    if args.cmd in ("submit", "status", "result"):
        # the tenant side of the service: MUST stay jax-free — clients
        # never pay the cold start (tests pin this with a poisoned jax)
        return _run_service_client(args)

    if args.cmd == "serve-fleet":
        # the fleet parent is jax-free (children are full `cli serve`
        # processes with their own platform hygiene)
        from ..service.fleet import FleetServeConfig, serve_fleet_daemons

        serve_args = [
            "--min-bucket", str(args.min_bucket),
            "--chunk-size", str(args.chunk_size),
            "--visited-backend", args.visited_backend,
            "--cache-entries", str(args.cache_entries),
        ]
        if args.no_batching:
            serve_args.append("--no-batching")
        if args.no_state_cache:
            serve_args.append("--no-state-cache")
        if args.cpu:
            serve_args.append("--cpu")
        daemons = max(1, args.daemons)
        return serve_fleet_daemons(
            FleetServeConfig(
                service_dir=_service_dir(args.service_dir),
                daemons=daemons,
                min_daemons=(
                    daemons if args.min_daemons is None
                    else max(1, args.min_daemons)
                ),
                max_daemons=args.max_daemons,
                poll_s=args.poll,
                stall_timeout=args.stall_timeout,
                max_restarts=args.max_restarts,
                backoff_base=args.backoff_base,
                scale_interval_s=args.scale_interval,
                scale_up_pending=args.scale_up_pending,
                scale_down_idle_s=args.scale_down_idle,
                serve_args=tuple(serve_args),
                state_cache_dir=args.state_cache_dir,
                host_instance=args.host_instance,
            )
        )

    if args.cmd == "serve" and args.supervised:
        # daemon supervision: same watchdog as engine runs, pointed at the
        # daemon's own heartbeat (it ticks every poll even when idle)
        from ..resilience.supervisor import daemon_supervisor_config, supervise

        child_argv = [
            a
            for a in (argv if argv is not None else sys.argv[1:])
            if not (a.startswith("--su") and "--supervised".startswith(a))
        ]
        svc_dir = _service_dir(args.service_dir)
        cfg = daemon_supervisor_config(
            svc_dir,
            [sys.executable, "-m", "kafka_specification_tpu.utils.cli"]
            + child_argv,
            stall_timeout=args.stall_timeout,
            max_restarts=args.max_restarts,
        )
        return supervise(cfg)

    if args.cmd == "serve":
        # the daemon IS the jax process: same platform hygiene as `check`
        # (guarded re-exec against a wedged accelerator tunnel, persistent
        # XLA compile cache so even a restarted daemon re-warms from disk)
        if (
            not args.cpu
            and not _platform_is_pinned()
            and not os.environ.get(_CLI_CHILD_ENV)
        ):
            return _guarded_reexec(
                list(argv if argv is not None else sys.argv[1:])
            )
        from .platform_guard import pin_cpu_in_process, reassert_env_pin

        if args.cpu:
            pin_cpu_in_process()
        elif _platform_is_pinned():
            reassert_env_pin()
        if os.environ.get(_CLI_CHILD_ENV):
            _mark_platform_ready()
        _enable_compile_cache()
        from ..service.daemon import ServeConfig
        from ..service.daemon import serve as _serve

        return _serve(
            ServeConfig(
                service_dir=_service_dir(args.service_dir),
                poll_s=args.poll,
                max_jobs=args.max_jobs,
                idle_exit_s=args.idle_exit,
                min_bucket=args.min_bucket,
                chunk_size=args.chunk_size,
                visited_backend=args.visited_backend,
                cache_entries=args.cache_entries,
                batching=not args.no_batching,
                state_cache=not args.no_state_cache,
                state_cache_dir=args.state_cache_dir,
            )
        )

    from pathlib import Path

    module = args.module or Path(args.cfg).stem
    try:
        tlc_cfg = parse_cfg(args.cfg)
    except (OSError, ValueError) as e:
        print(f"error: cannot parse {args.cfg}: {e}", file=sys.stderr)
        return 2

    if args.cmd == "check" and (args.checkpoint_every < 1 or args.checkpoint_keep < 1):
        print(
            "error: --checkpoint-every and --checkpoint-keep must be >= 1",
            file=sys.stderr,
        )
        return 2

    if args.cmd == "check" and args.mem_budget is not None:
        from ..storage import parse_mem_budget

        try:
            args.mem_budget = parse_mem_budget(args.mem_budget)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if args.cmd == "check" and args.disk_budget is not None:
        from ..resilience.resources import parse_bytes

        try:
            args.disk_budget = parse_bytes(args.disk_budget)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if args.cmd == "check" and args.sharded \
            and getattr(args, "integrity_shadow", None):
        # shadow re-execution is a single-device-engine oracle; silently
        # dropping the flag on a sharded run would report a clean pass an
        # operator (sent here by the report's own guidance) would trust
        print(
            "error: --integrity-shadow is single-device only (the shadow "
            "oracles are the legacy pipeline + host fingerprint oracle); "
            "re-run without --sharded to localize corruption",
            file=sys.stderr,
        )
        return 2

    if args.cmd == "check" and args.fault:
        from ..resilience.faults import FaultPlan

        try:
            FaultPlan(args.fault)  # validate the grammar before running
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        os.environ["KSPEC_FAULT"] = args.fault

    if args.cmd == "check" and args.resilient:
        return _run_resilient(args, argv if argv is not None else sys.argv[1:])

    if args.cmd in ("check", "simulate"):
        if (
            not args.cpu
            and not _platform_is_pinned()
            and not os.environ.get(_CLI_CHILD_ENV)
        ):
            # default platform may be a hang-prone accelerator tunnel:
            # run guarded (init-bounded child, CPU fallback).  Pin the run
            # directory HERE so a CPU retry after a wedged default-platform
            # attempt reopens the same run (one run_id per invocation, not
            # per attempt)
            child_argv = list(argv if argv is not None else sys.argv[1:])
            if args.cmd == "check" and args.run_dir is None:
                from ..obs import default_run_dir, new_run_id

                args.run_dir = default_run_dir(new_run_id())
                child_argv += ["--run-dir", args.run_dir]
            return _guarded_reexec(child_argv)
        from .platform_guard import pin_cpu_in_process, reassert_env_pin

        if args.cpu:
            pin_cpu_in_process()
        elif _platform_is_pinned():
            # sitecustomize may force jax_platforms (e.g. "axon,cpu") at
            # interpreter start, overriding the env var — re-assert it
            reassert_env_pin()
        if os.environ.get(_CLI_CHILD_ENV):
            _mark_platform_ready()
        _enable_compile_cache()
        if (
            os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("KSPEC_MULTIHOST") == "1"
        ):
            # fleet-launched process (scripts/resilient_run.py --fleet, or
            # any jax.distributed job): join the job BEFORE anything
            # initializes the XLA backend — this is what lets the plain
            # CLI be the per-process command of a supervised fleet
            from ..parallel.multihost import init_distributed

            info = init_distributed()
            if info["process_count"] > 1:
                print(
                    f"[fleet] process {info['process_id']}/"
                    f"{info['process_count']} "
                    f"({info['local_devices']} local / "
                    f"{info['global_devices']} global devices)",
                    file=sys.stderr,
                )

    if args.cmd == "validate":
        # structural validation never needs an accelerator, but building
        # the emitted model initializes a backend — keep it off a possibly
        # wedged tunnel
        from .platform_guard import pin_cpu_in_process

        pin_cpu_in_process()
        from .tla_frontend import validate_cfg_constants, validate_model

        problems = validate_cfg_constants(tlc_cfg, args.reference, module)
        # validate the base (single-partition) model: Partitions is an
        # authored product-space constant with no reference counterpart,
        # and the combinator renames actions to p<k>.<Name>
        tlc_cfg.constants.pop("Partitions", None)
        model = _build_or_fail(
            module, tlc_cfg, emitted=args.emitted, reference=args.reference
        )
        problems += validate_model(model, args.reference, module)
        if problems:
            for pr in problems:
                print(f"MISMATCH: {pr}")
            return 1
        kind = "emitted DNF branches" if args.emitted else "actions"
        print(
            f"{module}: constants assigned; {len(model.actions)} {kind} "
            f"cover the reference Next disjuncts exactly."
        )
        return 0

    if args.cmd == "simulate":
        from ..engine.simulate import simulate

        model = _build_or_fail(
            module, tlc_cfg, emitted=_kernel_source(args, module)
        )
        res = simulate(
            model, num_walks=args.walks, max_depth=args.depth, seed=args.seed
        )
        if res.violation is None:
            print(
                f"Simulation: {args.walks} walks x depth {args.depth}, "
                f"{res.total} states visited, no violations "
                f"({res.states_per_sec:,.0f} states/sec)."
            )
        else:
            _print_result(res, args.json, model_meta=model.meta)
        return 0 if res.violation is None else 1

    if args.cmd == "oracle":
        from ..oracle.interp import oracle_bfs

        om = _build_or_fail(module, tlc_cfg, oracle=True)
        t0 = time.perf_counter()
        r = oracle_bfs(
            om,
            max_depth=args.max_depth,
            max_states=args.max_states,
            keep_level_sets=False,
            check_deadlock=tlc_cfg.check_deadlock,
        )
        dt = time.perf_counter() - t0
        print(
            f"Oracle: {r.total} distinct states, diameter {r.diameter}, "
            f"{dt:.2f}s ({r.total / max(dt, 1e-9):,.0f} states/sec)"
        )
        if r.violation:
            name, depth, _ = r.violation
            print(f"Invariant {name} is VIOLATED at depth {depth}.")
            from .pretty import render_trace

            print("Counterexample trace:")
            print(render_trace(om.meta, r.trace))
        else:
            print("No invariant violations. Exhaustive check complete.")
        return 0 if r.violation is None else 1

    model = _build_or_fail(
        module, tlc_cfg, emitted=_kernel_source(args, module)
    )
    run_ctx = None
    if args.cmd == "check" and _is_obs_coordinator():
        # every check invocation gets a run directory: manifest + stats +
        # spans + metrics correlated under one run_id (cli report renders
        # it, live or post-mortem — docs/observability.md).  One writer
        # per job: in a multi-process sharded run only process 0 opens
        # the run dir (the replicated loops would otherwise race the
        # manifest or strand never-finished orphan dirs)
        from ..obs import RunContext

        run_ctx = RunContext(args.run_dir)
        run_ctx.record_config(
            module=module,
            cfg=args.cfg,
            sharded=bool(args.sharded),
            checkpoint=args.checkpoint,
            stats=args.stats,
        )
        spill_defaulted = False
        if args.mem_budget is not None and args.spill_dir is None \
                and args.checkpoint is None:
            # un-homed disk tier: spill under the run dir instead of an
            # ephemeral tmp dir — a crashed run's spill is then
            # inspectable next to its stats/spans.  Like the ephemeral
            # tmp it replaces, it is deleted once the run completes
            # (checkpointed runs keep <checkpoint>/spill: the tier lives
            # and dies with the checkpoints that reference it)
            args.spill_dir = run_ctx.spill_dir
            spill_defaulted = True
        print(
            f"[obs] run dir: {run_ctx.dir} (run {run_ctx.run_id})",
            file=sys.stderr,
        )
    progress = None
    if args.progress:
        def progress(depth, new_n, total):
            print(f"  level {depth}: {new_n} new, {total} total", file=sys.stderr)

    import contextlib

    prof = contextlib.nullcontext()
    if args.profile:
        import jax

        prof = jax.profiler.trace(args.profile)
    chunk_kw = {} if args.chunk_size is None else {"chunk_size": args.chunk_size}
    from ..resilience.integrity import EXIT_INTEGRITY, IntegrityError
    from ..resilience.resources import (
        EXIT_RESOURCE_EXHAUSTED,
        ResourceExhausted,
    )

    assert EXIT_RESOURCE_EXHAUSTED == _EXIT_RESOURCE_EXHAUSTED
    assert EXIT_INTEGRITY == _EXIT_INTEGRITY
    try:
        with prof:
            res = _run_engine(args, model, tlc_cfg, progress, chunk_kw,
                              run=run_ctx)
    except IntegrityError as e:
        # typed integrity terminal: the run's DATA failed a check (digest
        # chain / shadow / framing / read-side CRC), the manifest is
        # stamped `integrity-violation`, and the distinct exit code lets
        # supervisors restart from the newest chain-verified generation
        # (corrupted ones are skipped by the resume-path validators)
        print(f"INTEGRITY VIOLATION: {e}", file=sys.stderr)
        if args.json:
            from ..service.verdict import error_verdict

            json.dump(
                error_verdict(
                    f"INTEGRITY_VIOLATION[{e.site}]: {e.detail}",
                    run_id=run_ctx.run_id if run_ctx is not None else None,
                    exit_code=EXIT_INTEGRITY,
                ),
                sys.stdout,
            )
            print()
        if args.checkpoint:
            print(
                f"  re-running resumes from the newest chain-verified "
                f"generation in {args.checkpoint} (verify offline with "
                f"`... verify-checkpoint {args.checkpoint}`).  Recurring "
                f"violations on one host suggest failing hardware",
                file=sys.stderr,
            )
        else:
            print(
                "  no --checkpoint was configured: a re-run starts over "
                "(add --checkpoint so integrity exits resume from the "
                "newest chain-verified generation)",
                file=sys.stderr,
            )
        return EXIT_INTEGRITY
    except ResourceExhausted as e:
        # the typed terminal: the engine already checkpointed what it
        # could, stamped the run manifest, and left every promoted
        # generation verifiable — tell the operator what ran out and how
        # to resume, and exit with the distinct resource code (75) so
        # supervisors never classify this as a crash
        print(f"RESOURCE EXHAUSTED: {e}", file=sys.stderr)
        if args.json:
            # the stable verdict record covers ALL exits (0/1/75/2): a
            # client switching between local runs and submitted jobs must
            # get a kspec-verdict/1 object on the rc-75 path too, exactly
            # like `cli result` does for a resource-exhausted service job
            from ..service.verdict import error_verdict

            json.dump(
                error_verdict(
                    f"RESOURCE_EXHAUSTED[{e.reason}]: {e.detail}",
                    run_id=run_ctx.run_id if run_ctx is not None else None,
                    exit_code=EXIT_RESOURCE_EXHAUSTED,
                ),
                sys.stdout,
            )
            print()
        if args.checkpoint:
            print(
                f"  checkpoint intact at {args.checkpoint} — verify with "
                f"`... verify-checkpoint {args.checkpoint}`, free space "
                f"(or raise --disk-budget), then re-run the same command "
                f"to resume",
                file=sys.stderr,
            )
        else:
            print(
                "  no --checkpoint was configured: a re-run starts over "
                "(add --checkpoint to make resource exits resumable)",
                file=sys.stderr,
            )
        return EXIT_RESOURCE_EXHAUSTED
    if run_ctx is not None and spill_defaulted:
        # completed run: the spilled fingerprint data is dead weight (the
        # spill accounting lives on in metrics/spans); only a crash —
        # which never reaches here — leaves it behind for post-mortems
        import shutil

        shutil.rmtree(run_ctx.spill_dir, ignore_errors=True)
    _print_result(
        res, args.json, model_meta=model.meta,
        run_id=run_ctx.run_id if run_ctx is not None else None,
    )
    return 0 if res.violation is None else 1



def _run_simfleet(args) -> int:
    """`cli simfleet run|replay`: the deterministic fleet simulator.

    Exit codes — run: 0 = every seed clean, 1 = violations (repros
    banked under --out), 2 = bad arguments.  replay: 0 = the repro
    still reproduces its recorded violation, 2 = stale."""
    from ..resilience import simfleet as sf

    if args.sf_cmd == "run":
        cfg = sf.SimConfig(hosts=args.hosts, jobs=args.jobs,
                           steps=args.steps)
        if args.seeds < 1 or args.hosts < 1 or args.jobs < 0:
            print("error: --seeds/--hosts must be >= 1", file=sys.stderr)
            return 2
        seeds = range(args.start_seed, args.start_seed + args.seeds)
        summary = sf.sweep_seeds(
            seeds, config=cfg, coverage=args.coverage,
            max_extra=max(2, args.seeds // 5) if args.coverage else 0,
        )
        banked = []
        for hit in summary["violating"]:
            seed, record = hit["seed"], hit["record"]
            v = record["violations"][0]
            try:
                small, srec = sf.shrink(record["schedule"], cfg, seed,
                                        v["oracle"])
            except ValueError:
                # drain-phase-only violation on an empty-ish schedule:
                # the full schedule IS the minimal repro
                small, srec = record["schedule"], record
            os.makedirs(args.out, exist_ok=True)
            path = os.path.join(
                args.out, f"repro-seed{seed}-{v['oracle']}.json")
            # bank the violation as the SHRUNK run reports it: job ids
            # shift when submit events drop out of the schedule, and a
            # repro must name a job that exists in its own replay
            sv = next((w for w in srec["violations"]
                       if w["oracle"] == v["oracle"]), v)
            sf.save_repro(path, seed, cfg, sv, small, srec,
                          shrunk_from=len(record["schedule"]))
            banked.append({"seed": seed, "oracle": v["oracle"],
                           "events": len(small), "path": path})
        rec = {
            "schema": "kspec-simfleet-sweep/1",
            "config": summary["config"],
            "runs": summary["runs"],
            "clean": summary["clean"],
            "pair_coverage": summary["pair_coverage"],
            "violations": banked,
            "ok": not banked,
        }
        if args.json:
            print(json.dumps(rec))
        else:
            print(f"kspec simfleet: {rec['runs']} seed(s) — "
                  f"{rec['clean']} clean, {len(banked)} violating "
                  f"({rec['pair_coverage']} event-pair(s) covered)")
            for b in banked:
                print(f"  VIOLATION seed {b['seed']}: {b['oracle']} — "
                      f"shrunk to {b['events']} event(s), repro at "
                      f"{b['path']}")
        return 0 if rec["ok"] else 1

    # replay
    try:
        repro = sf.load_repro(args.repro)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    out = sf.replay_repro(repro, keep_root=args.trace)
    record = out["record"]
    rec = {
        "schema": "kspec-simfleet-replay/1",
        "repro": {k: repro[k] for k in
                  ("seed", "violation", "events_digest", "shrunk_from")},
        "reproduced": out["reproduced"],
        "digest_match": out["digest_match"],
        "violations": record["violations"],
    }
    try:
        if args.json:
            print(json.dumps(rec))
        else:
            v = repro["violation"]
            state = ("REPRODUCED" if out["reproduced"] else
                     "STALE (violation no longer fires)")
            print(f"kspec simfleet replay: {state} — {v['oracle']} "
                  f"on {v['job']} over {len(repro['schedule'])} "
                  f"event(s); digest "
                  f"{'match' if out['digest_match'] else 'DRIFT'}")
            for got in record["violations"]:
                print(f"  {got['oracle']} @step {got['step']} "
                      f"job={got['job']}: {got['detail']}")
            if args.trace and out["kernel"] is not None:
                from ..obs import fleettrace as ft

                job = (next((g["job"] for g in record["violations"]
                             if g.get("job")), None)
                       or v.get("job")
                       or next(iter(record["verdicts"]), None))
                if job:
                    recs = ft.load_trace(out["kernel"].trace_roots(),
                                         job)
                    if recs:
                        print()
                        print(ft.render_trace(ft.assemble(recs, job)))
                    else:
                        print(f"  (no trace records for {job})")
    finally:
        if out["kernel"] is not None:
            out["kernel"].cleanup()
    return 0 if out["reproduced"] else 2


def _run_analyze(args) -> int:
    """`cli analyze`: the spec & engine static-analysis driver.

    Exit codes: 0 = no HIGH findings, 1 = HIGH findings, 2 = a target
    could not even be analyzed (unreadable cfg, unknown module)."""
    from pathlib import Path

    from ..analysis import (
        analysis_record,
        analyze_engine_sources,
        install_jax_stub,
        repo_root,
    )

    install_jax_stub()
    findings = []
    targets = []
    rc_error = 0

    if not args.no_models:
        from ..analysis.encoding import EncodingUnsound, analyze_model

        cfg_paths = list(args.cfgs)
        if args.module and len(cfg_paths) != 1:
            # never silently drop an explicit flag: --module pairs with
            # exactly one .cfg (the default matrix resolves its own)
            print(
                "error: --module requires exactly one .cfg argument "
                f"(got {len(cfg_paths)})",
                file=sys.stderr,
            )
            return 2
        if not cfg_paths:
            cfg_paths = sorted(
                str(p) for p in Path(repo_root(), "configs").glob("*.cfg")
            )
        # stems that are not module names (TLC pairs Model.cfg with
        # Model.tla; the stretch cfg documents its explicit module)
        aliases = {"Kip320Stretch": "Kip320"}
        for path in cfg_paths:
            stem = Path(path).stem
            module = args.module or aliases.get(stem, stem)
            targets.append(f"{module} ({path})")
            try:
                tlc_cfg = parse_cfg(path)
                # analysis_gate=False: the gate raises on the FIRST HIGH
                # finding; the analyzer wants the full list instead
                model = build_model(module, tlc_cfg, analysis_gate=False)
            except EncodingUnsound as e:
                findings.extend(e.findings)
                continue
            except (OSError, ValueError, KeyError) as e:
                # the record must reflect the failure too: a JSON
                # consumer keying off `ok` must never read a partially
                # analyzed matrix as verified clean
                from ..analysis import Finding

                findings.append(Finding(
                    kind="analysis-error", severity="HIGH",
                    target=f"{module} ({path})",
                    message=f"cannot analyze: {e}",
                    data={"path": str(path), "module": module},
                ))
                print(f"error: cannot analyze {path}: {e}",
                      file=sys.stderr)
                rc_error = 2
                continue
            findings.extend(analyze_model(model))

    if not args.no_engine:
        targets.append("engine sources (ownership + purity)")
        findings.extend(analyze_engine_sources())
        # span-kind vocabulary lint (obs/fleettrace registries): every
        # span/event emitted anywhere in the package must name a
        # registered kind, and every registered kind must appear in
        # docs/observability.md — an undocumented or typo'd kind would
        # silently vanish from `cli trace`'s stage decomposition
        targets.append("trace vocabulary (obs/fleettrace registries)")
        from ..analysis import Finding
        from ..obs.fleettrace import lint_trace_vocabulary

        for prob in lint_trace_vocabulary():
            findings.append(Finding(
                kind="trace-vocab", severity="HIGH",
                target=f"{prob['path']}:{prob['line']}",
                message=prob["problem"],
                data=dict(prob),
            ))
        # durable-write discipline lint (analysis/durable_lint): every
        # rename/replace and append journal must route through the
        # durable_io shim (or a registered emitter) so the crashcheck
        # harness records it — an unrecorded durable effect is a crash
        # state the torture harness silently never enumerates
        targets.append("durable-write discipline (durable_io boundary)")
        from ..analysis.durable_lint import lint_durable_io

        for prob in lint_durable_io():
            findings.append(Finding(
                kind="durable-io", severity="HIGH",
                target=f"{prob['path']}:{prob['line']}",
                message=prob["problem"],
                data=dict(prob),
            ))
        # raw-clock discipline lint (analysis/clock_lint): every timing
        # decision in a clock-migrated module must route through
        # utils/clock.py so the simfleet virtual clock owns it — a raw
        # time.time()/sleep()/monotonic() site silently reads the real
        # wall clock under simulation and breaks seed determinism
        targets.append("raw-clock discipline (utils/clock boundary)")
        from ..analysis.clock_lint import lint_raw_clock

        for prob in lint_raw_clock():
            findings.append(Finding(
                kind="raw-clock", severity="HIGH",
                target=f"{prob['path']}:{prob['line']}",
                message=prob["problem"],
                data=dict(prob),
            ))

    rec = analysis_record(findings, targets=targets)
    if args.json:
        print(json.dumps(rec))
    else:
        c = rec["counts"]
        print(
            f"kspec analyze: {len(targets)} target(s) — "
            f"{c['HIGH']} high / {c['MEDIUM']} medium / {c['LOW']} low / "
            f"{c['INFO']} info"
        )
        shown = [f for f in findings
                 if args.info or f.severity != "INFO"]
        for f in shown:
            tag = f" [suppressed: {f.suppressed}]" if f.suppressed else ""
            print(f"  {f.severity:<6} {f.kind:<24} {f.target}{tag}")
            print(f"         {f.message}")
        if not shown:
            print("  clean: encoding sound, frames honored, ownership "
                  "contracts verified")
    if rc_error:
        return rc_error
    return 0 if rec["ok"] else 1


def _service_dir(given) -> str:
    return given or os.environ.get("KSPEC_SERVICE_DIR", "service")


def _run_router(args) -> int:
    """`cli route`: cross-host placement + dead-host recovery.  Jax-free
    by contract (it runs on the operator box, often while a host is
    down — the worst possible moment for a cold start)."""
    from ..service.router import Router

    try:
        router = Router(
            args.router_dir,
            hosts=args.hosts,
            dead_after_s=args.dead_after,
        )
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.status:
        ov = router.overview()
        if args.json:
            print(json.dumps(ov))
        else:
            print(
                f"router {ov['dir']}: {len(ov['hosts'])} hosts, "
                f"{ov['routes']} routed jobs, dead after "
                f"{ov['dead_after_s']}s (+{ov['clock_skew_s']}s skew)"
            )
            for h in ov["hosts"]:
                age = h["hb_age_s"]
                age_s = "never" if age is None else f"{age:.1f}s ago"
                print(
                    f"  host{h['host']} [{h['state']:>6}] {h['dir']}: "
                    f"{h['pending']} pending, {h['claimed']} in flight, "
                    f"heartbeat {age_s}"
                )
        return 0

    if args.once:
        out = router.sweep()
        if args.json:
            print(json.dumps(out))
        else:
            dead = [h["host"] for h in out["hosts"]
                    if h["state"] == "dead"]
            took = sum(len(v) for v in out["takeover"].values())
            moved = sum(len(v) for v in out["rerouted"].values())
            print(
                f"sweep: {len(dead)} dead hosts"
                + (f" ({', '.join(f'host{i}' for i in dead)})"
                   if dead else "")
                + f", {took} claims taken over, "
                f"{moved} pending jobs re-routed"
            )
        return 0

    print(
        f"router serving {len(router.hosts)} hosts from {router.dir} "
        f"(poll {args.poll}s)",
        file=sys.stderr,
    )
    import signal

    signal.signal(signal.SIGTERM, lambda *_: router.request_stop())
    try:
        router.serve(poll_s=args.poll)
    except KeyboardInterrupt:
        pass
    return 0


def _run_fleet_obs(args) -> int:
    """`cli trace|top|fleet-report`: the fleet trace plane's read side
    (obs/fleettrace.py, docs/observability.md § Fleet traces).  Jax-free
    by contract — everything renders from side-channel files."""
    from ..obs import fleettrace as ft

    router_dir = getattr(args, "router", None)
    if router_dir:
        from ..service.router import Router

        try:
            router = Router(router_dir)
        except (FileNotFoundError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        roots = [router.dir] + [q.dir for q in router.queues]
        host_roots = [q.dir for q in router.queues]
    else:
        host_roots = [
            os.path.normpath(d)
            for d in (getattr(args, "service_dir", None)
                      or [_service_dir(None)])
        ]
        roots = host_roots

    if args.cmd == "trace":
        recs = ft.load_trace(roots, args.job_id)
        if not recs:
            print(
                f"no trace for job {args.job_id} under "
                + ", ".join(roots),
                file=sys.stderr,
            )
            return 1
        data = ft.assemble(recs, job_id=args.job_id)
        print(json.dumps(data, default=str) if args.json
              else ft.render_trace(data))
        return 0

    if args.cmd == "fleet-report":
        data = ft.fleet_report_data(roots, exemplars=args.exemplars)
        if args.json:
            print(json.dumps(data, default=str))
        else:
            print(ft.render_fleet_report(data))
        return 0

    # top: one frame under --once/--json, else redraw until interrupted
    if args.json:
        print(json.dumps(
            ft.top_data(host_roots, router_dir=router_dir), default=str
        ))
        return 0
    try:
        while True:
            frame = ft.render_top(
                ft.top_data(host_roots, router_dir=router_dir)
            )
            if args.once:
                print(frame)
                return 0
            # whole-frame redraw: clear + home, then the frame (the
            # watch(1) idiom; no curses dependency)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


def _run_sweep(args) -> int:
    """`cli sweep plan|run|report|bisect`: the coverage-sweep subsystem
    (sweep/ package, docs/sweep.md).  Jax-free by contract — a sweep is
    a queue/router client; daemons do the engine work."""
    from ..sweep import (
        SweepConfig,
        load_lattice,
        load_manifest,
        plan_sweep,
        run_sweep,
    )

    if args.sweep_cmd == "plan":
        try:
            lattice = load_lattice(args.lattice)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        cfg = SweepConfig(
            sweep_dir=".",  # plan never writes
            service_dir=_service_dir(getattr(args, "service_dir", None)),
            state_cache_dir=args.state_cache_dir,
        )
        plan = plan_sweep(lattice, cfg)
        if args.json:
            print(json.dumps({
                "lattice": lattice.record(),
                "points": len(plan["points"]),
                "runnable": len(plan["runnable"]),
                "deferred": len(plan["deferred"]),
                "skipped": [
                    {"point": p.record(), "findings": p.vacuous}
                    for p in plan["skipped"]
                ],
                "cost_model": plan["model"].to_dict(),
                "predictions": plan["predictions"],
            }))
            return 0
        m = plan["model"]
        total_states = sum(
            plan["predictions"][p.point_id]["states"]
            for p in plan["runnable"] + plan["deferred"]
        )
        total_s = sum(
            plan["predictions"][p.point_id]["seconds"] or 0.0
            for p in plan["runnable"] + plan["deferred"]
        )
        print(
            f"lattice {lattice.name}: {len(plan['points'])} points "
            f"({len(plan['runnable'])} runnable, "
            f"{len(plan['deferred'])} deferred, "
            f"{len(plan['skipped'])} skipped as statically vacuous)"
        )
        print(
            f"cost model: {m.n_records} corpus records, predicted "
            f"~{total_states} states, ~{total_s:.1f}s engine wall "
            "(flat-throughput; honesty limits in docs/sweep.md)"
        )
        for p in plan["skipped"][:8]:
            acts = ", ".join(
                f.get("target", "?") for f in p.vacuous[:3]
            )
            print(f"  skipped: vacuous {dict(p.coords)} [{acts}]")
        if len(plan["skipped"]) > 8:
            print(f"  ... and {len(plan['skipped']) - 8} more")
        return 0

    if args.sweep_cmd == "run":
        try:
            lattice = load_lattice(args.lattice)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        cfg = SweepConfig(
            sweep_dir=args.sweep_dir,
            service_dir=(
                None if args.router
                else _service_dir(args.service_dir)
            ),
            router_dir=args.router,
            tenant=args.tenant,
            max_inflight=args.max_inflight,
            solo_threshold_states=args.solo_threshold,
            wait_timeout_s=args.timeout,
            state_cache_dir=args.state_cache_dir,
        )
        rec = run_sweep(lattice, cfg, log=lambda s: print(s))
        if args.json:
            print(json.dumps(rec))
        incomplete = sum(
            1 for row in rec["points"].values()
            if row.get("status") in ("pending", "submitted")
        )
        errors = sum(
            1 for row in rec["points"].values()
            if row.get("status") == "error"
        )
        return 1 if errors else (75 if incomplete else 0)

    if args.sweep_cmd == "report":
        from ..obs.report import render_sweep_report, sweep_report_data

        try:
            data = sweep_report_data(args.sweep_dir)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps(data) if args.json else render_sweep_report(data))
        return 0

    # bisect: witness the frontier (runs neighbors through the service)
    from ..sweep.bisect import refine_frontier
    from ..sweep.lattice import enumerate_points
    from ..sweep.portfolio import Dispatcher

    try:
        man = load_manifest(args.sweep_dir)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    runner = None
    if args.service_dir or args.router:
        cfg = SweepConfig(
            sweep_dir=args.sweep_dir,
            service_dir=(
                None if args.router
                else _service_dir(args.service_dir)
            ),
            router_dir=args.router,
            tenant=args.tenant,
        )
        dispatch = Dispatcher(cfg)

        def runner(coords):
            # synthesize the probe point by re-enumerating the lattice
            # restricted to these coordinates: same canonical keys, so
            # the probe may itself be a state-cache hit
            from ..sweep.lattice import load_lattice as _ll

            spec = _ll(dict(man["lattice"]))
            want = dict(coords)
            for p in enumerate_points(spec):
                if dict(p.coords) == want:
                    import os as _os

                    jid = (
                        f"probe-{man['sweep_id']}-"
                        f"{p.point_id.replace(':', '-')}-"
                        f"{_os.urandom(2).hex()}"
                    )
                    dispatch.submit(p, jid, solo=True)
                    rec = dispatch.backend.wait_result(
                        jid, timeout=args.timeout
                    )
                    return rec or {}
            return {}
    else:

        def runner(coords):
            return {}  # manifest-only mode: unknown neighbors stay unrun

    out = refine_frontier(
        man, runner, log=lambda s: print(s, file=sys.stderr),
        invariant=args.invariant, max_probes=args.max_probes,
    )
    if args.json:
        print(json.dumps(out))
        return 0
    if not out:
        print("no violating points in the manifest — nothing to bisect")
        return 0
    for inv in sorted(out):
        rep = out[inv]
        print(f"{inv}: frontier of {len(rep['frontier'])} minimal "
              f"violating configs ({len(rep['witnesses'])} neighbors "
              f"witnessed, {len(rep['demoted'])} claims demoted)")
        for r in rep["frontier"]:
            coords = r.get("coords")
            print(f"  {dict(coords) if coords else r.get('_indices')}")
    return 0


def _run_service_client(args) -> int:
    """submit / status / result: the tenants' side of the service.  Only
    jax-free imports allowed here — the zero-cold-start contract."""
    from ..service.queue import JobQueue
    from ..service.verdict import render_verdict, verdict_exit_code

    router = None
    if getattr(args, "router", None):
        # --router: resolve through the cross-host router instead of a
        # single service dir (still jax-free — router.py never imports
        # jax).  Placement and the fleet-WIDE tenant admission check
        # live inside Router.submit
        from ..service.router import Router

        try:
            router = Router(args.router)
        except (FileNotFoundError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        q = None
    else:
        try:
            # submit creates the tree (tenants may enqueue before the
            # first daemon start); status/result are read-only so a
            # mistyped --service-dir errors instead of minting an empty
            # service tree
            q = JobQueue(
                _service_dir(args.service_dir), create=args.cmd == "submit"
            )
        except FileNotFoundError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if args.cmd == "submit":
        from pathlib import Path

        try:
            cfg_text = Path(args.cfg).read_text()
        except OSError as e:
            print(f"error: cannot read {args.cfg}: {e}", file=sys.stderr)
            return 2
        module = args.module or Path(args.cfg).stem
        try:
            tlc_cfg = parse_cfg(cfg_text)  # validate before queueing
        except ValueError as e:
            print(f"error: cannot parse {args.cfg}: {e}", file=sys.stderr)
            return 2
        if args.hand and args.emitted:
            print("error: --hand and --emitted are mutually exclusive",
                  file=sys.stderr)
            return 2
        if args.fault:
            from ..resilience.faults import FaultPlan

            try:
                FaultPlan(args.fault)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
        if router is None:
            # admission control: the tenant's max_pending cap (advisory
            # — the check is client-side so a racing burst can
            # overshoot; the budget that matters, the resource
            # governor, is daemon-side).  With --router the check moves
            # inside Router.submit, where it is fleet-wide
            from ..resilience.resources import (
                budget_for_tenant,
                load_tenant_budgets,
            )

            try:
                budgets = load_tenant_budgets(q.tenants_path)
            except (OSError, ValueError) as e:
                print(f"error: bad tenants.json: {e}", file=sys.stderr)
                return 2
            b = budget_for_tenant(budgets, args.tenant)
            if b is not None and b.max_pending is not None:
                mine = q.pending_for_tenant(
                    args.tenant, stop_at=b.max_pending
                )
                if mine >= b.max_pending:
                    print(
                        f"error: tenant {args.tenant!r} at max_pending="
                        f"{b.max_pending} ({mine} queued) — drain or raise "
                        f"the cap in tenants.json",
                        file=sys.stderr,
                    )
                    return 2
        kernel_source = (
            "emitted" if args.emitted else "hand" if args.hand else "auto"
        )
        try:
            # the submit-side router retries transient queue-dir errors
            # (EAGAIN/EIO/ESTALE — network filesystems) with bounded
            # backoff inside JobQueue.submit; only a PERSISTENT failure
            # reaches here, rendered cleanly instead of as a traceback
            spec = (router or q).submit(
                cfg_text,
                module,
                tenant=args.tenant,
                cfg_path=args.cfg,
                kernel_source=kernel_source,
                max_depth=args.max_depth,
                max_states=args.max_states,
                fault=args.fault,
            )
        except OSError as e:
            where = router.dir if router is not None else q.dir
            print(
                f"error: cannot publish job to {where!r} after retries: "
                f"{e}",
                file=sys.stderr,
            )
            return 2
        except RuntimeError as e:
            # AdmissionDenied: the router's fleet-wide tenant cap
            print(f"error: {e}", file=sys.stderr)
            return 2
        where = (
            f"host{spec['host']} ({router.hosts[spec['host']]})"
            if router is not None
            else q.dir
        )
        if args.json and not args.wait:
            out = {"job_id": spec["job_id"]}
            if router is not None:
                out["host"] = spec["host"]
                out["service_dir"] = router.hosts[spec["host"]]
            else:
                out["service_dir"] = q.dir
            print(json.dumps(out))
        else:
            print(f"submitted {spec['job_id']} (tenant {args.tenant}) -> "
                  f"{where}", file=sys.stderr)
        if not args.wait:
            if not args.json:
                print(spec["job_id"])
            return 0
        rec = (router or q).wait_result(spec["job_id"], timeout=args.timeout)
        if rec is None:
            hint = (
                f"`cli route {router.dir} --status`" if router is not None
                else f"`cli serve {q.dir}`"
            )
            print(
                f"error: no verdict for {spec['job_id']} within "
                f"{args.timeout}s (is the daemon up?  {hint})",
                file=sys.stderr,
            )
            return 2
        print(json.dumps(rec) if args.json else render_verdict(rec))
        return verdict_exit_code(rec)

    if args.cmd == "status":
        if args.job_id is None:
            ov = (router or q).overview()
            if args.json:
                print(json.dumps(ov))
            elif router is not None:
                print(
                    f"router {ov['dir']}: {len(ov['hosts'])} hosts, "
                    f"{ov['routes']} routed jobs"
                )
                for h in ov["hosts"]:
                    print(
                        f"  host{h['host']} [{h['state']:>6}] {h['dir']}: "
                        f"{h['pending']} pending, {h['claimed']} in flight"
                    )
            else:
                c = ov["counts"]
                print(
                    f"service {ov['dir']}: {c['pending']} pending, "
                    f"{c['claimed']} in flight, {c['done']} done"
                )
                for jid in ov["recent_done"]:
                    rec = q.result(jid) or {}
                    print(f"  {jid}  {rec.get('status', '?')}")
            return 0
        st = (router or q).status(args.job_id)
        if args.json:
            print(json.dumps(st))
        else:
            line = f"{st['job_id']}: {st['state']}"
            if st.get("host") is not None:
                line += f" @ host{st['host']}"
            rec = st.get("result")
            if rec:
                line += f" ({rec.get('status', '?')})"
            print(line)
        return 0 if st["state"] != "unknown" else 1

    # result
    rec = (
        (router or q).wait_result(args.job_id, timeout=args.timeout)
        if args.wait
        else (router or q).result(args.job_id)
    )
    if rec is None:
        print(
            f"error: no verdict for {args.job_id}"
            + ("" if args.wait else " (yet — use --wait)"),
            file=sys.stderr,
        )
        return 2
    print(json.dumps(rec) if args.json else render_verdict(rec))
    return verdict_exit_code(rec)


def _print_verify_checkpoint(rep: dict) -> None:
    print(f"Checkpoint directory: {rep['dir']}")
    if rep.get("error"):
        print(f"  ERROR: {rep['error']}")
    if not rep["stores"]:
        print("  no checkpoint files found")
    for store in rep["stores"]:
        print(f"  {store['basename']}: "
              f"{'OK' if store['ok'] else 'NOT RESUMABLE'}")
        for g in store["generations"]:
            bits = [f"gen {g['gen']}", f"depth {g.get('depth')}"]
            if "mesh_D" in g:
                bits.append(f"shards {g['mesh_D']} x procs {g.get('mesh_P')}")
            if g.get("digest_chain") and g["digest_chain"] != "absent":
                bits.append(f"chain {g['digest_chain']}")
            if g.get("parts"):
                bits.append(
                    "parts " + ",".join(
                        f"{p}@{gen}" if gen is not None else f"{p}@MISSING"
                        for p, gen in sorted(g["parts"].items())
                    )
                )
            if "spill" in g:
                bits.append(
                    f"spill {g['spill']['files_checked']} files "
                    + ("resolved" if g["spill"]["ok"] else "BROKEN")
                )
            status = "ok" if g["ok"] else "FAILED"
            print(f"    {status:>6}  " + "  ".join(bits))
            for e in g["errors"]:
                print(f"            - {e}")
    print(f"Verdict: {'resumable' if rep['ok'] else 'NOT resumable'}")


def _is_obs_coordinator() -> bool:
    """True unless this is a non-coordinator process of a multi-process
    jax job (jax is initialized by model building before this runs)."""
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def _run_resilient(args, argv) -> int:
    """`check --resilient`: re-run this command under the supervisor.

    The child is this same CLI minus --resilient; engines resume from
    --checkpoint automatically, so a restart is just a re-run.  The parent
    opens the run directory and hands it to every child attempt: one
    run_id correlates the supervisor's events with each attempt's stats
    and spans (a restart reopens the run, appending to its lineage)."""
    from pathlib import Path

    from ..obs import RunContext
    from ..resilience.supervisor import SupervisorConfig, supervise

    # strip the flag AND its argparse prefix abbreviations ("--resil" also
    # sets args.resilient; letting it through would make every child spawn
    # its own supervisor recursively)
    child_argv = [
        a
        for a in argv
        if not (a.startswith("--re") and "--resilient".startswith(a))
    ]
    run_ctx = RunContext(args.run_dir)
    if args.run_dir is None:
        child_argv += ["--run-dir", run_ctx.dir]
    if not args.stats:
        # heartbeat lives in the run dir by default — the stall detector
        # always has a stream to watch
        args.stats = run_ctx.stats_path
        child_argv += ["--stats", args.stats]
    if not args.checkpoint:
        print(
            "warning: --resilient without --checkpoint — a restarted run "
            "starts over from the initial states",
            file=sys.stderr,
        )
    events = args.events or run_ctx.events_path
    run_ctx.record_config(
        module=args.module or Path(args.cfg).stem,
        cfg=args.cfg,
        supervised=True,
        stall_timeout=args.stall_timeout,
        max_restarts=args.max_restarts,
    )
    if args.checkpoint:
        os.makedirs(args.checkpoint, exist_ok=True)
    print(
        f"[obs] run dir: {run_ctx.dir} (run {run_ctx.run_id})",
        file=sys.stderr,
    )
    cfg = SupervisorConfig(
        cmd=[sys.executable, "-m", "kafka_specification_tpu.utils.cli"]
        + child_argv,
        heartbeat=args.stats,
        events=events,
        log_dir=run_ctx.log_dir,
        stall_timeout=args.stall_timeout,
        max_restarts=args.max_restarts,
        env=dict(os.environ),
        run_id=run_ctx.run_id,
        # resource-exit policy: halt with a verdict, or prune + retry
        # once under --reclaim (never restart into a full disk)
        reclaim=bool(args.reclaim),
        reclaim_dirs=tuple(
            d for d in (args.checkpoint, args.spill_dir) if d
        ),
    )
    return supervise(cfg)


def _kernel_source(args, module) -> bool:
    """Resolve check/simulate kernel source: True = emitted (the default
    when the reference corpus is on disk), False = hand-translated.

    The north star wants stock specs + .cfg to drive the checker — so the
    mechanical path is the default engine and the hand kernels are the
    independent cross-check (`--hand`), mirroring how the test suite holds
    the two to exact state-set equality."""
    if args.hand and args.emitted:
        print("error: --hand and --emitted are mutually exclusive", file=sys.stderr)
        raise SystemExit(2)
    if args.hand:
        return False
    if args.emitted:
        return True
    from ..models.emitted import ref_path

    ref = ref_path()
    if (ref / f"{module}.tla").exists():
        return True
    print(
        f"note: no reference checkout at {ref} (set KSPEC_REFERENCE) — "
        f"using hand-translated kernels",
        file=sys.stderr,
    )
    return False


def _build_or_fail(module, tlc_cfg, oracle=False, emitted=False, reference=None):
    try:
        return build_model(
            module, tlc_cfg, oracle=oracle, emitted=emitted, reference=reference
        )
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        raise SystemExit(2)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        raise SystemExit(2)


def _run_engine(args, model, tlc_cfg, progress, chunk_kw, run=None):
    store_kw = dict(
        mem_budget=args.mem_budget,
        spill_dir=args.spill_dir,
        store=args.store,
        disk_budget=args.disk_budget,
        run=run,
        overlap=getattr(args, "overlap", None),
    )
    if args.sharded:
        from ..parallel.sharded import check_sharded

        res = check_sharded(
            model,
            max_depth=args.max_depth,
            max_states=args.max_states,
            min_bucket=args.min_bucket,
            progress=progress,
            check_deadlock=tlc_cfg.check_deadlock,
            store_trace=not args.no_trace,
            checkpoint_dir=args.checkpoint,
            checkpoint_every=args.checkpoint_every,
            checkpoint_keep=args.checkpoint_keep,
            stats_path=args.stats,
            visited_backend=args.visited_backend,
            pipeline=getattr(args, "pipeline", None),
            **store_kw,
            **chunk_kw,
        )
    else:
        from ..engine.bfs import check

        res = check(
            model,
            max_depth=args.max_depth,
            max_states=args.max_states,
            store_trace=not args.no_trace,
            min_bucket=args.min_bucket,
            progress=progress,
            checkpoint_dir=args.checkpoint,
            checkpoint_every=args.checkpoint_every,
            checkpoint_keep=args.checkpoint_keep,
            check_deadlock=tlc_cfg.check_deadlock,
            stats_path=args.stats,
            visited_backend=args.visited_backend,
            pipeline=getattr(args, "pipeline", None),
            integrity_shadow=getattr(args, "integrity_shadow", None),
            **store_kw,
            **chunk_kw,
        )
    return res


if __name__ == "__main__":
    sys.exit(main())
