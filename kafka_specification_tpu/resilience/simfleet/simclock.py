"""The virtual clock the simulation installs over `utils/clock.py`.

One instance owns all of simulated time.  ``t`` is the global (true)
timeline; ``offset`` is the ACTING host's clock skew, set by the kernel
before each actor step — so wall stamps written by a skewed host
(`lease_unix`, heartbeat ``unix``, route ``at``) carry that host's
drifted view while the kernel keeps judging ground truth on ``t``.

``sleep`` advances ``t`` instead of blocking: a retry backoff ladder or
a poll loop inside production code costs virtual time only, which is
what makes a 500-seed soak finish in seconds of wall time.
"""

from __future__ import annotations

from ...utils import clock as _clock

#: virtual epoch, deliberately in the future of any real wall clock the
#: test venue can have: a stray REAL-mtime file (a tmp the sim didn't
#: stamp) reads as ancient under virtual time, and the only consumer of
#: that age (the leaseless-claim grace window) degrades by requeueing an
#: idempotent job — safe, and deterministic in every comparison that
#: matters
SIM_EPOCH = 2_000_000_000.0


class SimClock(_clock.Clock):
    """Virtual wall + monotonic time with a per-actor skew offset."""

    def __init__(self, start: float = SIM_EPOCH):
        self.t = float(start)        # ground-truth timeline
        self.offset = 0.0            # acting host's skew (kernel-set)
        self.slept = 0.0             # total virtual sleep (diagnostics)

    def now(self) -> float:
        return self.t + self.offset

    def monotonic(self) -> float:
        # monotonic is only ever used for LOCAL durations; skew (a wall
        # phenomenon) must not leak into it
        return self.t

    def sleep(self, seconds: float) -> None:
        s = max(0.0, float(seconds))
        self.t += s
        self.slept += s

    def advance(self, seconds: float) -> None:
        """Kernel-driven time advance (scheduler `dt`, not a sleep)."""
        self.t += max(0.0, float(seconds))
