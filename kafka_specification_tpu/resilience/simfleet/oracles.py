"""Invariant oracles over a simulated fleet run.

Each oracle judges the run against ground truth the kernel keeps on the
true (unskewed) timeline — the whole point of simulation is that the
judge sees through the skewed stamps production code must reason under.

``live-claim-stolen``      a janitor/sweep requeued a claim whose owner
                           daemon was alive, connected, holding a LANDED
                           lease renewed less than ``lease_ttl`` (true
                           seconds) ago.  Within the skew allowance the
                           widened expiry window makes this impossible;
                           seeing it means the lease math regressed.
``double-run``             a daemon claimed a job that another alive,
                           connected daemon was already executing —
                           i.e. a runnable copy was duplicated, not
                           handed over.  (A *partitioned* owner losing
                           its claim at TTL is the documented
                           at-least-once case and is exempt.)
``duplicate-runnable-copy`` a job had more than one pending/claimed
                           spec across the fleet after a step — the
                           exactly-once re-route/takeover rename
                           protocols both exist to prevent this.
``job-lost``               a job had no runnable copy, no protocol-
                           private file, and no verdict — nobody can
                           ever finish it.
``cache-torn-read``        a cache lookup raised, or served a verdict
                           that differs from the canonical verdict for
                           that key (readers must see whole entries or
                           nothing).
``missing-verdict``        after the drain a submitted job still has no
                           routed verdict.
``conflicting-verdicts``   two hosts hold different verdict content for
                           one job (identical duplicate files are the
                           accepted at-least-once residue; different
                           ones are not).
``fleet-failed-to-drain``  the bounded-liveness oracle: the fixed drain
                           protocol exhausted its rounds with work still
                           undone after every fault healed.
"""

from __future__ import annotations

import json
import os


def _violate(kernel, kind: str, job, detail: str, step=None) -> None:
    kernel.violations.append({
        "oracle": kind,
        "job": job,
        "t": round(kernel.clock.t, 3),
        "step": step if step is not None else len(kernel.events),
        "detail": detail,
    })


# --- at takeover sites ------------------------------------------------------

def check_takeover(kernel, moved: list, by: str) -> None:
    """O: live-claim-stolen.  ``moved`` is a janitor's requeued list."""
    for jid in moved:
        c = kernel.claims.get(jid)
        if not c or not c.get("landed"):
            continue  # lease never landed: documented grace degradation
        h = kernel.hosts[c["host"]]
        d = h.daemon
        if d.gen != c["gen"] or not d.alive or not d.connected:
            continue  # owner dead/partitioned: legitimate takeover
        if jid not in d.running:
            continue  # owner already finished or abandoned it
        age = kernel.clock.t - c["renewed_true"]
        if age < kernel.cfg.lease_ttl:
            _violate(
                kernel, "live-claim-stolen", jid,
                f"{by} stole {jid} from live host{c['host']} "
                f"gen{c['gen']} with a lease renewed {age:.3f}s ago "
                f"(ttl {kernel.cfg.lease_ttl}s)")


# --- at claim sites ---------------------------------------------------------

def check_claim(kernel, jid: str, host: int) -> None:
    """O: double-run.  Called before the claiming daemon starts the job.

    At-least-once execution after a GENUINE lease expiry (an executor
    that stopped renewing past the TTL, e.g. wedged or heartbeat-
    starved) is the documented contract, so the violation is scoped to
    what must never happen: a second claim while the original executor
    is alive, connected, and holding a landed lease younger than the
    TTL on the true timeline — i.e. a duplicated runnable copy or a
    stolen live claim, not a handover."""
    c = kernel.claims.get(jid)
    for (oh, ogen) in sorted(kernel.running_by.get(jid, ())):
        od = kernel.hosts[oh].daemon
        if not (od.gen == ogen and od.alive and od.connected):
            continue
        if not (c and c.get("landed")
                and c.get("host") == oh and c.get("gen") == ogen):
            continue  # lease never landed: grace-window degradation
        age = kernel.clock.t - c["renewed_true"]
        if age < kernel.cfg.lease_ttl:
            _violate(
                kernel, "double-run", jid,
                f"host{host} claimed {jid} while live+connected "
                f"host{oh} gen{ogen} still executes it under a lease "
                f"renewed {age:.3f}s ago (ttl {kernel.cfg.lease_ttl}s)")


# --- at cache-read sites ----------------------------------------------------

def check_cache_lookup(kernel, jid: str, module: str, key):
    """O: cache-torn-read.  Returns the hit (or None) for the caller."""
    from ...service import state_cache as sc

    try:
        hit = kernel.cache.lookup(key)
    except OSError:
        # an fs fault surfacing from lookup is environment, not cache
        # integrity — callers treat it as a miss and run the job; the
        # injected flaky-fs schedule hits this path on purpose
        return None
    except Exception as e:  # noqa: BLE001 - typed fallback is the contract
        _violate(kernel, "cache-torn-read", jid,
                 f"lookup raised {type(e).__name__}: {e}")
        return None
    if hit is None:
        return None
    if not isinstance(hit, sc.CacheHit):
        return None  # a seed is a miss to the stub engine
    expected = kernel._stub_verdict(module)
    got = {k: hit.verdict.get(k)
           for k in ("model", "distinct_states", "exit_code", "violation")}
    want = {k: expected.get(k)
            for k in ("model", "distinct_states", "exit_code", "violation")}
    if got != want:
        _violate(kernel, "cache-torn-read", jid,
                 f"hit served {got} where the canonical verdict is {want}")
        return None
    return hit


# --- after every step -------------------------------------------------------

def _runnable_copies(kernel, jid: str) -> list:
    out = []
    for h in kernel.hosts:
        q = h.daemon.queue
        for state in ("pending", "claimed"):
            if os.path.isfile(q._job_path(state, jid)):
                out.append(f"host{h.index}/{state}")
    return out

def _private_copies(kernel, jid: str) -> list:
    out = []
    for h in kernel.hosts:
        q = h.daemon.queue
        for state in ("pending", "claimed"):
            d = os.path.join(q.queue_dir, state)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for n in sorted(names):
                if (n.startswith(jid + ".json.requeue-")
                        or n.startswith(jid + ".json.reroute-")):
                    out.append(f"host{h.index}/{state}/{n}")
    return out


def _verdict_files(kernel, jid: str) -> list:
    out = []
    for h in kernel.hosts:
        p = h.daemon.queue.result_path(jid)
        if os.path.isfile(p):
            out.append(p)
    return out


def check_copies(kernel, step: int) -> None:
    """O: duplicate-runnable-copy + job-lost, between every two steps
    (each step runs whole protocol functions, so mid-protocol states
    are never observed — exactly the atomicity the rename protocols
    promise)."""
    for jid in kernel.submitted:
        copies = _runnable_copies(kernel, jid)
        if len(copies) > 1:
            _violate(kernel, "duplicate-runnable-copy", jid,
                     f"runnable in {copies}", step=step)
            continue
        if copies:
            continue
        if _verdict_files(kernel, jid) or _private_copies(kernel, jid):
            continue
        _violate(kernel, "job-lost", jid,
                 "no runnable copy, no protocol-private file, no verdict",
                 step=step)


# --- final ------------------------------------------------------------------

def check_final(kernel, drained: bool) -> None:
    """O: missing-verdict + conflicting-verdicts + fleet-failed-to-drain."""
    if not drained:
        undone = [jid for jid in kernel.submitted
                  if kernel._safe(lambda: kernel.router.result(jid))
                  is None]
        _violate(kernel, "fleet-failed-to-drain", None,
                 f"drain rounds exhausted with {sorted(undone)} undone")
    kernel._as_actor(None)
    for jid in sorted(kernel.submitted):
        routed = kernel._safe(lambda: kernel.router.result(jid))
        if routed is None:
            if drained:
                _violate(kernel, "missing-verdict", jid,
                         "drained fleet serves no verdict")
            continue
        seen = []
        for p in _verdict_files(kernel, jid):
            try:
                with open(p) as fh:
                    v = json.load(fh)
            except (OSError, ValueError) as e:
                _violate(kernel, "conflicting-verdicts", jid,
                         f"unreadable verdict file {p}: {e}")
                continue
            seen.append({k: v.get(k) for k in
                         ("model", "distinct_states", "exit_code",
                          "violation", "job_id")})
        if any(s != seen[0] for s in seen[1:]):
            _violate(kernel, "conflicting-verdicts", jid,
                     f"hosts disagree: {seen}")
