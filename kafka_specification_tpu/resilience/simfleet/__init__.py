"""Deterministic fleet simulation: seed-driven interleaving search over
the whole jax-free control plane, with schedule shrinking.

The chaos grammar (`resilience/faults`) and crashcheck
(`resilience/crashcheck`) prove the fleet survives *hand-picked* faults
at *hand-picked* points; this package searches the faults nobody picked.
It runs the REAL router + queue + daemon(stub-engine) + federated-cache
code in one process under a virtual clock (`utils/clock.py`) and a
seeded discrete-event scheduler that owns every yield point — sleeps,
lease/heartbeat stamps, fs-op faults via the `durable_io` hook, per-host
clock skew, host kill/partition — so one integer seed determines the
entire interleaving, FoundationDB-style.

After every run a set of invariant oracles judges the final state and
the event history; any violation is shrunk (event-subset + delay
reduction) to a minimal schedule persisted as a ``kspec-simfleet/1``
repro, replayable bit-for-bit via ``cli simfleet replay``.

Layout:

``simclock``  the virtual `Clock` (wall + per-host skew offset +
              sleep-advances-time)
``kernel``    actors, actions, fault injection, the event log, one run
``oracles``   the invariant checks (verdict-exactly-once, live-claim
              never stolen, single runnable copy, cache-torn-read,
              bounded drain)
``search``    seed sweep, ddmin shrinking, repro persist/load/replay
"""

from .simclock import SIM_EPOCH, SimClock
from .kernel import SimConfig, SimKernel, run_schedule, run_seed
from .search import (
    REPRO_SCHEMA,
    load_repro,
    replay_repro,
    save_repro,
    shrink,
    sweep_seeds,
)

__all__ = [
    "SIM_EPOCH", "SimClock",
    "SimConfig", "SimKernel", "run_seed", "run_schedule",
    "REPRO_SCHEMA", "sweep_seeds", "shrink",
    "save_repro", "load_repro", "replay_repro",
]
