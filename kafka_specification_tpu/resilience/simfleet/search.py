"""Seed sweep, schedule shrinking, and the ``kspec-simfleet/1`` repro.

A sweep runs generation-mode seeds and, for each violating seed, shrinks
the recorded schedule to a minimal one that still trips the same oracle:
ddmin-style chunk removal over the event list, then single-event
removal, then a delay-zeroing pass (a step that only matters for its
time advance survives with ``dt`` intact; one that doesn't loses it).
Replay of a subset works because the kernel skips entries that no
longer apply — dropping a ``kill`` simply makes the later ``restart``
a no-op, not an error.

The minimal schedule is persisted as a ``kspec-simfleet/1`` file:

    {"schema": "kspec-simfleet/1",
     "seed": <int>,                    # feeds the retry-jitter RNG
     "config": {...SimConfig...},
     "violation": {"oracle": ..., "job": ..., "detail": ...},
     "schedule": [{"a","h","x","dt"}, ...],
     "events_digest": <sha256 of the shrunk run's surface>,
     "shrunk_from": <original step count>}

``replay_repro`` re-runs the schedule and reports whether the recorded
oracle fires again AND the determinism surface digest matches — a repro
that stops reproducing (the bug got fixed, or the tree drifted) is
reported stale, never silently green.
"""

from __future__ import annotations

import json

from ... import durable_io as _dio
from .kernel import SimConfig, run_schedule, run_seed

REPRO_SCHEMA = "kspec-simfleet/1"

#: per-candidate replay budget during shrinking — ddmin on an 80-step
#: schedule stays well under a second per candidate, but a pathological
#: run record must not turn shrinking into the slow part of a sweep
MAX_SHRINK_RUNS = 400


def _violates(record: dict, oracle: str) -> bool:
    return any(v["oracle"] == oracle for v in record["violations"])


def shrink(schedule: list, config: SimConfig, seed: int,
           oracle: str) -> tuple:
    """-> (minimal schedule, its run record).  The predicate is "the
    same oracle still fires"; every candidate is a full deterministic
    replay."""
    runs = 0

    def trial(cand: list):
        nonlocal runs
        if runs >= MAX_SHRINK_RUNS:
            return None
        runs += 1
        rec, _ = run_schedule(cand, config=config, seed=seed)
        return rec if _violates(rec, oracle) else None

    best = list(schedule)
    best_rec = trial(best)
    if best_rec is None:
        raise ValueError(
            f"schedule does not reproduce oracle {oracle!r}")
    # ddmin: drop halves, then quarters, ... of the event list
    chunk = max(1, len(best) // 2)
    while chunk >= 1:
        i = 0
        progressed = False
        while i < len(best) and len(best) > 1:
            cand = best[:i] + best[i + chunk:]
            rec = trial(cand)
            if rec is not None:
                best, best_rec = cand, rec
                progressed = True
            else:
                i += chunk
        if chunk == 1 and not progressed:
            break
        chunk = max(1, chunk // 2) if chunk > 1 else 0
    # delay-zeroing: keep an event, drop its time advance
    for i in range(len(best)):
        if best[i].get("dt"):
            cand = [dict(e) for e in best]
            cand[i]["dt"] = 0.0
            rec = trial(cand)
            if rec is not None:
                best, best_rec = cand, rec
    return best, best_rec


def save_repro(path: str, seed: int, config: SimConfig, violation: dict,
               schedule: list, record: dict, shrunk_from: int) -> dict:
    repro = {
        "schema": REPRO_SCHEMA,
        "seed": seed,
        "config": config.to_dict(),
        "violation": {k: violation[k]
                      for k in ("oracle", "job", "detail")},
        "schedule": schedule,
        "events_digest": record["digest"],
        "shrunk_from": shrunk_from,
    }
    _dio.write_text(path, json.dumps(repro, indent=1, sort_keys=True)
                    + "\n", fsync=True)
    return repro


def load_repro(path: str) -> dict:
    with open(path) as fh:
        repro = json.load(fh)
    if repro.get("schema") != REPRO_SCHEMA:
        raise ValueError(
            f"not a {REPRO_SCHEMA} file: {repro.get('schema')!r}")
    return repro


def replay_repro(repro: dict, keep_root: bool = False) -> dict:
    """-> {"reproduced": bool, "digest_match": bool, "record": ...,
    "kernel": SimKernel|None}.  ``keep_root`` leaves the simulated
    host/router dirs on disk (the ``--trace`` waterfall reads them)."""
    cfg = SimConfig.from_dict(repro["config"])
    rec, kernel = run_schedule(repro["schedule"], config=cfg,
                               seed=int(repro.get("seed") or 0),
                               keep=keep_root)
    return {
        "reproduced": _violates(rec, repro["violation"]["oracle"]),
        "digest_match": rec["digest"] == repro.get("events_digest"),
        "record": rec,
        "kernel": kernel if keep_root else None,
    }


def _pair_coverage(record: dict) -> set:
    """Adjacent event-type pairs the run exercised — the cheap schedule-
    shape signal the coverage-guided sweep steers on."""
    acts = [e["a"] for e in record["events"] if not e["out"].get("skipped")]
    return {(a, b) for a, b in zip(acts, acts[1:])}


def sweep_seeds(seeds, config: SimConfig = None, coverage: bool = False,
                max_extra: int = 0, progress=None) -> dict:
    """Run generation-mode seeds; -> summary with any violations (one
    entry per violating seed, carrying the full run record for
    shrinking).  ``coverage=True`` queues up to ``max_extra`` derived
    seeds (seed*1000+k) behind any seed whose run reached new adjacent
    event-type pairs — interleaving neighborhoods that discover new
    schedule shapes get searched harder."""
    config = config or SimConfig()
    seen_pairs: set = set()
    queue = list(seeds)
    extra_budget = max_extra if coverage else 0
    out = {"config": config.to_dict(), "runs": 0, "clean": 0,
           "violating": [], "pair_coverage": 0}
    while queue:
        seed = queue.pop(0)
        record = run_seed(seed, config=config)
        out["runs"] += 1
        if progress is not None:
            progress(seed, record)
        if record["violations"]:
            out["violating"].append({"seed": seed, "record": record})
        else:
            out["clean"] += 1
        if coverage:
            pairs = _pair_coverage(record)
            fresh = pairs - seen_pairs
            seen_pairs |= pairs
            if fresh and extra_budget > 0:
                derived = [seed * 1000 + k for k in (1, 2)]
                derived = derived[:extra_budget]
                extra_budget -= len(derived)
                queue.extend(derived)
    out["pair_coverage"] = len(seen_pairs)
    return out
