"""The simulation kernel: real control-plane code, simulated schedule.

One :class:`SimKernel` run drives REAL ``JobQueue``/``Router``/
``StateSpaceCache`` instances (plus a stub-engine daemon actor) inside
one process, under the virtual :class:`~.simclock.SimClock` and a
recorded schedule of discrete steps.  Determinism is total per
(seed, config): time only moves when the kernel moves it, every random
draw comes from the run's own seeded RNG (including the queue's
transient-retry jitter, patched in for the run), actor identity
(pid/claim-token) is virtual and kernel-assigned, and filesystem faults
fire from a deterministic budget through the ``durable_io`` fault hook.

Yield-point inventory (what a schedule step can interleave):

``advance``         pure time advance (the scheduler's `dt` riding every
                    step is the fine-grained version)
``client_submit``   one `Router.submit` of the next job
``daemon_claim``    one `JobQueue.claim_pending(limit=1)` on a host
``daemon_finish``   complete the earliest-due running job: cache lookup,
                    stub verdict, cache publish, `JobQueue.finish`
``daemon_hb``       busy-heartbeat: `renew_leases` + heartbeat append
``daemon_janitor``  `JobQueue.requeue_orphans` (startup/periodic janitor)
``router_sweep``    one full `Router.sweep`
``kill``            daemon process death (claims + leases left behind)
``restart``         daemon restart: new generation, pid, token, and the
                    production startup janitor
``partition``       host unreachable: its daemon stops stepping (and
                    renewing) but its pid stays alive — the exact
                    scenario claim leases exist for
``heal``            partition ends; the daemon resumes mid-thought
``skew``            set a host's wall-clock offset (within the
                    configured allowance)
``flaky_fs``        arm the next-K durable fs ops to fail EIO (through
                    `durable_io.set_fault_hook`, exercising every
                    `retry_transient` envelope in virtual time)

After the schedule, the kernel heals all faults and runs a fixed
deterministic drain protocol; the oracles (`oracles.py`) judge every
step and the final state.  The event log is pure data — same seed,
bit-identical log.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import shutil
import tempfile
from dataclasses import asdict, dataclass
from typing import Optional

from ... import durable_io as _dio
from ...utils import clock as _clock
from .. import heartbeat as _hb
from . import oracles as _oracles
from .simclock import SIM_EPOCH, SimClock

#: per-step time advances the generator draws from (seconds, weight) —
#: a mix of "same instant", "momentarily later", and jumps that cross
#: the heartbeat-freshness and lease-TTL horizons
DT_CHOICES = (
    (0.0, 4), (0.05, 4), (0.5, 4), (2.0, 4), (7.0, 3), (15.0, 2),
    (40.0, 1),
)

#: stub-engine execution durations a claim step can draw
DURATION_CHOICES = (0.1, 1.0, 5.0, 20.0)

#: host skew offsets a skew step can draw — all within the default
#: allowance (DEFAULT_CLOCK_SKEW_S = 5.0), including the exact boundary
SKEW_CHOICES = (-5.0, -4.999, -1.0, 0.0, 1.0, 4.999, 5.0)

#: how many consecutive durable fs ops a flaky_fs step poisons
FLAKY_CHOICES = (1, 2, 3, 6)

_ACTION_WEIGHTS = (
    ("advance", 16),
    ("client_submit", 10),
    ("daemon_claim", 12),
    ("daemon_finish", 14),
    ("daemon_hb", 12),
    ("daemon_janitor", 6),
    ("router_sweep", 8),
    ("kill", 2),
    ("restart", 5),
    ("partition", 2),
    ("heal", 5),
    ("skew", 2),
    ("flaky_fs", 2),
)

_MODULES = ("SimRegistry", "SimBroker")

MAX_DRAIN_ROUNDS = 48
_DRAIN_RESTART_ROUND = 16


@dataclass
class SimConfig:
    """Knobs of one simulated fleet.  Sim-scale defaults: a lease TTL of
    minutes would need minutes of virtual time per takeover scenario."""

    hosts: int = 2
    jobs: int = 4
    steps: int = 60
    lease_ttl: float = 30.0
    dead_after_s: float = 20.0
    skew_allowance_s: float = 5.0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SimConfig":
        return cls(**{k: d[k] for k in asdict(cls()) if k in d})


class _Daemon:
    """One daemon incarnation on a host (a restart makes a new one)."""

    def __init__(self, host: int, gen: int, queue):
        self.host = host
        self.gen = gen
        self.pid = 100000 + host * 1000 + gen
        self.token = f"simtok-{host}-{gen:02d}"
        self.queue = queue
        self.alive = True
        self.connected = True
        # job_id -> {"finish_at": true-time, "spec": dict}
        self.running: dict = {}


class _Host:
    def __init__(self, index: int, service_dir: str):
        self.index = index
        self.dir = service_dir
        self.skew = 0.0
        self.gen = 0
        self.daemon: Optional[_Daemon] = None


class SimKernel:
    """One deterministic run.  Generation mode draws steps from a seeded
    RNG and records them; replay mode consumes a given schedule (entries
    that no longer apply no-op, which is what makes ddmin subsets
    runnable).  Either way the drain phase and the oracles are fixed and
    rng-free."""

    def __init__(self, config: SimConfig, root: Optional[str] = None):
        self.cfg = config
        self._own_root = root is None
        self.root = root or tempfile.mkdtemp(prefix="simfleet-")
        self.clock = SimClock(SIM_EPOCH)
        self.events: list = []
        self.violations: list = []
        self.schedule: list = []
        self.flaky_remaining = 0
        self.next_job = 0
        self.submitted: list = []
        # oracle bookkeeping (ground truth, kernel-side)
        self.claims: dict = {}       # job -> lease/owner bookkeeping
        self.running_by: dict = {}   # job -> set of (host, gen)
        self.hosts: list = []
        self.router = None
        self.cache = None
        self._alive_pids: set = set()
        self._rng = None
        self._restores: list = []

    # --- environment install/teardown ----------------------------------

    def _install(self, seed: int) -> None:
        from ...service import queue as qmod
        from ...service import router as rmod
        from ...service import state_cache as scmod

        prev_clock = _clock.install(self.clock)
        self._restores.append(lambda: _clock.install(prev_clock))

        real_getpid = os.getpid
        self._restores.append(lambda: setattr(os, "getpid", real_getpid))

        real_alive_q = qmod._pid_alive
        real_alive_r = rmod._pid_alive
        alive = self._alive_pids

        def sim_pid_alive(pid: int) -> bool:
            return pid in alive

        qmod._pid_alive = sim_pid_alive
        rmod._pid_alive = sim_pid_alive
        self._restores.append(
            lambda: (setattr(qmod, "_pid_alive", real_alive_q),
                     setattr(rmod, "_pid_alive", real_alive_r)))

        real_token = qmod._PROC_TOKEN
        self._restores.append(
            lambda: setattr(qmod, "_PROC_TOKEN", real_token))

        # the queue's transient-retry jitter draws virtual SLEEPS; an
        # unseeded module RNG would make virtual time itself
        # nondeterministic, so the run gets its own
        real_retry_rng = qmod._RETRY_RNG
        qmod._RETRY_RNG = random.Random(seed ^ 0x5EED)
        self._restores.append(
            lambda: setattr(qmod, "_RETRY_RNG", real_retry_rng))

        prev_hook = _dio.set_fault_hook(self._fault_hook)
        self._restores.append(lambda: _dio.set_fault_hook(prev_hook))

        prev_env = os.environ.get("KSPEC_HOST_INSTANCE")

        def restore_env():
            if prev_env is None:
                os.environ.pop("KSPEC_HOST_INSTANCE", None)
            else:
                os.environ["KSPEC_HOST_INSTANCE"] = prev_env

        self._restores.append(restore_env)

        host_dirs = []
        for i in range(self.cfg.hosts):
            d = os.path.join(self.root, f"host{i}")
            h = _Host(i, d)
            self.hosts.append(h)
            host_dirs.append(d)
        self._as_actor(None)  # router identity while constructing
        self.router = rmod.Router(
            os.path.join(self.root, "router"), hosts=host_dirs,
            dead_after_s=self.cfg.dead_after_s,
            skew_s=self.cfg.skew_allowance_s,
        )
        self.cache = scmod.StateSpaceCache(os.path.join(self.root, "sc"))
        for h in self.hosts:
            self._spawn_daemon(h, startup_janitor=False)

    def _teardown(self) -> None:
        while self._restores:
            self._restores.pop()()
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)

    def _fault_hook(self, op: str, path: str) -> None:
        if self.flaky_remaining > 0:
            self.flaky_remaining -= 1
            raise OSError(5, f"simfleet flaky-fs injected EIO ({op})")

    # --- actor identity -------------------------------------------------

    def _as_actor(self, host: Optional[int]) -> None:
        """Point process-visible identity (pid, claim token, wall-clock
        offset, trace clock domain) at the acting component: a host's
        daemon, or the router/client plane (``None``, unskewed)."""
        from ...service import queue as qmod

        if host is None:
            os.getpid = lambda: 99999
            qmod._PROC_TOKEN = "simtok-router"
            self.clock.offset = 0.0
            os.environ["KSPEC_HOST_INSTANCE"] = "router"
        else:
            h = self.hosts[host]
            d = h.daemon
            os.getpid = (lambda pid=d.pid: pid)
            qmod._PROC_TOKEN = d.token
            self.clock.offset = h.skew
            os.environ["KSPEC_HOST_INSTANCE"] = f"host{host}"

    def _spawn_daemon(self, h: _Host, startup_janitor: bool = True):
        from ...service import queue as qmod

        if h.daemon is not None:
            self._alive_pids.discard(h.daemon.pid)
            h.daemon.alive = False
        h.gen += 1
        d = _Daemon(h.index, h.gen,
                    qmod.JobQueue(h.dir, skew_s=self.cfg.skew_allowance_s))
        h.daemon = d
        self._alive_pids.add(d.pid)
        if startup_janitor:
            self._as_actor(h.index)
            moved = self._safe(lambda: d.queue.requeue_orphans(
                lease_ttl=self.cfg.lease_ttl,
                skew_s=self.cfg.skew_allowance_s)) or []
            _oracles.check_takeover(self, sorted(moved),
                                    by=f"startup-janitor:host{h.index}")
            return sorted(moved)
        return []

    @staticmethod
    def _safe(fn):
        """Production callers tolerate transient OSErrors around these
        protocols; the sim does the same so an injected EIO degrades a
        step instead of crashing the kernel."""
        try:
            return fn()
        except OSError:
            return None

    # --- the job stub ----------------------------------------------------

    def _module_for(self, n: int) -> str:
        return _MODULES[n % len(_MODULES)]

    def _stub_verdict(self, module: str) -> dict:
        counts = [1, 3, 5] if module == _MODULES[0] else [2, 4]
        return {
            "model": module, "distinct_states": sum(counts),
            "diameter": 2, "levels": counts, "violation": None,
            "exit_code": 0, "states_per_sec": 1.0, "seconds": 0.1,
        }

    def _cache_key(self, module: str):
        from ...service import state_cache as sc

        return sc.CacheKey(module, False,
                           (("MaxId", 2 + _MODULES.index(module)),),
                           ("TypeOk",), (), False, max_depth=2)

    def _cache_rows(self, module: str):
        import numpy as np

        rng = np.random.RandomState(1 + _MODULES.index(module))
        counts = self._stub_verdict(module)["levels"]
        return [rng.randint(0, 50, size=(n, 2)).astype(np.uint32)
                for n in counts]

    # --- step execution --------------------------------------------------

    def _eligible(self, action: str) -> list:
        """Hosts (or [None] for hostless actions) the action applies to
        right now; empty = inapplicable."""
        alive_conn = [h.index for h in self.hosts
                      if h.daemon.alive and h.daemon.connected]
        if action in ("daemon_claim", "daemon_hb", "daemon_janitor"):
            return alive_conn
        if action == "daemon_finish":
            return [i for i in alive_conn
                    if self.hosts[i].daemon.running]
        if action == "kill":
            return [h.index for h in self.hosts if h.daemon.alive]
        if action == "restart":
            return [h.index for h in self.hosts if not h.daemon.alive]
        if action == "partition":
            return [h.index for h in self.hosts
                    if h.daemon.alive and h.daemon.connected]
        if action == "heal":
            return [h.index for h in self.hosts
                    if h.daemon.alive and not h.daemon.connected]
        if action == "skew":
            return [h.index for h in self.hosts]
        if action == "client_submit":
            return [None] if self.next_job < self.cfg.jobs else []
        if action in ("advance", "router_sweep", "flaky_fs"):
            return [None]
        raise ValueError(f"unknown action {action!r}")

    def _perform(self, action: str, host, extra):
        """Execute one applicable step; returns the event `out` dict."""
        if action == "advance":
            return {}
        if action == "flaky_fs":
            self.flaky_remaining += int(extra or 1)
            return {"armed": self.flaky_remaining}
        if action == "skew":
            self.hosts[host].skew = float(extra or 0.0)
            return {"skew": self.hosts[host].skew}
        if action == "kill":
            h = self.hosts[host]
            d = h.daemon
            d.alive = False
            d.connected = False
            self._alive_pids.discard(d.pid)
            aborted = sorted(d.running)
            for jid in aborted:
                self.running_by.get(jid, set()).discard((host, d.gen))
            d.running.clear()
            return {"aborted": aborted}
        if action == "restart":
            moved = self._spawn_daemon(self.hosts[host])
            return {"gen": self.hosts[host].gen, "janitor_moved": moved}
        if action == "partition":
            self.hosts[host].daemon.connected = False
            return {}
        if action == "heal":
            self.hosts[host].daemon.connected = True
            return {}
        if action == "client_submit":
            return self._step_submit()
        if action == "daemon_claim":
            return self._step_claim(host, float(extra or 1.0))
        if action == "daemon_finish":
            return self._step_finish(host)
        if action == "daemon_hb":
            return self._step_hb(host)
        if action == "daemon_janitor":
            return self._step_janitor(host)
        if action == "router_sweep":
            return self._step_sweep()
        raise ValueError(f"unknown action {action!r}")

    def _step_submit(self) -> dict:
        self._as_actor(None)
        jid = f"job-{self.next_job:04d}"
        module = self._module_for(self.next_job)
        try:
            spec = self.router.submit(
                "sim cfg", module, tenant="sim", kernel_source="hand",
                job_id=jid,
            )
            out = {"job": jid, "host": spec["host"]}
        except OSError as e:
            # the client saw the submit fail; only count the job as in
            # flight if the spec actually landed somewhere
            landed = any(
                os.path.isfile(h.daemon.queue._job_path(st, jid))
                for h in self.hosts for st in ("pending", "claimed"))
            if not landed:
                return {"job": jid, "failed": f"EIO:{e.errno}"}
            out = {"job": jid, "host": None, "partial": True}
        self.submitted.append(jid)
        self.next_job += 1
        return out

    def _step_claim(self, host: int, duration: float) -> dict:
        self._as_actor(host)
        d = self.hosts[host].daemon
        specs = d.queue.claim_pending(limit=1) or []
        out = {"claimed": []}
        for spec in specs:
            jid = spec["job_id"]
            out["claimed"].append(jid)
            existing = self._safe(lambda: d.queue.result(jid))
            if existing is not None:
                # the production daemon's short-circuit: terminal truth
                # already on disk — retire, never re-run
                self._safe(lambda: d.queue.finish(jid, existing))
                out["short_circuit"] = jid
                continue
            _oracles.check_claim(self, jid, host)
            d.running[jid] = {
                "finish_at": self.clock.t + duration,
                "module": spec["module"],
            }
            self.running_by.setdefault(jid, set()).add((host, d.gen))
            self._note_lease(jid, host)
        return out

    def _note_lease(self, jid: str, host: int) -> None:
        d = self.hosts[host].daemon
        if not os.path.isfile(d.queue._job_path("claimed", jid)):
            # the claim was taken over (a legitimacy already judged at
            # the takeover site): the old executor renewing a DANGLING
            # lease does not re-acquire the claim, so it must not
            # refresh the ownership bookkeeping either
            return
        lease = d.queue.read_lease(jid)
        self.claims[jid] = {
            "host": host, "gen": d.gen,
            "renewed_true": self.clock.t,
            "landed": bool(lease and lease.get("token") == d.token),
        }

    def _step_finish(self, host: int) -> dict:
        self._as_actor(host)
        d = self.hosts[host].daemon
        due = sorted(
            (v["finish_at"], j) for j, v in d.running.items()
            if v["finish_at"] <= self.clock.t
        )
        if not due:
            return {"due": 0}
        jid = due[0][1]
        module = d.running[jid]["module"]
        verdict = dict(self._stub_verdict(module))
        key = self._cache_key(module)
        hit = _oracles.check_cache_lookup(self, jid, module, key)
        if hit is None:
            try:
                self.cache.publish(
                    key, self._stub_verdict(module), exact64=True,
                    lanes=2, level_rows=self._cache_rows(module),
                    diameter=2)
                published = True
            except OSError:
                published = False
        else:
            published = False
        verdict["job_id"] = jid
        try:
            d.queue.finish(jid, verdict)
        except OSError as e:
            # verdict publish failed (flaky fs): the job stays running
            # and a later finish step retries — production's supervisor
            # retry, compressed
            return {"job": jid, "finish_failed": f"EIO:{e.errno}"}
        del d.running[jid]
        self.running_by.get(jid, set()).discard((host, d.gen))
        return {"job": jid, "cache": "hit" if hit else "miss",
                "published": published}

    def _step_hb(self, host: int) -> dict:
        self._as_actor(host)
        d = self.hosts[host].daemon
        jobs = sorted(d.running)
        self._safe(lambda: d.queue.renew_leases(jobs))
        for jid in jobs:
            self._note_lease(jid, host)
        try:
            _hb.append_jsonl(
                os.path.join(self.hosts[host].dir, "heartbeat-sim.jsonl"),
                _hb.heartbeat_record("daemon", pid=d.pid, state="busy"
                                     if jobs else "idle"),
            )
            landed = True
        except OSError:
            landed = False
        return {"renewed": jobs, "hb": landed}

    def _step_janitor(self, host: int) -> dict:
        self._as_actor(host)
        d = self.hosts[host].daemon
        moved = self._safe(lambda: d.queue.requeue_orphans(
            lease_ttl=self.cfg.lease_ttl,
            skew_s=self.cfg.skew_allowance_s)) or []
        moved = sorted(moved)
        _oracles.check_takeover(self, moved, by=f"janitor:host{host}")
        return {"moved": moved}

    def _step_sweep(self) -> dict:
        self._as_actor(None)
        out = self._safe(self.router.sweep)
        if out is None:
            return {"failed": "EIO"}
        for hid, moved in sorted(out.get("takeover", {}).items()):
            _oracles.check_takeover(self, sorted(moved),
                                    by=f"sweep:host{hid}")
        return {
            "states": [h["state"] for h in out["hosts"]],
            "takeover": {str(k): sorted(v)
                         for k, v in sorted(out["takeover"].items())},
            "rerouted": {str(k): sorted(v)
                         for k, v in sorted(out["rerouted"].items())},
        }

    # --- run loop ---------------------------------------------------------

    def _record_event(self, i, action, host, extra, dt, out) -> None:
        self.events.append({
            "i": i, "t": round(self.clock.t, 3), "a": action,
            "h": host, "x": extra, "dt": dt, "out": out,
        })

    def _run_step(self, i: int, entry: dict) -> None:
        action, host = entry["a"], entry.get("h")
        extra, dt = entry.get("x"), float(entry.get("dt", 0.0))
        self.clock.offset = 0.0
        self.clock.advance(dt)
        eligible = self._eligible(action)
        if not eligible or (host is not None and host not in eligible):
            out = {"skipped": True}
        else:
            out = self._perform(action, host, extra)
        self.clock.offset = 0.0
        self._record_event(i, action, host, extra, dt, out)
        _oracles.check_copies(self, step=i)

    def _gen_entry(self, rng: random.Random) -> dict:
        dts, dtw = zip(*DT_CHOICES)
        dt = rng.choices(dts, weights=dtw)[0]
        acts, actw = zip(*_ACTION_WEIGHTS)
        while True:
            action = rng.choices(acts, weights=actw)[0]
            eligible = self._eligible(action)
            if eligible:
                break
        host = rng.choice(eligible)
        extra = None
        if action == "daemon_claim":
            extra = rng.choice(DURATION_CHOICES)
        elif action == "skew":
            extra = rng.choice(SKEW_CHOICES)
        elif action == "flaky_fs":
            extra = rng.choice(FLAKY_CHOICES)
        return {"a": action, "h": host, "x": extra, "dt": dt}

    def _drain(self) -> bool:
        """Deterministic rng-free cool-down: heal every fault, then run
        fixed rounds of the full control loop until every submitted job
        has a routed verdict.  A mid-drain rolling restart (idle daemons
        only) releases any protocol-private files a live pid still pins
        — production's rolling-restart recovery, compressed."""
        self.flaky_remaining = 0
        for h in self.hosts:
            if h.daemon.alive:
                h.daemon.connected = True
        for r in range(MAX_DRAIN_ROUNDS):
            if self._drained():
                return True
            if r == _DRAIN_RESTART_ROUND:
                for h in self.hosts:
                    if h.daemon.alive and not h.daemon.running:
                        self._spawn_daemon(h)
            for h in self.hosts:
                if not h.daemon.alive:
                    self._spawn_daemon(h)
                self._step_hb(h.index)
                while self._step_finish(h.index).get("due", 1) != 0:
                    pass
                self._step_claim(h.index, 1.0)
            for h in self.hosts:
                self._step_janitor(h.index)
            self._step_sweep()
            self.clock.offset = 0.0
            self.clock.advance(5.0)
            _oracles.check_copies(self, step=-1 - r)
        return self._drained()

    def _drained(self) -> bool:
        self._as_actor(None)
        for jid in self.submitted:
            if self._safe(lambda: self.router.result(jid)) is None:
                return False
        return True

    def run(self, seed: Optional[int] = None,
            schedule: Optional[list] = None) -> dict:
        """Generation mode (``seed`` alone) or replay mode (``schedule``
        given; ``seed`` then only feeds the retry-jitter RNG, so a repro
        carries its original seed alongside its schedule).  Returns the
        run record; the kernel's ``root`` (host/router/trace dirs)
        survives until the caller tears it down via :meth:`cleanup`."""
        if seed is None and schedule is None:
            raise ValueError("need a seed or a schedule")
        eff_seed = seed if seed is not None else 0
        self._install(eff_seed)
        try:
            if schedule is None:
                rng = random.Random(seed)
                for i in range(self.cfg.steps):
                    entry = self._gen_entry(rng)
                    self.schedule.append(entry)
                    self._run_step(i, entry)
            else:
                self.schedule = [dict(e) for e in schedule]
                for i, entry in enumerate(self.schedule):
                    self._run_step(i, entry)
            drained = self._drain()
            _oracles.check_final(self, drained)
            verdicts = self._final_verdicts()
        finally:
            os.environ.pop("KSPEC_HOST_INSTANCE", None)
            self._teardown_patches()
        record = {
            "schema": "kspec-simfleet-run/1",
            "seed": seed,
            "config": self.cfg.to_dict(),
            "schedule": self.schedule,
            "events": self.events,
            "verdicts": verdicts,
            "violations": self.violations,
            "drained": drained,
        }
        record["digest"] = run_digest(record)
        return record

    def _final_verdicts(self) -> dict:
        self._as_actor(None)
        out = {}
        for jid in sorted(self.submitted):
            v = self._safe(lambda: self.router.result(jid))
            out[jid] = (None if v is None else
                        {"exit_code": v.get("exit_code"),
                         "distinct_states": v.get("distinct_states"),
                         "model": v.get("model")})
        return out

    def _teardown_patches(self) -> None:
        # split from root cleanup so replay callers can keep the root
        # (for --trace) while identity/clock patches are long restored
        while self._restores:
            self._restores.pop()()

    def cleanup(self) -> None:
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)

    def trace_roots(self) -> list:
        return [h.dir for h in self.hosts] + [self.router.dir]


def run_digest(record: dict) -> str:
    """The determinism surface: events + verdicts + violations + drain,
    canonically serialized.  Same seed ⇒ same digest, bit for bit."""
    surface = {
        "events": record["events"],
        "verdicts": record["verdicts"],
        "violations": record["violations"],
        "drained": record["drained"],
    }
    blob = json.dumps(surface, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def run_seed(seed: int, config: Optional[SimConfig] = None,
             root: Optional[str] = None, keep: bool = False) -> dict:
    """One generation-mode run; cleans its workdir unless ``keep``."""
    k = SimKernel(config or SimConfig(), root=root)
    try:
        return k.run(seed=seed)
    finally:
        if not keep:
            k.cleanup()


def run_schedule(schedule: list, config: Optional[SimConfig] = None,
                 seed: int = 0, root: Optional[str] = None,
                 keep: bool = False):
    """One replay-mode run; returns (record, kernel) — the kernel keeps
    its root alive when ``keep`` so callers can assemble fleet traces."""
    k = SimKernel(config or SimConfig(), root=root)
    try:
        rec = k.run(seed=seed, schedule=schedule)
        return rec, k
    finally:
        if not keep:
            k.cleanup()
