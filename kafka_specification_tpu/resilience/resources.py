"""Resource-exhaustion governance: budgets, reclamation, typed clean exits.

A multi-day out-of-core run on one box dies to a full spill disk, a
breached memory budget, or a silent stall long before it dies to a crash
(GPUexplore's scalability study names exactly this as the practical wall
for explicit-state checking at scale — PAPERS.md, arXiv:1801.05857).  PR 1
and PR 4 made crashes restartable; this module makes *running out of
things* a governed, checkpointed degradation instead of a torn exception:

- :class:`ResourceGovernor` — threaded through both engines.  It tracks
  spill-dir + checkpoint-dir disk usage against ``--disk-budget``, process
  RSS against an (opt-in) RSS budget, and a per-level deadline watchdog.
  On a **soft breach** (usage past ``soft_frac`` of a budget) it emits a
  ``resource-pressure`` event and runs the engine's reclamation callback
  (tmp janitor → eager spill-run merges → fresh checkpoint → prune
  generations → flush the deletion barrier).  On a **hard breach** it
  performs checkpoint-then-clean-exit: best-effort final checkpoint, then
  a typed :class:`ResourceExhausted` that the engines convert into a
  ``resource-exhausted`` terminal status and the CLI into exit code
  :data:`EXIT_RESOURCE_EXHAUSTED` — resumable after the operator frees
  space, never a torn crash.
- :func:`reclaim_disk` — the supervisor's ``--reclaim`` policy: an
  operator-grade filename-level sweep (stale ``.tmp`` files, rotated
  checkpoint generations past the newest) that frees space WITHOUT
  importing storage/numpy, so jax-free supervisor parents can run it
  before their single permitted reclaim-retry.

Budgets parse like ``--mem-budget`` (``512M``/``4G``); environment knobs:
``KSPEC_DISK_BUDGET``, ``KSPEC_RSS_BUDGET``, ``KSPEC_LEVEL_DEADLINE``
(seconds), ``KSPEC_RESOURCE_SOFT`` (soft fraction, default 0.85).

The RSS watchdog is gauge-only unless an RSS budget is explicitly
configured: ``--mem-budget`` bounds the *host fingerprint set*, not the
whole process (jax runtime + compiled programs + frontier buffers ride on
top), so breaching on it directly would kill every legitimately-sized
run.  ``kspec_rss_bytes`` is always exported for the pressure timeline.

Must stay jax-free AND storage-free at import: the supervisor imports
this from a parent that must survive a wedged accelerator tunnel, and
importing the storage package would pull the native C++ FpSet bindings.
"""

from __future__ import annotations

import errno
import os
import re
import time
from typing import Optional

# sysexits EX_TEMPFAIL: "temporary failure, retry later" — exactly the
# contract (free space / raise the budget, then resume from checkpoint).
# Distinct from crash codes so supervisors never hot-loop restarts into
# the same full disk.
EXIT_RESOURCE_EXHAUSTED = 75

_DISK_FULL_ERRNOS = (errno.ENOSPC, errno.EDQUOT)


class ResourceExhausted(RuntimeError):
    """Typed terminal: the run stopped because it ran OUT of something
    (disk budget, RSS budget, level deadline, ENOSPC from a writer) — not
    because it crashed.  The engines convert it into a
    checkpoint-then-clean-exit whose on-disk state still passes
    ``cli verify-checkpoint``; the CLI maps it to
    :data:`EXIT_RESOURCE_EXHAUSTED`; the supervisor classifies it
    separately from crashes (halt with a verdict, or exactly one
    reclaim-retry under ``--reclaim``)."""

    def __init__(self, reason: str, detail: str = "", depth=None,
                 at_boundary: bool = False):
        self.reason = reason  # disk | rss | deadline | stall | enospc
        self.detail = detail
        self.depth = depth
        # True iff raised at a level boundary (consistent, checkpointable
        # state); mid-level exhaustion resumes from the last checkpoint
        self.at_boundary = at_boundary
        super().__init__(
            f"RESOURCE_EXHAUSTED[{reason}]"
            + (f" at level {depth}" if depth is not None else "")
            + (f": {detail}" if detail else "")
        )


def is_disk_full(exc: BaseException) -> bool:
    """True for the OS-level out-of-space family (real or injected)."""
    if isinstance(exc, OSError) and exc.errno in _DISK_FULL_ERRNOS:
        return True
    return "No space left on device" in str(exc)


def parse_bytes(text) -> int:
    """'512M' / '4G' / '65536' -> bytes (mirrors storage.parse_mem_budget,
    duplicated here so jax-free parents never import the storage package)."""
    if isinstance(text, (int, float)):
        return int(text)
    s = str(text).strip()
    mult = 1
    suffixes = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30, "T": 1 << 40}
    if s and s[-1].upper() in suffixes:
        mult = suffixes[s[-1].upper()]
        s = s[:-1]
    try:
        v = float(s)
    except ValueError:
        raise ValueError(f"bad byte budget {text!r} (use e.g. 512M, 4G)")
    if v <= 0:
        raise ValueError(f"byte budget must be positive, got {text!r}")
    return int(v * mult)


def dir_usage_bytes(paths) -> int:
    """Total file bytes under `paths` (nested watch dirs counted once)."""
    roots = sorted({os.path.normpath(p) for p in paths if p})
    total = 0
    for i, r in enumerate(roots):
        if any(
            r != k and r.startswith(k + os.sep) for k in roots[:i]
        ):
            continue  # nested under an earlier root: already counted
        for dirpath, _dirs, files in os.walk(r):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    pass  # unlinked mid-walk (deletion barrier flushing)
    return total


def rss_bytes() -> Optional[int]:
    """Current process resident set size, or None when unknowable."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:  # portable fallback: peak (not current) residency.  ru_maxrss
        # is KiB on Linux but BYTES on macOS (the platform that actually
        # takes this fallback — Linux has /proc)
        import resource
        import sys as _sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak if _sys.platform == "darwin" else peak * 1024
    except Exception:
        return None


class ResourceGovernor:
    """Budget watchdog threaded through both engines' level loops.

    Call protocol (single-device and sharded engines alike):

    - ``level_begin(depth)`` — arm the per-level deadline,
    - ``poll(depth)`` — at chunk boundaries: cheap deadline check only,
    - ``level_end(depth, reclaim=..., save_hook=...)`` — at the level
      boundary (after the periodic checkpoint): export pressure gauges,
      fire the injected ``stall@level:N`` fault, run soft-breach
      reclamation, and on hard breach call ``save_hook`` (best-effort
      final checkpoint) then raise :class:`ResourceExhausted`.
    """

    def __init__(
        self,
        disk_budget=None,
        rss_budget=None,
        level_deadline=None,
        soft_frac: float = 0.85,
        watch_dirs=(),
        fault_plan=None,
    ):
        self.disk_budget = (
            None if disk_budget in (None, "") else parse_bytes(disk_budget)
        )
        self.rss_budget = (
            None if rss_budget in (None, "") else parse_bytes(rss_budget)
        )
        # NB: 0 is a real deadline ("every level is instantly late" — the
        # deterministic watchdog test), not "off"
        self.level_deadline = (
            None if level_deadline in (None, "") else float(level_deadline)
        )
        self.soft_frac = min(1.0, max(0.0, float(soft_frac)))
        self.watch_dirs = [p for p in watch_dirs if p]
        self.fault_plan = fault_plan
        self._level_t0 = None
        self._level_depth = None
        self.reclaims = 0
        self.pressure_events = 0

    @classmethod
    def from_env(cls, disk_budget=None, watch_dirs=(), fault_plan=None):
        env = os.environ
        if disk_budget is None and env.get("KSPEC_DISK_BUDGET"):
            disk_budget = env["KSPEC_DISK_BUDGET"]
        return cls(
            disk_budget=disk_budget,
            rss_budget=env.get("KSPEC_RSS_BUDGET") or None,
            level_deadline=env.get("KSPEC_LEVEL_DEADLINE", ""),
            soft_frac=float(env.get("KSPEC_RESOURCE_SOFT") or "0.85"),
            watch_dirs=watch_dirs,
            fault_plan=fault_plan,
        )

    # --- level protocol --------------------------------------------------
    def level_begin(self, depth: int) -> None:
        self._level_t0 = time.monotonic()
        self._level_depth = int(depth)

    def poll(self, depth: int) -> None:
        """Chunk-boundary check: the per-level deadline watchdog.  A level
        that outlives its deadline is a silent stall (wedged tunnel, IO
        collapse) — exhausted TIME is governed like exhausted space, but
        mid-level there is no consistent state to checkpoint, so the exit
        resumes from the last durable generation."""
        if self.level_deadline is None or self._level_t0 is None:
            return
        dt = time.monotonic() - self._level_t0
        if dt > self.level_deadline:
            self._event(
                "resource-exhausted", resource="deadline", depth=depth,
                level=self._level_depth, seconds=round(dt, 1),
            )
            raise ResourceExhausted(
                "deadline",
                f"level {self._level_depth} running {dt:.1f}s "
                f"> {self.level_deadline:.1f}s deadline",
                depth=depth,
            )

    def level_end(self, depth: int, reclaim=None, save_hook=None) -> None:
        from ..obs import metrics as _met  # lazy: cycle hygiene

        if self.fault_plan is not None and self.fault_plan.stalled(depth):
            self._hard(
                "stall",
                f"injected level stall at depth {depth} (KSPEC_FAULT)",
                depth,
                save_hook,
            )
        rss = rss_bytes()
        if rss is not None:
            _met.set_gauge("kspec_rss_bytes", rss)
        if self.rss_budget:
            _met.set_gauge("kspec_rss_budget_bytes", self.rss_budget)
            if rss is not None and rss > self.rss_budget:
                # reclamation cannot shrink a live process's heap — go
                # straight to the typed exit (the resumed run re-plans)
                self._hard(
                    "rss",
                    f"RSS {rss} bytes > budget {self.rss_budget}",
                    depth,
                    save_hook,
                )
            elif rss is not None and rss > self.soft_frac * self.rss_budget:
                self._pressure("rss", rss, self.rss_budget, depth)
        if not self.disk_budget:
            return
        used = dir_usage_bytes(self.watch_dirs)
        _met.set_gauge("kspec_disk_used_bytes", used)
        _met.set_gauge("kspec_disk_budget_bytes", self.disk_budget)
        if used > self.soft_frac * self.disk_budget:
            self._pressure("disk", used, self.disk_budget, depth)
            if reclaim is not None:
                before = used
                reclaim()
                self.reclaims += 1
                used = dir_usage_bytes(self.watch_dirs)
                _met.set_gauge("kspec_disk_used_bytes", used)
                _met.inc("kspec_reclaims_total")
                self._event(
                    "reclaim",
                    depth=depth,
                    freed_bytes=max(0, before - used),
                    used_bytes=used,
                )
        if used > self.disk_budget:
            self._hard(
                "disk",
                f"{used} bytes under watch > --disk-budget "
                f"{self.disk_budget}",
                depth,
                save_hook,
            )

    # --- internals -------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        from ..obs import tracer as _obs

        _obs.event(kind, **fields)

    def _pressure(self, resource: str, used: int, budget: int, depth) -> None:
        self.pressure_events += 1
        self._event(
            "resource-pressure",
            resource=resource,
            used=int(used),
            budget=int(budget),
            depth=depth,
        )

    def _hard(self, reason: str, detail: str, depth, save_hook) -> None:
        self._event(
            "resource-exhausted", resource=reason, depth=depth,
            detail=detail[:200],
        )
        if save_hook is not None:
            try:
                save_hook()  # checkpoint-then-clean-exit
            except OSError as e:
                # a genuinely full disk may refuse the final save; the
                # previously promoted generations still verify, so the
                # exit stays clean and resumable — just older
                import sys

                print(
                    f"[resources] final checkpoint save failed ({e}); "
                    f"resuming will use the previous generation",
                    file=sys.stderr,
                )
        raise ResourceExhausted(reason, detail, depth=depth, at_boundary=True)

    def stats(self) -> dict:
        return {
            "disk_budget": self.disk_budget,
            "rss_budget": self.rss_budget,
            "level_deadline": self.level_deadline,
            "reclaims": self.reclaims,
            "pressure_events": self.pressure_events,
        }


# --- per-tenant budgets (the serving daemon's governance unit) -------------
#
# `cli serve` multiplexes many tenants' checks onto one process; each
# tenant's jobs run under that tenant's OWN ResourceGovernor instance so a
# budget breach exits *that job* typed (the same RESOURCE_EXHAUSTED / rc-75
# contract as a solo run) without touching the daemon or sibling jobs.
# Budgets load from the service directory's `tenants.json`:
#
#     {"acme": {"disk_budget": "64M", "rss_budget": null,
#               "level_deadline": 30, "max_pending": 100},
#      "*":    {"disk_budget": "256M"}}
#
# "*" is the default applied to tenants with no explicit entry.  RSS is
# process-wide in an in-process daemon, so an RSS budget here is a coarse
# backstop (the whole daemon's residency is charged to the breaching
# tenant's job), documented in docs/service.md.


class TenantBudget:
    """Parsed per-tenant resource policy (all fields optional)."""

    def __init__(self, disk_budget=None, rss_budget=None,
                 level_deadline=None, max_pending=None, soft_frac=None):
        self.disk_budget = (
            None if disk_budget in (None, "") else parse_bytes(disk_budget)
        )
        self.rss_budget = (
            None if rss_budget in (None, "") else parse_bytes(rss_budget)
        )
        self.level_deadline = (
            None if level_deadline in (None, "") else float(level_deadline)
        )
        self.max_pending = (
            None if max_pending in (None, "") else int(max_pending)
        )
        self.soft_frac = (
            None if soft_frac in (None, "") else float(soft_frac)
        )

    @classmethod
    def from_dict(cls, d: dict) -> "TenantBudget":
        unknown = set(d) - {
            "disk_budget", "rss_budget", "level_deadline", "max_pending",
            "soft_frac",
        }
        if unknown:
            raise ValueError(f"unknown tenant-budget keys: {sorted(unknown)}")
        return cls(**d)

    def governor(self, watch_dirs=(), fault_plan=None) -> ResourceGovernor:
        """A fresh per-job governor under this tenant's budgets (fresh so
        one job's deadline timer / pressure counters never leak into the
        tenant's next job)."""
        return ResourceGovernor(
            disk_budget=self.disk_budget,
            rss_budget=self.rss_budget,
            level_deadline=self.level_deadline,
            soft_frac=0.85 if self.soft_frac is None else self.soft_frac,
            watch_dirs=watch_dirs,
            fault_plan=fault_plan,
        )


def load_tenant_budgets(path: str) -> dict:
    """Parse a tenants.json -> {tenant: TenantBudget}.  A missing file
    means no budgets (every tenant unrestricted); a malformed one is an
    error — silently ignoring a governance config would un-enforce it."""
    import json

    if not os.path.isfile(path):
        return {}
    with open(path) as fh:
        raw = json.load(fh)
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: expected an object of tenant -> budgets")
    return {t: TenantBudget.from_dict(d or {}) for t, d in raw.items()}


def budget_for_tenant(budgets: dict, tenant: str) -> Optional[TenantBudget]:
    """Tenant's explicit budget, else the '*' default, else None."""
    return budgets.get(tenant) or budgets.get("*")


# --- supervisor-side reclamation (`--reclaim`) -----------------------------

# rotated checkpoint generations: <stem>.<gen>.npz[.<part>] with gen >= 1
_GEN_RE = re.compile(r"^.+\.(\d+)\.npz(\..+)?$")


def _is_tmp_name(name: str) -> bool:
    return name.endswith(".tmp") or ".tmp." in name


def reclaim_disk(dirs, keep_gens: int = 1) -> list:
    """Operator-grade reclamation for the supervisor's ``--reclaim``
    policy: sweep stale ``.tmp`` files and prune rotated checkpoint
    generations past `keep_gens` (filename-level — never touches the
    newest generation or the disk tier's referenced run files, so the
    surviving chain still passes ``cli verify-checkpoint``).  Returns the
    removed paths.  Pure-stdlib on purpose: runs in jax-free supervisor
    parents before their single reclaim-retry."""
    removed = []
    for d in dirs:
        if not d or not os.path.isdir(d):
            continue
        for dirpath, _dirs, files in os.walk(d):
            for name in files:
                m = _GEN_RE.match(name)
                old_gen = m is not None and int(m.group(1)) >= keep_gens
                if not (_is_tmp_name(name) or old_gen):
                    continue
                p = os.path.join(dirpath, name)
                try:
                    os.unlink(p)
                    removed.append(p)
                except OSError:
                    pass
    return removed
