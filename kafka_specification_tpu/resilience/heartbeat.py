"""Shared JSONL heartbeat envelope.

Every record written by the observability/liveness streams — the engines'
per-level stats lines, the TPU-window sentry's per-attempt lines, and the
supervisor's own event log — carries the same envelope so one consumer
(the supervisor's stall detector, or a human with `tail -f | jq`) can read
any of them:

    {"kind": "<stream>", "ts": "<UTC ISO-8601>", "unix": <float seconds>, ...}

`kind` values in use: "level" (engine per-level stats), "sentry" (TPU
sentry attempts), "supervisor" (resilient_run events).  Stream-specific
fields ride alongside.

Must stay jax-free: imported by parents that never touch the accelerator.
"""

from __future__ import annotations

import json
import time

from .. import durable_io as _dio
from ..utils import clock as _clk


def heartbeat_record(kind: str, t: float = None, **fields) -> dict:
    """Envelope a record; `t` overrides the stamped time (e.g. a consumer
    that needs event-START semantics stamps the start, not now).  The
    default stamp comes from the injected clock (utils/clock.py), so a
    simulated daemon's liveness trail carries virtual time."""
    if t is None:
        t = _clk.now()
    return {
        "kind": kind,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t)),
        "unix": round(t, 3),
        **fields,
    }


def append_jsonl(path: str, record: dict) -> None:
    # routed through the durable-io leaf so the crashcheck harness sees
    # heartbeat emits in its op-traces (same buffered-append semantics)
    _dio.append_text(path, json.dumps(record) + "\n")
