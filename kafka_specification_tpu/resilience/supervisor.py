"""Supervised auto-resume runner (the library behind scripts/resilient_run.py).

Replaces the round-5 bash supervisor (`scripts/supervise_prod464.sh`) with
a watchdog that actually observes progress instead of only exit codes:

- spawns the run as a child process (stdout/stderr to a per-attempt log
  when `log_dir` is set),
- watches the heartbeat JSONL file the child appends to (the engines'
  `stats_path` per-level stream) — any growth counts as progress,
- kills the child (SIGTERM, then SIGKILL) when the heartbeat stalls past
  `stall_timeout` seconds — the wedged-tunnel mode that has eaten whole
  rounds hangs without exiting, which a bash `for` loop never notices,
- restarts from the engine checkpoint with a bounded restart budget and
  jittered exponential backoff (thundering-herd hygiene even for one box),
- classifies a RESOURCE_EXHAUSTED child exit (code 75: full disk /
  breached budget, checkpointed clean — resilience.resources) separately
  from crashes: restarting into the same full disk would hot-loop, so it
  halts with an actionable verdict, or under `reclaim=True` prunes the
  reclaim dirs and retries exactly once,
- appends one heartbeat-enveloped JSONL event per transition (start /
  stall-kill / exit / resource-exhausted / reclaim / resource-verdict /
  complete / give-up) to the event log.

The child is responsible for its own resume: engines resume automatically
from `checkpoint_dir` (hardened, checksummed, keep-last-K — see
`resilience.checkpoints`), so a restart is exactly "run the same command
again".

Must stay jax-free (the parent never touches a possibly-wedged tunnel).
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Optional

from .heartbeat import append_jsonl, heartbeat_record
from ..utils import clock as _clk
from .integrity import EXIT_INTEGRITY
from .resources import EXIT_RESOURCE_EXHAUSTED, reclaim_disk


@dataclass
class SupervisorConfig:
    cmd: list
    heartbeat: Optional[str] = None  # JSONL the child appends to
    events: str = "RESILIENT_EVENTS.jsonl"
    log_dir: Optional[str] = None  # per-attempt child logs
    stall_timeout: float = 1800.0  # no heartbeat growth for this long -> kill
    max_restarts: int = 8  # restarts, not attempts (attempts = 1 + this)
    backoff_base: float = 5.0
    backoff_cap: float = 300.0
    jitter: float = 0.25
    poll: float = 0.5
    term_grace: float = 10.0  # SIGTERM -> SIGKILL grace
    env: Optional[dict] = None
    run_id: Optional[str] = None  # obs correlation key (stamped per event)
    # resource-exit policy (resilience.resources): a child exiting
    # EXIT_RESOURCE_EXHAUSTED ran out of disk/RSS/time and checkpointed —
    # restarting it into the same full disk would hot-loop, so the
    # supervisor either halts with an actionable verdict (default) or,
    # with reclaim=True, prunes reclaim_dirs (stale tmps + rotated
    # checkpoint generations) and retries EXACTLY once
    reclaim: bool = False
    reclaim_dirs: tuple = ()
    rng: random.Random = field(default_factory=random.Random, repr=False)

    def backoff(self, restart: int) -> float:
        d = min(self.backoff_base * 2.0 ** (restart - 1), self.backoff_cap)
        return d * (1.0 + self.jitter * self.rng.random())

    def event(self, **fields) -> None:
        """One supervisor event: heartbeat-enveloped, run_id-stamped when
        the run has one (obs run directories), appended to the log."""
        extra = {"run_id": self.run_id} if self.run_id else {}
        append_jsonl(
            self.events, heartbeat_record("supervisor", **extra, **fields)
        )


STALL_RC = -97  # synthetic rc recorded for a stall-killed attempt


def classify_exit(rc: Optional[int]) -> str:
    """THE supervisor taxonomy for a supervised child's exit, shared by
    the single-child supervisor, the sharded fleet and the serving-daemon
    fleet (service/fleet.py) so the policy table cannot drift:

      'ok'        rc 0 — clean exit
      'resource'  rc 75 — typed RESOURCE_EXHAUSTED; restarting into the
                  same full disk would hot-loop: halt with a verdict (at
                  most one reclaim-retry)
      'integrity' rc 76 — typed INTEGRITY_VIOLATION; restartable (the
                  resume path skips chain-failed state), budget-bounded
      'stall'     the synthetic STALL_RC a watchdog stamped on a wedged
                  child it killed; restartable, budget-bounded
      'crash'     anything else — restartable, budget-bounded
    """
    if rc == 0:
        return "ok"
    if rc == EXIT_RESOURCE_EXHAUSTED:
        return "resource"
    if rc == EXIT_INTEGRITY:
        return "integrity"
    if rc == STALL_RC:
        return "stall"
    return "crash"


def _hb_size(path: Optional[str]) -> int:
    if not path:
        return 0
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


def _run_attempt(cfg: SupervisorConfig, attempt: int) -> int:
    """One child run: returns its exit code, or STALL_RC if stall-killed."""
    log_fh = None
    if cfg.log_dir is not None:
        os.makedirs(cfg.log_dir, exist_ok=True)
        log_fh = open(
            os.path.join(cfg.log_dir, f"attempt-{attempt:02d}.log"), "wb"
        )
    try:
        # own session/process group: a stall-kill must take down the whole
        # tree (the command may be a shell wrapper whose wedged grandchild
        # would otherwise survive, keep the accelerator, and race the
        # restarted attempt on the checkpoint directory)
        child = subprocess.Popen(
            cfg.cmd,
            stdout=log_fh or None,
            stderr=subprocess.STDOUT if log_fh else None,
            env=cfg.env,
            start_new_session=True,
        )

        def signal_tree(sig):
            try:
                os.killpg(child.pid, sig)  # pgid == pid (new session)
            except (OSError, ProcessLookupError):
                try:
                    child.send_signal(sig)
                except (OSError, ProcessLookupError):
                    pass

        last_progress = _clk.monotonic()
        hb_size = _hb_size(cfg.heartbeat)
        while True:
            rc = child.poll()
            if rc is not None:
                return rc
            if cfg.heartbeat is None:
                # no heartbeat stream configured: the stall detector is
                # off (a constant size would read as an eternal stall and
                # kill every healthy child) — only child exits matter
                _clk.sleep(cfg.poll)
                continue
            size = _hb_size(cfg.heartbeat)
            if size != hb_size:
                hb_size = size
                last_progress = _clk.monotonic()
            if _clk.monotonic() - last_progress > cfg.stall_timeout:
                cfg.event(
                    event="stall-kill",
                    attempt=attempt,
                    stall_timeout=cfg.stall_timeout,
                    heartbeat=cfg.heartbeat,
                )
                signal_tree(signal.SIGTERM)
                try:
                    child.wait(timeout=cfg.term_grace)
                except subprocess.TimeoutExpired:
                    signal_tree(signal.SIGKILL)
                    child.wait()
                return STALL_RC
            _clk.sleep(cfg.poll)
    finally:
        if log_fh is not None:
            log_fh.close()


def _resource_verdict(cfg, attempt: int, rc: int, reclaimed: bool) -> int:
    """Halt on a RESOURCE_EXHAUSTED child exit: restarting into the same
    full disk would hot-loop (each attempt re-fills what little space the
    backoff freed and dies at the same level).  The verdict event + stderr
    line tell the operator exactly what to do; the supervisor's own exit
    code stays EXIT_RESOURCE_EXHAUSTED so callers can classify too."""
    cfg.event(
        event="resource-verdict",
        attempt=attempt,
        rc=rc,
        reclaim_tried=reclaimed,
    )
    print(
        f"[supervisor] child exited RESOURCE_EXHAUSTED (rc={rc})"
        + (" after one reclaim-retry" if reclaimed else "")
        + "; NOT restarting into an unreclaimed full disk.  Free space "
        "(or raise --disk-budget), check `cli verify-checkpoint`, then "
        "re-run to resume"
        + ("" if reclaimed or cfg.reclaim else
           "; or re-run the supervisor with --reclaim for one automatic "
           "prune-and-retry")
        + f".  Events: {cfg.events}",
        file=sys.stderr,
    )
    return EXIT_RESOURCE_EXHAUSTED


def _try_reclaim(cfg, attempt: int) -> None:
    removed = reclaim_disk(cfg.reclaim_dirs)
    cfg.event(
        event="reclaim",
        attempt=attempt,
        files_removed=len(removed),
        dirs=list(cfg.reclaim_dirs),
    )


def supervise(cfg: SupervisorConfig) -> int:
    """Run cfg.cmd to success or budget exhaustion; returns the final rc."""
    rc = None
    reclaimed = False
    attempt = 0
    restarts_used = 0
    # while-loop with explicit restart accounting (not a for-range): the
    # one --reclaim retry must happen even when the resource exit lands
    # on the final budgeted attempt — it is a different recovery lever
    # than a crash restart and must never be silently dropped (nor ever
    # consume the crash-restart budget)
    while True:
        attempt += 1
        cfg.event(event="start", attempt=attempt, cmd=cfg.cmd)
        t0 = _clk.now()
        rc = _run_attempt(cfg, attempt)
        cfg.event(
            event="exit",
            attempt=attempt,
            rc=rc,
            seconds=round(_clk.now() - t0, 1),
        )
        if rc == 0:
            cfg.event(event="complete", attempt=attempt)
            return 0
        if rc == EXIT_RESOURCE_EXHAUSTED:
            # resource exits are NOT crashes: never burn the restart
            # budget hot-looping into the same full disk — at most one
            # reclaim-retry (--reclaim), else halt with the verdict
            cfg.event(event="resource-exhausted", attempt=attempt, rc=rc)
            if cfg.reclaim and not reclaimed:
                reclaimed = True
                _try_reclaim(cfg, attempt)
                continue
            return _resource_verdict(cfg, attempt, rc, reclaimed)
        if rc == EXIT_INTEGRITY:
            # integrity violations (exit 76, resilience.integrity) ARE
            # restartable — the child's resume path skips corrupted
            # generations via the digest-chain validators, so the restart
            # resumes from the newest CHAIN-VERIFIED checkpoint
            # generation.  Restarts stay bounded by the normal budget:
            # persistent violations (failing DIMM, rotting disk) must
            # converge to a give-up, never a corruption-retry hot-loop
            cfg.event(event="integrity-violation", attempt=attempt, rc=rc)
        if restarts_used >= cfg.max_restarts:
            break
        restarts_used += 1
        delay = cfg.backoff(restarts_used)
        cfg.event(
            event="restart", attempt=attempt, backoff_s=round(delay, 2)
        )
        _clk.sleep(delay)
    cfg.event(event="give-up", attempts=attempt, rc=rc)
    print(
        f"[supervisor] giving up after {attempt} attempts "
        f"(last rc={rc}); see {cfg.events}",
        file=sys.stderr,
    )
    return rc if rc not in (0, None) else 1
# --- serving-daemon supervision (`cli serve --supervised`) -----------------


def daemon_supervisor_config(
    service_dir: str,
    cmd: list,
    stall_timeout: float = 120.0,
    max_restarts: int = 8,
    env: Optional[dict] = None,
) -> SupervisorConfig:
    """SupervisorConfig for the checking-as-a-service daemon
    (service/daemon.py): the daemon appends one heartbeat line per poll
    tick to ``<service-dir>/service/heartbeat.jsonl`` even when idle, so a
    wedged accelerator (the failure mode that motivated the whole
    supervision stack) stalls the heartbeat and earns the same kill +
    bounded-backoff restart as an engine run.  A restarted daemon re-claims
    the queue's orphaned ``claimed/`` jobs on startup (service/queue.py),
    so in-flight work survives the bounce.  The default stall timeout is
    minutes, not the engine's half-hour: an idle daemon heartbeats every
    poll interval, so silence means wedged, not busy.

    The daemon's RESOURCE_EXHAUSTED handling is per-JOB (a breaching job
    exits typed inside the daemon; the daemon itself exits 0/1), so the
    supervisor's rc-75 halt policy only triggers if the daemon process
    itself dies typed — which it never does in normal operation."""
    svc = os.path.join(service_dir, "service")
    os.makedirs(svc, exist_ok=True)
    return SupervisorConfig(
        cmd=cmd,
        heartbeat=os.path.join(svc, "heartbeat.jsonl"),
        events=os.path.join(svc, "events.jsonl"),
        log_dir=os.path.join(svc, "logs"),
        stall_timeout=stall_timeout,
        max_restarts=max_restarts,
        env=dict(env if env is not None else os.environ),
    )


# --- fleet supervision (the multi-process jax.distributed regime) --------
#
# A pod-scale sharded run is P cooperating processes in one
# jax.distributed job; losing ANY of them wedges the rest in their next
# collective (they block on a peer that will never answer), so
# per-process restart is meaningless — the correct unit of recovery is
# the whole fleet.  `supervise_fleet`:
#
# - launches all P processes of the job (injecting
#   JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, with a
#   fresh coordinator port per attempt — the old coordinator dies with
#   the fleet),
# - watches one heartbeat file per process (`<heartbeat_dir>/proc<i>.jsonl`,
#   appended per BFS level by parallel/sharded.py under
#   KSPEC_SHARD_HEARTBEAT_DIR) — so a *stalled* shard is detected even
#   while its peers' heartbeats still grow,
# - on any process death or per-shard stall, records which process/pid
#   failed, tears the WHOLE fleet down (SIGTERM the process groups, then
#   SIGKILL), and
# - restarts the entire job under the usual bounded budget with jittered
#   backoff; the children resume from the newest cross-shard-consistent
#   checkpoint generation exactly as a single-process restart would
#   (resilience.checkpoints pairs the coordinator's main file with every
#   per-host part file BY LEVEL, so a crash between part and main
#   promotes falls back to the newest level all shards agree on).


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class FleetConfig:
    cmd: list  # one command, launched num_processes times
    num_processes: int
    events: str = "RESILIENT_EVENTS.jsonl"
    heartbeat_dir: Optional[str] = None  # per-process shard heartbeats
    log_dir: Optional[str] = None  # per-attempt, per-process child logs
    stall_timeout: float = 1800.0
    max_restarts: int = 8
    backoff_base: float = 5.0
    backoff_cap: float = 300.0
    jitter: float = 0.25
    poll: float = 0.5
    term_grace: float = 10.0
    env: Optional[dict] = None
    run_id: Optional[str] = None
    coordinator_host: str = "127.0.0.1"
    # CPU fleets (CI / rehearsals): virtual devices per process via
    # --xla_force_host_platform_device_count; None = leave XLA_FLAGS alone
    devices_per_proc: Optional[int] = None
    # resource-exit policy, same contract as SupervisorConfig: one
    # process exiting EXIT_RESOURCE_EXHAUSTED (its peers wedge in the
    # next collective and are torn down) halts the fleet with a verdict,
    # or reclaims + retries exactly once under reclaim=True
    reclaim: bool = False
    reclaim_dirs: tuple = ()
    rng: random.Random = field(default_factory=random.Random, repr=False)

    backoff = SupervisorConfig.backoff
    event = SupervisorConfig.event


def _child_env(cfg: FleetConfig, proc: int, port: int) -> dict:
    env = dict(cfg.env if cfg.env is not None else os.environ)
    env["JAX_COORDINATOR_ADDRESS"] = f"{cfg.coordinator_host}:{port}"
    env["JAX_NUM_PROCESSES"] = str(cfg.num_processes)
    env["JAX_PROCESS_ID"] = str(proc)
    if cfg.heartbeat_dir is not None:
        env["KSPEC_SHARD_HEARTBEAT_DIR"] = cfg.heartbeat_dir
    if cfg.devices_per_proc is not None:
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={cfg.devices_per_proc}"
        ).strip()
    return env


def _signal_pg(pid: int, sig) -> None:
    try:
        os.killpg(pid, sig)  # pgid == pid (start_new_session)
    except (OSError, ProcessLookupError):
        try:
            os.kill(pid, sig)
        except (OSError, ProcessLookupError):
            pass


def _teardown_fleet(cfg: FleetConfig, children: list) -> None:
    """SIGTERM every live process group, grace, then SIGKILL: a partial
    fleet must never be left holding devices or the checkpoint dir."""
    live = [c for c in children if c is not None and c.poll() is None]
    for c in live:
        _signal_pg(c.pid, signal.SIGTERM)
    deadline = _clk.monotonic() + cfg.term_grace
    for c in live:
        while c.poll() is None and _clk.monotonic() < deadline:
            _clk.sleep(0.05)
        if c.poll() is None:
            _signal_pg(c.pid, signal.SIGKILL)
            c.wait()


def _run_fleet_attempt(cfg: FleetConfig, attempt: int) -> str:
    """One whole-fleet launch -> 'ok' | 'dead' | 'resource'.

    'resource': some process performed a RESOURCE_EXHAUSTED clean exit
    (full disk / breached budget — resilience.resources); its wedged
    peers are torn down like any fleet failure, but the *classification*
    must survive so supervise_fleet never restarts into the full disk."""
    port = _free_port()
    if cfg.heartbeat_dir is not None:
        os.makedirs(cfg.heartbeat_dir, exist_ok=True)
    log_fhs = []
    children = []
    try:
        for i in range(cfg.num_processes):
            fh = None
            if cfg.log_dir is not None:
                os.makedirs(cfg.log_dir, exist_ok=True)
                fh = open(
                    os.path.join(
                        cfg.log_dir, f"attempt-{attempt:02d}-proc{i}.log"
                    ),
                    "wb",
                )
            log_fhs.append(fh)
            children.append(
                subprocess.Popen(
                    cfg.cmd,
                    stdout=fh or None,
                    stderr=subprocess.STDOUT if fh else None,
                    env=_child_env(cfg, i, port),
                    start_new_session=True,
                )
            )
        hb_paths = [
            os.path.join(cfg.heartbeat_dir, f"proc{i}.jsonl")
            if cfg.heartbeat_dir is not None
            else None
            for i in range(cfg.num_processes)
        ]
        hb_sizes = [_hb_size(p) for p in hb_paths]
        last_progress = [_clk.monotonic()] * cfg.num_processes
        done = [None] * cfg.num_processes  # rc once exited
        while True:
            now = _clk.monotonic()
            stalled = None
            for i, child in enumerate(children):
                if done[i] is not None:
                    continue
                rc = child.poll()
                if rc is not None:
                    done[i] = rc
                    continue
                if hb_paths[i] is not None:
                    size = _hb_size(hb_paths[i])
                    if size != hb_sizes[i]:
                        hb_sizes[i] = size
                        last_progress[i] = now
                    elif (
                        stalled is None
                        and now - last_progress[i] > cfg.stall_timeout
                    ):
                        stalled = i
            # classify only AFTER a full sweep: a peer noticing a lost
            # rc-75 process can itself die non-zero within the same poll
            # window, and child-index order must never let that crash mask
            # the typed exit (the "restart into a full disk" hot-loop)
            failed = next(
                (i for i, rc in enumerate(done) if rc not in (0, None)), None
            )
            if failed is not None and done[failed] != EXIT_RESOURCE_EXHAUSTED:
                # one extra poll cycle of grace for the reverse ordering —
                # the peer's crash landing just before the typed exit
                _clk.sleep(cfg.poll)
                for i, child in enumerate(children):
                    if done[i] is None:
                        done[i] = child.poll()
            resource = next(
                (
                    i
                    for i, rc in enumerate(done)
                    if rc == EXIT_RESOURCE_EXHAUSTED
                ),
                None,
            )
            if resource is not None:
                # one process ran out of disk/RSS/time and exited typed;
                # its peers wedge in the next collective — tear down like
                # any fleet failure, but carry the classification up
                cfg.event(
                    event="shard-resource-exhausted",
                    attempt=attempt,
                    proc=resource,
                    pid=children[resource].pid,
                    rc=done[resource],
                )
                return "resource"
            if failed is not None:
                # one shard's process died: the rest are (or will be)
                # wedged in a collective — fail the whole attempt
                if done[failed] == EXIT_INTEGRITY:
                    # typed integrity exit: restartable like a crash (the
                    # resume path skips chain-failed generations), but
                    # the classification is recorded for attribution
                    cfg.event(
                        event="shard-integrity-violation",
                        attempt=attempt,
                        proc=failed,
                        pid=children[failed].pid,
                        rc=done[failed],
                    )
                cfg.event(
                    event="shard-exit",
                    attempt=attempt,
                    proc=failed,
                    pid=children[failed].pid,
                    rc=done[failed],
                )
                return "dead"
            if stalled is not None:
                cfg.event(
                    event="shard-stall",
                    attempt=attempt,
                    proc=stalled,
                    pid=children[stalled].pid,
                    stall_timeout=cfg.stall_timeout,
                    heartbeat=hb_paths[stalled],
                )
                return "dead"
            if all(rc == 0 for rc in done):
                return "ok"
            _clk.sleep(cfg.poll)
    finally:
        _teardown_fleet(cfg, children)
        for fh in log_fhs:
            if fh is not None:
                fh.close()


def supervise_fleet(cfg: FleetConfig) -> int:
    """Run the whole fleet to success or budget exhaustion; 0 on success."""
    reclaimed = False
    attempt = 0
    restarts_used = 0
    # same while-loop restart accounting as supervise(): the one
    # --reclaim retry is guaranteed even on the final budgeted attempt
    while True:
        attempt += 1
        cfg.event(
            event="fleet-start",
            attempt=attempt,
            processes=cfg.num_processes,
            cmd=cfg.cmd,
        )
        t0 = _clk.now()
        status = _run_fleet_attempt(cfg, attempt)
        cfg.event(
            event="fleet-teardown",
            attempt=attempt,
            ok=status == "ok",
            status=status,
            seconds=round(_clk.now() - t0, 1),
        )
        if status == "ok":
            cfg.event(event="fleet-complete", attempt=attempt)
            return 0
        if status == "resource":
            # same contract as the single-process supervisor: resource
            # exits never burn the restart budget into a full disk —
            # one reclaim-retry at most, else halt with the verdict
            if cfg.reclaim and not reclaimed:
                reclaimed = True
                _try_reclaim(cfg, attempt)
                continue
            return _resource_verdict(
                cfg, attempt, EXIT_RESOURCE_EXHAUSTED, reclaimed
            )
        if restarts_used >= cfg.max_restarts:
            break
        restarts_used += 1
        delay = cfg.backoff(restarts_used)
        cfg.event(event="restart", attempt=attempt, backoff_s=round(delay, 2))
        _clk.sleep(delay)
    cfg.event(event="fleet-give-up", attempts=attempt)
    print(
        f"[supervisor] fleet giving up after {attempt} "
        f"attempts; see {cfg.events}",
        file=sys.stderr,
    )
    return 1
