"""Supervised auto-resume runner (the library behind scripts/resilient_run.py).

Replaces the round-5 bash supervisor (`scripts/supervise_prod464.sh`) with
a watchdog that actually observes progress instead of only exit codes:

- spawns the run as a child process (stdout/stderr to a per-attempt log
  when `log_dir` is set),
- watches the heartbeat JSONL file the child appends to (the engines'
  `stats_path` per-level stream) — any growth counts as progress,
- kills the child (SIGTERM, then SIGKILL) when the heartbeat stalls past
  `stall_timeout` seconds — the wedged-tunnel mode that has eaten whole
  rounds hangs without exiting, which a bash `for` loop never notices,
- restarts from the engine checkpoint with a bounded restart budget and
  jittered exponential backoff (thundering-herd hygiene even for one box),
- appends one heartbeat-enveloped JSONL event per transition (start /
  stall-kill / exit / complete / give-up) to the event log.

The child is responsible for its own resume: engines resume automatically
from `checkpoint_dir` (hardened, checksummed, keep-last-K — see
`resilience.checkpoints`), so a restart is exactly "run the same command
again".

Must stay jax-free (the parent never touches a possibly-wedged tunnel).
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

from .heartbeat import append_jsonl, heartbeat_record


@dataclass
class SupervisorConfig:
    cmd: list
    heartbeat: Optional[str] = None  # JSONL the child appends to
    events: str = "RESILIENT_EVENTS.jsonl"
    log_dir: Optional[str] = None  # per-attempt child logs
    stall_timeout: float = 1800.0  # no heartbeat growth for this long -> kill
    max_restarts: int = 8  # restarts, not attempts (attempts = 1 + this)
    backoff_base: float = 5.0
    backoff_cap: float = 300.0
    jitter: float = 0.25
    poll: float = 0.5
    term_grace: float = 10.0  # SIGTERM -> SIGKILL grace
    env: Optional[dict] = None
    run_id: Optional[str] = None  # obs correlation key (stamped per event)
    rng: random.Random = field(default_factory=random.Random, repr=False)

    def backoff(self, restart: int) -> float:
        d = min(self.backoff_base * 2.0 ** (restart - 1), self.backoff_cap)
        return d * (1.0 + self.jitter * self.rng.random())

    def event(self, **fields) -> None:
        """One supervisor event: heartbeat-enveloped, run_id-stamped when
        the run has one (obs run directories), appended to the log."""
        extra = {"run_id": self.run_id} if self.run_id else {}
        append_jsonl(
            self.events, heartbeat_record("supervisor", **extra, **fields)
        )


STALL_RC = -97  # synthetic rc recorded for a stall-killed attempt


def _hb_size(path: Optional[str]) -> int:
    if not path:
        return 0
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


def _run_attempt(cfg: SupervisorConfig, attempt: int) -> int:
    """One child run: returns its exit code, or STALL_RC if stall-killed."""
    log_fh = None
    if cfg.log_dir is not None:
        os.makedirs(cfg.log_dir, exist_ok=True)
        log_fh = open(
            os.path.join(cfg.log_dir, f"attempt-{attempt:02d}.log"), "wb"
        )
    try:
        # own session/process group: a stall-kill must take down the whole
        # tree (the command may be a shell wrapper whose wedged grandchild
        # would otherwise survive, keep the accelerator, and race the
        # restarted attempt on the checkpoint directory)
        child = subprocess.Popen(
            cfg.cmd,
            stdout=log_fh or None,
            stderr=subprocess.STDOUT if log_fh else None,
            env=cfg.env,
            start_new_session=True,
        )

        def signal_tree(sig):
            try:
                os.killpg(child.pid, sig)  # pgid == pid (new session)
            except (OSError, ProcessLookupError):
                try:
                    child.send_signal(sig)
                except (OSError, ProcessLookupError):
                    pass

        last_progress = time.monotonic()
        hb_size = _hb_size(cfg.heartbeat)
        while True:
            rc = child.poll()
            if rc is not None:
                return rc
            if cfg.heartbeat is None:
                # no heartbeat stream configured: the stall detector is
                # off (a constant size would read as an eternal stall and
                # kill every healthy child) — only child exits matter
                time.sleep(cfg.poll)
                continue
            size = _hb_size(cfg.heartbeat)
            if size != hb_size:
                hb_size = size
                last_progress = time.monotonic()
            if time.monotonic() - last_progress > cfg.stall_timeout:
                cfg.event(
                    event="stall-kill",
                    attempt=attempt,
                    stall_timeout=cfg.stall_timeout,
                    heartbeat=cfg.heartbeat,
                )
                signal_tree(signal.SIGTERM)
                try:
                    child.wait(timeout=cfg.term_grace)
                except subprocess.TimeoutExpired:
                    signal_tree(signal.SIGKILL)
                    child.wait()
                return STALL_RC
            time.sleep(cfg.poll)
    finally:
        if log_fh is not None:
            log_fh.close()


def supervise(cfg: SupervisorConfig) -> int:
    """Run cfg.cmd to success or budget exhaustion; returns the final rc."""
    rc = None
    for attempt in range(1, cfg.max_restarts + 2):
        cfg.event(event="start", attempt=attempt, cmd=cfg.cmd)
        t0 = time.time()
        rc = _run_attempt(cfg, attempt)
        cfg.event(
            event="exit",
            attempt=attempt,
            rc=rc,
            seconds=round(time.time() - t0, 1),
        )
        if rc == 0:
            cfg.event(event="complete", attempt=attempt)
            return 0
        if attempt > cfg.max_restarts:
            break
        delay = cfg.backoff(attempt)
        cfg.event(
            event="restart", attempt=attempt, backoff_s=round(delay, 2)
        )
        time.sleep(delay)
    cfg.event(event="give-up", attempts=cfg.max_restarts + 1, rc=rc)
    print(
        f"[supervisor] giving up after {cfg.max_restarts + 1} attempts "
        f"(last rc={rc}); see {cfg.events}",
        file=sys.stderr,
    )
    return rc if rc not in (0, None) else 1
