"""Resilience subsystem: fault injection, hardened checkpoints, retries.

Long-horizon exhaustive searches (the 10.7-hour half-billion-state product
runs, RUNPROD464_r5.log) treat a crash as a restartable event, not a lost
run.  This package supplies the four pieces the engines and the supervisor
share:

- `faults`      — deterministic fault injection (`KSPEC_FAULT` env grammar)
                  so every recovery path below is testable in tier-1 on CPU;
- `checkpoints` — checksummed, keep-last-K rotating checkpoint store with
                  atomic promote and automatic fallback to the newest
                  verifying generation on load corruption;
- `retry`       — error classification (transient backend error vs device
                  RESOURCE_EXHAUSTED vs the reproducible wide-product
                  compile OOM vs fatal) and a bounded exponential-backoff
                  policy;
- `resources`   — resource-exhaustion governance (disk/RSS budgets,
                  per-level deadline watchdog, soft-breach reclamation,
                  the typed RESOURCE_EXHAUSTED clean exit, and the
                  supervisor's --reclaim sweep);
- `integrity`   — the silent-corruption defense (level digest chains,
                  shadow re-execution sampling, the typed
                  INTEGRITY_VIOLATION exit 76, and the jax-free chain
                  validator shared by resume and `cli verify-checkpoint`);
- `heartbeat`   — the shared JSONL heartbeat envelope ({kind, ts, unix})
                  written by the engines' per-level stats streams and
                  consumed by the supervisor's stall detector;
- `supervisor`  — the auto-resume run loop behind scripts/resilient_run.py
                  (spawn, watch heartbeat, kill on stall, restart from
                  checkpoint with a bounded budget and jittered backoff).

Nothing in this package imports jax: the supervisor and the TPU-window
sentry run in parents that must never touch a possibly-wedged accelerator
tunnel.
"""

from .checkpoints import CheckpointCorrupt, CheckpointStore
from .faults import FaultPlan, InjectedCrash, InjectedFault, corrupt_file
from .integrity import EXIT_INTEGRITY, IntegrityError, LevelDigestChain
from .heartbeat import append_jsonl, heartbeat_record
from .resources import (
    EXIT_RESOURCE_EXHAUSTED,
    ResourceExhausted,
    ResourceGovernor,
    is_disk_full,
    reclaim_disk,
)
from .retry import RetryPolicy, classify

__all__ = [
    "CheckpointCorrupt",
    "CheckpointStore",
    "EXIT_INTEGRITY",
    "EXIT_RESOURCE_EXHAUSTED",
    "FaultPlan",
    "IntegrityError",
    "LevelDigestChain",
    "InjectedCrash",
    "InjectedFault",
    "ResourceExhausted",
    "ResourceGovernor",
    "RetryPolicy",
    "append_jsonl",
    "classify",
    "corrupt_file",
    "heartbeat_record",
    "is_disk_full",
    "reclaim_disk",
]
