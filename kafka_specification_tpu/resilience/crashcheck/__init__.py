"""Crash-consistency torture harness for the repo's own recovery
protocols.

The repo's whole thesis is exhaustive state-space exploration — this
package applies the same discipline (ALICE-style; PAPERS.md) to the
seven durable-write protocols the serving plane stands on.  Three
parts:

1. **Durable-IO interposition** (``kafka_specification_tpu.durable_io``)
   — every durable filesystem effect flows through one recordable shim,
   so a scenario run yields the exact op-trace the protocol issued.
2. **Crash-state enumeration** (``fsmodel``) — every prefix of the
   op-trace, degraded per what a real filesystem may legally persist:
   un-fsynced data truncated or block-torn, un-dir-fsynced renames
   reverted or half-persisted, killed-mid-append tails.
3. **Recovery oracles** (``scenarios``) — each protocol's *existing*
   recovery owner runs against every materialized crash state and its
   convergence invariant is asserted: no acknowledged job lost,
   exactly-once verdicts, no torn entry ever served, chains verify or
   degrade typed, no orphan survives the janitor.

Front door: ``cli crashcheck [--protocol P] [--json]`` — jax-free,
exits 1 on any non-convergent state, emits the schema-versioned
``kspec-crashcheck/1`` record whose findings carry the op-log prefix
and crash state as a machine-readable repro.  docs/resilience.md
§ Crash consistency maps every durable artifact to its scenario.
"""

from .harness import CRASHCHECK_SCHEMA, run_crashcheck, run_scenario
from .scenarios import SCENARIOS, Scenario, list_scenarios

__all__ = [
    "CRASHCHECK_SCHEMA", "SCENARIOS", "Scenario", "list_scenarios",
    "run_crashcheck", "run_scenario",
]
