"""Record -> enumerate -> recover: the crashcheck run loop.

One scenario pass: run the protocol once against a scratch tree with the
``durable_io`` recorder installed, enumerate every legal post-crash
state of the recorded op-trace (``fsmodel``), materialize each state
into a fresh tree, and run the protocol's recovery owner against it
inside the *crashed-process view* — the recording pid reads as dead (so
pid-keyed adoption protocols fire) and the clock-skew allowance is
zeroed (so the backdated leases read as the expired leases they would be
at real recovery time).

Output is the schema-versioned ``kspec-crashcheck/1`` record.  Every
non-convergent state ships as a machine-readable finding: the summarized
op-log, the crash prefix, the degradations applied, and the state's file
listing — enough to rebuild the exact tree and replay the recovery under
a debugger.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tempfile
import time
from contextlib import contextmanager

from ... import durable_io as _dio
from .fsmodel import enumerate_crash_states, materialize, snapshot_tree, \
    summarize_ops

CRASHCHECK_SCHEMA = "kspec-crashcheck/1"


@contextmanager
def _crashed_process_view():
    """Recovery-side reality adjustment: this process recorded the
    scenario, so ITS pid is the 'crashed' one — adoption sweeps keyed on
    pid-aliveness must treat it as dead.  The zero-skew allowance that
    a corpse must not enjoy is no longer forced here via a process-global
    ``os.environ`` mutation (unsafe under concurrent harnesses): the
    recovery steps pass ``skew_s=0.0`` explicitly to the queue/router
    skew readers instead, and the env var stays the documented default
    for production sweeps."""
    from ...service import queue as qmod
    from ...service import router as rmod

    me = os.getpid()
    real = qmod._pid_alive

    def fake(pid: int) -> bool:
        return False if pid == me else real(pid)

    qmod._pid_alive = fake
    rmod._pid_alive = fake
    try:
        yield
    finally:
        qmod._pid_alive = real
        rmod._pid_alive = real


def _tree_listing(tree: dict) -> dict:
    return {
        path: {"len": len(data),
               "sha256": hashlib.sha256(data).hexdigest()[:16]}
        for path, data in sorted(tree.items())
    }


def run_scenario(scn, workdir: str) -> dict:
    """One scenario's full pass; -> its per-scenario record section."""
    t_start = time.monotonic()
    record_root = os.path.join(workdir, f"record-{scn.name}")
    os.makedirs(record_root)
    scn.setup(record_root)
    base, dirs = snapshot_tree(record_root)
    rec = _dio.OpRecorder(record_root)
    prev = _dio.install(rec)
    try:
        ctx = scn.run(record_root, rec)
    finally:
        _dio.install(prev)
    ops = rec.ops
    states = enumerate_crash_states(base, ops)
    findings = []
    checked = 0
    with _crashed_process_view():
        for st in states:
            acked = {
                op["label"] for op in ops[:st.prefix] if op["op"] == "ack"
            }
            dest = os.path.join(workdir, "state")
            if os.path.isdir(dest):
                shutil.rmtree(dest)
            materialize(st, dirs, dest)
            checked += 1
            try:
                violations = scn.recover(dest, acked, ctx)
            except Exception as e:  # noqa: BLE001 - a raise IS a finding
                violations = [
                    f"recovery itself raised {type(e).__name__}: {e}"
                ]
            if violations:
                findings.append({
                    "scenario": scn.name,
                    "protocol": scn.protocol,
                    "violations": violations,
                    "prefix": st.prefix,
                    "degraded": st.degraded,
                    "state_digest": st.digest(),
                    "acked": sorted(acked),
                    "op_log": summarize_ops(ops),
                    "tree": _tree_listing(st.tree),
                })
    return {
        "name": scn.name,
        "protocol": scn.protocol,
        "ops": len(ops),
        "states": checked,
        "non_convergent": len(findings),
        "seconds": round(time.monotonic() - t_start, 3),
        "findings": findings,
    }


def run_crashcheck(protocols=None, workdir=None) -> dict:
    """Run every scenario (or the ``--protocol``-selected subset) and
    return the ``kspec-crashcheck/1`` record.  ``ok`` is True iff every
    enumerated crash state converged."""
    from .scenarios import SCENARIOS

    selected = [
        s for s in SCENARIOS
        if protocols is None or s.protocol in protocols
        or s.name in protocols
    ]
    if not selected:
        raise ValueError(
            f"no crashcheck scenario matches {sorted(protocols)} "
            f"(protocols: {sorted({s.protocol for s in SCENARIOS})})"
        )
    t0 = time.monotonic()
    own_tmp = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="kspec-crashcheck-")
    sections, findings = [], []
    try:
        for scn in selected:
            section = run_scenario(scn, workdir)
            findings.extend(section.pop("findings"))
            sections.append(section)
    finally:
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)
    return {
        "schema": CRASHCHECK_SCHEMA,
        "scenarios": sections,
        "protocols": sorted({s["protocol"] for s in sections}),
        "states": sum(s["states"] for s in sections),
        "non_convergent": len(findings),
        "findings": findings,
        "seconds": round(time.monotonic() - t0, 3),
        "ok": not findings,
    }
