"""The crashcheck scenario registry: one entry per durable protocol.

Each scenario is three callables over a scratch root:

- ``setup(root)``: unrecorded preparation (directory skeletons, the
  dead host's stale heartbeat).  The tree snapshot taken after setup is
  the base every crash state is replayed onto.
- ``run(root, rec) -> ctx``: the recorded protocol steps, driven
  through the REAL production code (the queue's submit/claim/finish,
  the router's sweep, the cache's publish, ...).  ``rec.ack(label)``
  marks client-visible acknowledgement points; invariants conditioned
  on an ack apply only to crash prefixes after it.
- ``recover(root, acked, ctx) -> [violation strings]``: the protocol's
  existing recovery owner (startup janitor, lease takeover, reroute
  adoption, chain-verify-or-typed-fallback, ``verify_checkpoint_dir``,
  tolerant journal readers) run against one materialized crash state,
  followed by the protocol's convergence-invariant assertions.

Recovery runs inside the harness's crashed-process view: the recording
pid reads as dead (so ``.requeue-<pid>`` / ``.reroute-<pid>`` adoption
fires exactly as it would for a real crashed sibling) and the clock-skew
allowance is zeroed so backdated leases read as expired.

Everything here is jax-free; numpy is the heaviest import (checkpoint
and run-file payloads).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass

import numpy as np

from ... import durable_io as _dio
from ...obs import fleettrace
from ...obs.tracer import read_jsonl_tolerant

_CFG = "CONSTANTS MaxId = 3"
_MODULE = "IdSequence"

PENDING, CLAIMED, DONE = "pending", "claimed", "done"


@dataclass(frozen=True)
class Scenario:
    name: str
    protocol: str
    description: str
    setup: object
    run: object
    recover: object


def _queue_mod():
    from ...service import queue

    return queue


def _job_states(q, jid) -> list:
    return [st for st in (PENDING, CLAIMED, DONE)
            if os.path.isfile(q._job_path(st, jid))]


def _strays(directory: str, needle: str) -> list:
    try:
        return [n for n in os.listdir(directory) if needle in n]
    except OSError:
        return []


def _tmp_strays(*dirs) -> list:
    out = []
    for d in dirs:
        for n in _strays(d, ".tmp"):
            if n.endswith(".tmp") or ".tmp." in n:
                out.append(os.path.join(d, n))
    return out


# --- queue: submit -> claim -> verdict ------------------------------------


def _queue_setup(root):
    _queue_mod().JobQueue(os.path.join(root, "svc"))


def _queue_run(root, rec):
    q = _queue_mod().JobQueue(os.path.join(root, "svc"))
    spec = q.submit(_CFG, _MODULE, kernel_source="hand")
    jid = spec["job_id"]
    rec.ack("submitted", job_id=jid)
    claimed = q.claim_pending()
    assert [s["job_id"] for s in claimed] == [jid]
    verdict = {"model": _MODULE, "distinct_states": 4, "diameter": 2,
               "levels": [1, 3], "violation": None, "exit_code": 0,
               "job_id": jid}
    q.finish(jid, verdict)
    rec.ack("verdict", job_id=jid)
    return {"job_id": jid, "verdict": verdict}


def _queue_recover(root, acked, ctx):
    viol = []
    jid = ctx["job_id"]
    # skew_s=0.0 explicitly: the crashed claimer is a corpse, so the
    # live-but-drifted skew allowance must not protect its lease (this
    # used to be forced through os.environ["KSPEC_CLOCK_SKEW"] — now
    # threaded as a parameter so concurrent harnesses can't race on it)
    q = _queue_mod().JobQueue(os.path.join(root, "svc"), skew_s=0.0)
    q.requeue_orphans(lease_ttl=0.0)
    states = _job_states(q, jid)
    try:
        result = q.result(jid)
    except Exception as e:  # noqa: BLE001 - any raise is a finding
        viol.append(f"result() raised {type(e).__name__}: {e}")
        result = None
    try:
        q.status(jid)
    except Exception as e:  # noqa: BLE001
        viol.append(f"status() raised {type(e).__name__}: {e}")
    if "submitted" in acked and not states and result is None:
        viol.append("acknowledged submit lost: job in no queue state "
                    "and no verdict")
    if "verdict" in acked:
        if result is None:
            viol.append("acknowledged verdict lost")
        elif result.get("exit_code") != ctx["verdict"]["exit_code"]:
            viol.append("verdict content changed after crash")
    claimed_dir = os.path.join(q.queue_dir, CLAIMED)
    leftover = _strays(claimed_dir, ".requeue-")
    if leftover:
        viol.append(f"janitor left takeover-private files: {leftover}")
    tmps = _tmp_strays(os.path.join(q.queue_dir, PENDING), claimed_dir,
                       os.path.join(q.queue_dir, DONE), q.results_dir)
    if tmps:
        viol.append(f"aged tmp orphans survived the startup janitor: "
                    f"{tmps}")
    return viol


# --- router: re-route a dead host's pending job ---------------------------


def _hb_path(host_dir: str) -> str:
    svc = os.path.join(host_dir, "service")
    os.makedirs(svc, exist_ok=True)
    return os.path.join(svc, "heartbeat-daemon.jsonl")


def _stamp_heartbeat(host_dir: str, unix: float) -> None:
    with open(_hb_path(host_dir), "a") as fh:
        fh.write(json.dumps({"kind": "daemon", "unix": round(unix, 3)})
                 + "\n")


def _router_mod():
    from ...service import router

    return router


def _router_hosts(root):
    return [os.path.join(root, "hostA"), os.path.join(root, "hostB")]


def _router_setup(root):
    hosts = _router_hosts(root)
    for h in hosts:
        _queue_mod().JobQueue(h)
    now = time.time()
    _stamp_heartbeat(hosts[0], now - 3600.0)  # host A: long dead
    _stamp_heartbeat(hosts[1], now)  # host B: alive
    qa = _queue_mod().JobQueue(hosts[0])
    spec = qa.submit(_CFG, _MODULE, kernel_source="hand")
    _router_mod().Router(os.path.join(root, "router"), hosts=hosts)
    with open(os.path.join(root, "job_id"), "w") as fh:
        fh.write(spec["job_id"])


def _router_run(root, rec):
    with open(os.path.join(root, "job_id")) as fh:
        jid = fh.read().strip()
    r = _router_mod().Router(os.path.join(root, "router"),
                             hosts=_router_hosts(root))
    swept = r.sweep()
    rec.ack("rerouted", job_id=jid, swept=swept.get("rerouted", {}))
    return {"job_id": jid}


def _router_recover(root, acked, ctx):
    viol = []
    jid = ctx["job_id"]
    hosts = _router_hosts(root)
    # a live host B keeps heart-beating at real recovery time; restamp it
    # so the pre-crash stamp's age never misclassifies the survivor
    _stamp_heartbeat(hosts[1], time.time())
    # skew_s=0.0: same corpse-gets-no-allowance rule as _queue_recover
    r = _router_mod().Router(os.path.join(root, "router"), hosts=hosts,
                             skew_s=0.0)
    r.sweep()
    copies = []
    for q in r.queues:
        for st in (PENDING, CLAIMED):
            if os.path.isfile(q._job_path(st, jid)):
                copies.append(f"{q.dir}:{st}")
        copies.extend(
            f"{q.dir}:{n}"
            for n in _strays(os.path.join(q.queue_dir, PENDING),
                             ".reroute-")
        )
    if len(copies) != 1:
        viol.append(f"expected exactly one runnable copy after recovery "
                    f"sweep, found {len(copies)}: {copies}")
    route = r.read_route(jid)
    if route is not None and route.get("job_id") != jid:
        viol.append("route record torn or mismatched after crash")
    tmps = _tmp_strays(r.routes_dir)
    if tmps:
        viol.append(f"aged route tmp orphans survived the janitor: {tmps}")
    return viol


# --- state cache: concurrent same-key publish -----------------------------


def _cache_mod():
    from ...service import state_cache

    return state_cache


def _cache_key():
    sc = _cache_mod()
    return sc.CacheKey(_MODULE, False, (("MaxId", 3),), ("TypeOk",), (),
                       False, max_depth=2)


def _toy_publish(cache, key, seed: int) -> dict:
    rng = np.random.RandomState(seed)
    counts = [1, 3, 5]
    rows = [rng.randint(0, 50, size=(n, 2)).astype(np.uint32)
            for n in counts]
    verdict = {"model": _MODULE, "distinct_states": sum(counts),
               "diameter": 2, "levels": counts, "violation": None,
               "exit_code": 0, "states_per_sec": 1.0, "seconds": 0.1}
    ok = cache.publish(key, verdict, exact64=True, lanes=2,
                       level_rows=rows, diameter=2)
    assert ok, "toy publish refused"
    return verdict


def _cache_setup(root):
    _cache_mod().StateSpaceCache(os.path.join(root, "sc"))


def _cache_run(root, rec):
    sc = _cache_mod()
    c = sc.StateSpaceCache(os.path.join(root, "sc"))
    key = _cache_key()
    _toy_publish(c, key, seed=0)
    rec.ack("published")
    # the same-key race: a second publisher (fresh nonce) wins the
    # entry-promote last; the loser's uniquely-named artifacts become GC
    # fodder
    _toy_publish(c, key, seed=1)
    rec.ack("published2")
    return {}


def _cache_recover(root, acked, ctx):
    viol = []
    sc = _cache_mod()
    c = sc.StateSpaceCache(os.path.join(root, "sc"))
    key = _cache_key()
    try:
        hit = c.lookup(key)
    except Exception as e:  # noqa: BLE001 - lookup must degrade typed
        return [f"lookup raised {type(e).__name__}: {e} (typed "
                "cache-fallback is the only legal degradation)"]
    if hit is not None and not isinstance(hit, sc.CacheHit):
        viol.append(f"lookup returned a non-hit object: {type(hit)}")
    if acked and hit is None:
        viol.append("acknowledged publish not served (entry promote was "
                    "fsync'd + dir-fsync'd, so it must survive)")
    c.collect_garbage(key, grace_s=0.0)
    d = c._entry_dir(key)
    referenced = {"entry.json"}
    try:
        with open(os.path.join(d, "entry.json")) as fh:
            art = json.load(fh).get("artifact") or {}
        for part in ("visited", "boundary"):
            if art.get(part):
                referenced.add(art[part]["name"])
                # lookup's verify pass rebuilds a referenced run's
                # missing bloom sidecar — that sidecar is live, not
                # garbage
                referenced.add(art[part]["name"] + ".bloom")
    except (OSError, ValueError):
        pass
    try:
        leftovers = [n for n in os.listdir(d) if n not in referenced]
    except OSError:
        leftovers = []
    if leftovers:
        viol.append(f"orphan artifacts survived grace-aged GC: "
                    f"{sorted(leftovers)}")
    return viol


# --- checkpoints: save + rotate -------------------------------------------


def _ckpt_store(root):
    from ...resilience.checkpoints import CheckpointStore

    return CheckpointStore(os.path.join(root, "ck"), "state.npz",
                           ident="crashcheck", keep=2)


def _ckpt_setup(root):
    os.makedirs(os.path.join(root, "ck"), exist_ok=True)


def _ckpt_run(root, rec):
    store = _ckpt_store(root)
    for depth in (1, 2):
        store.save(depth, {"frontier": np.arange(4 * depth,
                                                 dtype=np.uint64)})
        rec.ack(f"saved{depth}", depth=depth)
    return {}


def _ckpt_recover(root, acked, ctx):
    viol = []
    from ...resilience.checkpoints import verify_checkpoint_dir

    try:
        verify_checkpoint_dir(os.path.join(root, "ck"))
    except Exception as e:  # noqa: BLE001
        viol.append(f"verify_checkpoint_dir raised {type(e).__name__}: "
                    f"{e}")
    from ...resilience.checkpoints import CheckpointCorrupt

    try:
        loaded = _ckpt_store(root).load()
    except CheckpointCorrupt:
        # load()'s documented contract: files exist but no generation
        # verifies.  Checkpoints are deliberately unfsynced (recomputable
        # progress — loss costs re-exploration, not correctness), so
        # this typed raise IS the convergent degradation for a crash
        # that tore every generation.
        return viol
    except Exception as e:  # noqa: BLE001 - only the typed raise is legal
        return viol + [f"load() raised {type(e).__name__}: {e} (a torn "
                       "generation must degrade to CheckpointCorrupt, "
                       "never crash the resume untyped)"]
    # What a successful load DOES owe: the generation it picked
    # round-trips intact — arrays match their stamped depth.
    if loaded is not None:
        main, _parts, _gen = loaded
        if int(main["frontier"].shape[0]) != 4 * int(main["depth"]):
            viol.append("load() returned a generation whose content "
                        "does not match its stamped depth")
    return viol


# --- spill runs: write + k-way merge + retire inputs ----------------------


def _spill_run(root, rec):
    from ...storage.runs import SortedRun, merge_runs, write_run

    d = os.path.join(root, "spill")
    os.makedirs(d, exist_ok=True)
    metas = []
    for i in range(2):
        fps = np.sort(
            np.arange(8, dtype=np.uint64) * np.uint64(7) + np.uint64(i)
        )
        metas.append(write_run(os.path.join(d, f"run-{i}.run"), fps))
        rec.ack(f"spilled{i}")
    runs = [SortedRun(d, m, verify=False) for m in metas]
    merged = merge_runs(runs, os.path.join(d, "merged.run"))
    rec.ack("merged")
    # adoption retires the merged inputs (storage/tiered.py's post-merge
    # unlink, driven at this layer so the protocol's op shape matches)
    for m in metas:
        _dio.unlink(os.path.join(d, m["name"]))
    rec.ack("inputs-retired")
    return {"metas": metas, "merged": merged}


def _spill_recover(root, acked, ctx):
    viol = []
    from ...storage.runs import RunCorrupt, SortedRun

    d = os.path.join(root, "spill")
    _dio.sweep_tmp(d)
    for meta in ctx["metas"] + [ctx["merged"]]:
        path = os.path.join(d, meta["name"])
        if not os.path.isfile(path):
            continue  # retired or never promoted: both legal
        try:
            run = SortedRun(d, meta, verify=True)
            run.arr._mmap.close()
        except RunCorrupt as e:
            viol.append(f"{meta['name']}: promoted run corrupt after "
                        f"crash ({e}) — the atomic promote must never "
                        "expose torn data")
        except Exception as e:  # noqa: BLE001
            viol.append(f"{meta['name']}: open raised "
                        f"{type(e).__name__}: {e}")
    if "merged" in acked and not os.path.isfile(
        os.path.join(d, ctx["merged"]["name"])
    ):
        viol.append("acknowledged merged run lost (its promote is "
                    "fsync'd + dir-fsync'd)")
    tmps = _tmp_strays(d)
    if tmps:
        viol.append(f"tmp orphans survived sweep_tmp: {tmps}")
    return viol


# --- sweep manifest: create, update, resume -------------------------------


def _sweep_lattice():
    from ...sweep.lattice import Axis, LatticeSheet, LatticeSpec

    sheet = LatticeSheet(module=_MODULE, cfg_text=_CFG,
                         axes=[Axis("MaxId", (2, 3))])
    return LatticeSpec(name="crashcheck", sheets=[sheet])


def _sweep_run(root, rec):
    from ...sweep.portfolio import Manifest

    d = os.path.join(root, "sweep")
    m = Manifest.open_or_create(d, _sweep_lattice())
    m.promote()
    rec.ack("manifest")
    m.rec["points"]["p0"] = {"state": "submitted", "job_id": "j0"}
    m.promote()
    rec.ack("manifest2")
    return {"sweep_id": m.rec["sweep_id"]}


def _sweep_recover(root, acked, ctx):
    viol = []
    from ...sweep.portfolio import Manifest, load_manifest

    d = os.path.join(root, "sweep")
    try:
        rec = load_manifest(d)
    except FileNotFoundError:
        rec = None
        if acked:
            viol.append("acknowledged manifest promote lost")
    except Exception as e:  # noqa: BLE001 - a torn manifest is a finding
        return [f"load_manifest raised {type(e).__name__}: {e} (the "
                "promote is atomic — a reader must never see a torn "
                "manifest)"]
    if rec is not None:
        if rec.get("sweep_id") != ctx["sweep_id"]:
            viol.append("manifest identity changed across the crash "
                        "(resume would mint duplicate jobs)")
        if "manifest2" in acked and "p0" not in rec.get("points", {}):
            viol.append("acknowledged manifest update lost")
    # crash-resume reopens the manifest: the open-time janitor must
    # collect aged promote tmps and the reopen must not raise
    try:
        Manifest.open_or_create(d, _sweep_lattice())
    except Exception as e:  # noqa: BLE001
        viol.append(f"crash-resume reopen raised {type(e).__name__}: {e}")
    tmps = _tmp_strays(d)
    if tmps:
        viol.append(f"aged manifest tmps survived the open janitor: "
                    f"{tmps}")
    return viol


# --- fleet trace journal: O_APPEND emits ----------------------------------


def _trace_run(root, rec):
    trace = fleettrace.mint_trace("job-cc", time.time())
    t0 = fleettrace.now()
    for i in range(3):
        sid = fleettrace.emit_span(
            root, trace, "job-submit" if i == 0 else "queue-claim",
            t0, fleettrace.now(), job_id="job-cc",
            span_id=trace["span_id"] if i == 0 else None,
        )
        assert sid is not None
        rec.ack(f"emitted{i}")
    fleettrace.emit_event(root, trace, "queue-requeue", job_id="job-cc",
                          from_pid=1, by_pid=2, reason="crashcheck")
    return {"trace_id": trace["trace_id"]}


def _trace_recover(root, acked, ctx):
    viol = []
    path = fleettrace.trace_path(root, "job-cc")
    try:
        recs = read_jsonl_tolerant(path)
    except Exception as e:  # noqa: BLE001 - tolerant reader, by name
        return [f"read_jsonl_tolerant raised {type(e).__name__}: {e}"]
    try:
        assembled = fleettrace.assemble(recs, job_id="job-cc")
    except Exception as e:  # noqa: BLE001
        return [f"assemble raised {type(e).__name__}: {e} on a torn "
                "journal"]
    # the journal contract: appends are best-effort telemetry (never
    # fsync'd, so even acked emits may be lost) but every SURVIVING
    # record is whole — a torn tail is dropped by the reader, never
    # half-parsed into a bogus span
    for r in recs:
        if r.get("kind") not in ("span", "event"):
            viol.append(f"torn record leaked through the tolerant "
                        f"reader: {r}")
    if assembled.get("job_id") != "job-cc":
        viol.append("assemble mangled the job identity on a torn "
                    "journal")
    return viol


SCENARIOS = (
    Scenario(
        "queue-lifecycle", "queue",
        "submit -> claim -> verdict through JobQueue; recovery = startup "
        "janitor + lease-takeover requeue.  Invariants: an acknowledged "
        "submit is never lost, an acknowledged verdict survives "
        "unchanged, no takeover-private file or aged tmp outlives the "
        "janitor.",
        _queue_setup, _queue_run, _queue_recover,
    ),
    Scenario(
        "router-reroute", "router",
        "router sweep moves a dead host's pending job to a survivor via "
        "the .reroute-<pid> private-rename protocol; recovery = "
        "stale-reroute adoption + another sweep.  Invariant: exactly one "
        "runnable copy across hosts, route records never torn.",
        _router_setup, _router_run, _router_recover,
    ),
    Scenario(
        "cache-publish", "cache",
        "two same-key state-cache publishes (the cross-host race); "
        "recovery = chain-verify-or-typed-fallback lookup + grace-aged "
        "GC.  Invariants: lookup never raises and never serves a torn "
        "entry, an acknowledged publish is served, no orphan artifact "
        "survives GC.",
        _cache_setup, _cache_run, _cache_recover,
    ),
    Scenario(
        "checkpoint-save", "ckpt",
        "two checkpoint saves with generation rotation; recovery = "
        "verify_checkpoint_dir + load().  Invariant: load never crashes "
        "and never accepts a torn generation (falls back or starts "
        "fresh; checkpoints are recomputable, so loss costs work, not "
        "correctness).",
        _ckpt_setup, _ckpt_run, _ckpt_recover,
    ),
    Scenario(
        "spill-merge", "spill",
        "two spill runs, a k-way merge, input retirement (adoption's "
        "durable half); recovery = sweep_tmp + CRC verification of "
        "every surviving run.  Invariants: a promoted run is never "
        "torn, an acknowledged merge survives, no tmp survives the "
        "sweep.",
        lambda root: os.makedirs(os.path.join(root, "spill"),
                                 exist_ok=True),
        _spill_run, _spill_recover,
    ),
    Scenario(
        "sweep-manifest", "sweep",
        "sweep manifest create + update promotes; recovery = "
        "load_manifest + crash-resume reopen (open-time janitor).  "
        "Invariants: never a torn manifest, acknowledged updates "
        "survive, sweep identity is stable across the crash.",
        lambda root: None, _sweep_run, _sweep_recover,
    ),
    Scenario(
        "trace-append", "trace",
        "fleet-trace O_APPEND emits; recovery = tolerant journal read + "
        "assemble.  Invariants: a torn tail never crashes a reader or "
        "leaks a half-record into the span tree (emits are best-effort "
        "telemetry; loss is legal, corruption is not).",
        lambda root: None, _trace_run, _trace_recover,
    ),
)


def list_scenarios() -> list:
    """[{name, protocol, description}] — the registry rows ``cli faults
    --list`` renders next to the fault grammar."""
    return [
        {"name": s.name, "protocol": s.protocol,
         "description": s.description}
        for s in SCENARIOS
    ]
