"""Legal post-crash filesystem states from a recorded durable-op trace.

ALICE-style model (PAPERS.md; the crash-consistency literature's core
observation): a crash does not leave "the filesystem as of the last
op" — it leaves any state the filesystem was *permitted* to persist.
The permissions this model grants, per op of the ``durable_io``
vocabulary:

- ``write`` with ``fsynced=False``: the file's *data* is independent of
  every directory operation — it may persist empty, as a prefix, or as
  a prefix plus a garbage block (a torn sector), no matter how much
  later the crash happens.  ``fsynced=True`` data is durable the moment
  the op is recorded (recording happens after the fsync returned), but
  a crash *during* the write is modeled at the preceding prefix as a
  partial application of the upcoming op.
- ``rename``/``unlink``: directory-entry ops are durable only once a
  ``fsync_dir`` of the affected directory follows them.  An un-fsynced
  rename may revert wholesale (the missing-dir-fsync case this harness
  exists to make observable: the file is back at the source name, the
  destination shows its pre-rename content) or half-persist with the
  source entry lingering next to the destination (both names reach the
  moved content — what ``sweep_tmp`` exists to collect).  An un-fsynced
  unlink may simply not have happened.
- ``append``: journal tails are never fsync'd; the final record on each
  path may be dropped entirely or torn mid-record.

Enumeration is bounded, not exhaustive: at every prefix of the op-log
we emit the clean state plus one state per (vulnerable op, degradation
mode) over a recent-ops window, plus a pairwise combination of the two
newest vulnerabilities (the classic "rename reverted AND data torn"
compound).  States are deduplicated by tree digest across the whole
scenario, so the reported count is of *distinct* filesystem states.
"""

from __future__ import annotations

import hashlib
import os
import time
from dataclasses import dataclass, field

#: torn-write garbage block: what a sector-torn write leaves after the
#: valid prefix (0xff bytes make JSON/zip/CRC readers fail loudly, the
#: adversarial case — a silent-prefix tear is covered separately)
_GARBAGE = b"\xff" * 8

#: how many of the most recent vulnerable ops get degraded per prefix
_VULN_WINDOW = 6

#: hard cap of degraded states emitted per prefix (clean state excluded)
_MAX_PER_PREFIX = 14


def snapshot_tree(root: str):
    """(files: {relpath: bytes}, dirs: [relpath]) under ``root``."""
    files, dirs = {}, []
    root = os.path.abspath(root)
    for cur, dns, fns in os.walk(root):
        rel = os.path.relpath(cur, root).replace(os.sep, "/")
        if rel != ".":
            dirs.append(rel)
        for fn in fns:
            p = os.path.join(cur, fn)
            with open(p, "rb") as fh:
                files[(rel + "/" + fn) if rel != "." else fn] = fh.read()
    return files, sorted(dirs)


def _dirname(path: str) -> str:
    return path.rsplit("/", 1)[0] if "/" in path else "."


def replay(base: dict, ops: list, n: int, transforms=None) -> dict:
    """Apply ``ops[:n]`` to a copy of ``base``; ``transforms`` maps an op
    index to a degradation: ``("skip",)`` (the op never persisted),
    ``("linger",)`` (rename persisted but the source entry survived too),
    or ``("data", bytes)`` (the op's payload persisted torn)."""
    transforms = transforms or {}
    tree = dict(base)
    for idx in range(n):
        op = ops[idx]
        t = transforms.get(idx)
        kind = op["op"]
        if t is not None and t[0] == "skip":
            continue
        if kind == "write":
            data = t[1] if t is not None and t[0] == "data" else op["data"]
            tree[op["path"]] = data
        elif kind == "append":
            data = t[1] if t is not None and t[0] == "data" else op["data"]
            tree[op["path"]] = tree.get(op["path"], b"") + data
        elif kind == "rename":
            src, dst = op["src"], op["dst"]
            if src in tree:
                tree[dst] = tree[src]
                if t is None or t[0] != "linger":
                    del tree[src]
        elif kind == "unlink":
            tree.pop(op["path"], None)
        # fsync_dir / ack: no tree effect
    return tree


def _vulnerable(ops: list, n: int) -> list:
    """[(op_index, mode)] of legal degradations at prefix ``n``, newest
    first.  ``mode``: "skip" | "linger" | "data" | "tail"."""
    def synced_after(j: int, d: str) -> bool:
        return any(
            ops[k]["op"] == "fsync_dir" and ops[k]["path"] == d
            for k in range(j + 1, n)
        )

    out = []
    last_append = {}
    for j in range(n):
        op = ops[j]
        kind = op["op"]
        if kind == "rename":
            dd, sd = _dirname(op["dst"]), _dirname(op["src"])
            if not synced_after(j, dd):
                out.append((j, "skip"))
                out.append((j, "linger"))
            elif not synced_after(j, sd):
                # destination entry is durable but the source removal may
                # not be (cross-directory rename fsyncing only the
                # destination — the queue's claim rename)
                out.append((j, "linger"))
        elif kind == "unlink":
            if not synced_after(j, _dirname(op["path"])):
                out.append((j, "skip"))
        elif kind == "write":
            if not op.get("fsynced"):
                out.append((j, "data"))
        elif kind == "append":
            last_append[op["path"]] = j
    out.extend((j, "tail") for j in last_append.values())
    out.sort(key=lambda it: -it[0])
    return out[:_VULN_WINDOW]


def _data_variants(data: bytes) -> list:
    """Torn-content variants of a payload, coarsest first."""
    variants = [b""]
    if len(data) > 1:
        variants.append(data[: len(data) // 2])
        variants.append(data[: len(data) - 1] + _GARBAGE)
    return variants


def _transforms_for(ops, idx, mode) -> list:
    """Concrete transform dicts for one (op, degradation-mode) pair."""
    op = ops[idx]
    if mode in ("skip", "linger"):
        return [{idx: (mode,)}]
    if mode == "data":
        return [{idx: ("data", v)} for v in _data_variants(op["data"])]
    if mode == "tail":  # last journal record on this path: lost or torn
        out = [{idx: ("skip",)}]
        data = op["data"]
        if len(data) > 1:
            out.append({idx: ("data", data[: len(data) // 2])})
        return out
    raise AssertionError(mode)


@dataclass
class CrashState:
    """One materializable post-crash state plus its machine repro."""

    prefix: int  # ops[:prefix] were issued before the crash
    degraded: list  # [[op_index, mode-string], ...]
    tree: dict = field(repr=False)  # relpath -> bytes

    def digest(self) -> str:
        h = hashlib.sha256()
        for path in sorted(self.tree):
            h.update(path.encode())
            h.update(b"\0")
            h.update(hashlib.sha256(self.tree[path]).digest())
        return h.hexdigest()[:16]


def enumerate_crash_states(base: dict, ops: list) -> list:
    """Every distinct :class:`CrashState` over every prefix of ``ops``."""
    states, seen = [], set()

    def add(prefix, transforms):
        tree = replay(base, ops, prefix, transforms)
        st = CrashState(
            prefix=prefix,
            degraded=[[i, "+".join(str(p) for p in t)]
                      for i, t in sorted(transforms.items())],
            tree=tree,
        )
        d = st.digest()
        if d not in seen:
            seen.add(d)
            states.append(st)
            return True
        return False

    for n in range(len(ops) + 1):
        add(n, {})
        emitted = 0
        vuln = _vulnerable(ops, n)
        for idx, mode in vuln:
            for tf in _transforms_for(ops, idx, mode):
                if emitted >= _MAX_PER_PREFIX:
                    break
                if add(n, tf):
                    emitted += 1
        # pairwise compound of the two newest vulnerabilities (rename
        # reverted AND the data it moved torn — the ALICE classic)
        if len(vuln) >= 2 and emitted < _MAX_PER_PREFIX:
            tf = {}
            for idx, mode in vuln[:2]:
                if idx not in tf:
                    tf.update(_transforms_for(ops, idx, mode)[0])
            if len(tf) == 2:
                add(n, tf)
        # a crash DURING the next op: partial application of ops[n]
        # (this is how a crash mid-``fsynced=True`` write is reachable —
        # the op itself is only ever recorded after its fsync returned)
        if n < len(ops) and ops[n]["op"] in ("write", "append"):
            for v in _data_variants(ops[n]["data"]):
                add(n + 1, {n: ("data", v)})
    return states


def materialize(state: CrashState, dirs: list, dest: str,
                age_s: float = 3600.0) -> None:
    """Write ``state`` into ``dest`` as a real tree.  Every mtime is
    backdated by ``age_s`` so recovery-side grace windows (the leaseless
    claim window, grace-aged tmp sweeps, cache GC) see the crash
    artifacts as the old files they would be at real recovery time."""
    os.makedirs(dest, exist_ok=True)
    for d in dirs:
        os.makedirs(os.path.join(dest, d), exist_ok=True)
    old = time.time() - age_s
    for rel, data in state.tree.items():
        p = os.path.join(dest, rel)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as fh:
            fh.write(data)
        os.utime(p, (old, old))
    for cur, _dns, _fns in os.walk(dest):
        os.utime(cur, (old, old))


def summarize_ops(ops: list) -> list:
    """JSON-safe op-log (payload bytes replaced by length + digest) —
    the machine-readable half of a finding's repro."""
    out = []
    for op in ops:
        rec = {}
        for k, v in op.items():
            if isinstance(v, bytes):
                rec[k] = {
                    "len": len(v),
                    "sha256": hashlib.sha256(v).hexdigest()[:16],
                }
            else:
                rec[k] = v
        out.append(rec)
    return out
