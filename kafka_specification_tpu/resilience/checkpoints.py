"""Hardened checkpoint store: checksums, keep-last-K rotation, fallback.

The seed engines wrote one `*.npz` per run whose only defense was an
identity stamp — a truncated or bit-rotted file aborted a 10-hour run.
`CheckpointStore` adds three guarantees:

- **Integrity**: every array in a checkpoint is CRC32-summed into a JSON
  manifest stored inside the npz (`__manifest__`).  Loads recompute and
  compare; the zip layer's own CRCs catch most torn writes, the manifest
  catches anything that slips through (and self-describes the format).
- **Keep-last-K rotation with atomic promote**: the newest generation
  always lives at the legacy filename (`<base>.npz`), older generations at
  `<base>.1.npz` ... `<base>.<K-1>.npz`.  A save writes a tmp file, shifts
  the existing generations up, then `os.replace`s the tmp into place — a
  crash at any point leaves at most one generation torn.
- **Automatic fallback**: `load()` walks generations newest -> oldest and
  returns the first one that verifies (checksums AND cross-file level
  consistency for per-shard part files).  Only if every present generation
  fails does it raise `CheckpointCorrupt` — a run never silently restarts
  from scratch while checkpoint data exists on disk.

Identity mismatches (a checkpoint from a different model/config/mesh) are
NOT corruption and still raise ValueError immediately: falling back past a
deliberate config change would silently resume the wrong search.

Per-shard part files (the sharded engine's per-host FpSet dumps) rotate in
lockstep with the main file — all processes checkpoint at the same levels —
and each generation is cross-checked: main and every part must record the
same `depth`, else that generation is treated as torn and skipped.

Legacy (pre-manifest) checkpoints load with the identity check only, so
existing checkpoint directories keep working.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Optional

import numpy as np

from .faults import FaultPlan

MANIFEST_KEY = "__manifest__"


class CheckpointCorrupt(Exception):
    """No on-disk checkpoint generation passed verification."""


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def build_manifest(arrays: dict) -> dict:
    """name -> {crc32, dtype, shape} for every array in a checkpoint."""
    man = {}
    for k, v in arrays.items():
        a = np.asarray(v)
        man[k] = {"crc32": _crc(a), "dtype": str(a.dtype), "shape": list(a.shape)}
    return man


class CheckpointStore:
    def __init__(
        self,
        directory: str,
        basename: str,
        ident: str,
        keep: int = 3,
        fault_plan: Optional[FaultPlan] = None,
    ):
        if not basename.endswith(".npz"):
            raise ValueError(f"basename must end in .npz, got {basename!r}")
        self.directory = directory
        self.basename = basename
        self.ident = ident
        self.keep = max(1, int(keep))
        self.fault_plan = fault_plan
        os.makedirs(directory, exist_ok=True)

    # --- paths ---------------------------------------------------------
    def path(self, gen: int = 0, part: Optional[str] = None) -> str:
        """Generation `gen` (0 = newest) of the main file or a part file.

        gen 0 keeps the legacy names (`<base>.npz`, `<base>.npz.<part>`) so
        pre-rotation directories and tooling stay compatible."""
        stem = self.basename[: -len(".npz")]
        name = self.basename if gen == 0 else f"{stem}.{gen}.npz"
        if part is not None:
            name += f".{part}"
        return os.path.join(self.directory, name)

    # --- save ----------------------------------------------------------
    def save(self, depth: int, arrays: dict, part: Optional[str] = None) -> str:
        """Checksummed write + rotate + atomic promote; returns the path.

        `depth` is stamped into the file (and must match across the main
        file and every part of a generation for a load to accept it)."""
        # lazy import: obs <-> resilience must stay acyclic at module level
        from ..obs import metrics as _met
        from ..obs import tracer as _obs

        arrays = dict(arrays)
        arrays["ident"] = self.ident
        arrays["depth"] = depth
        path = self.path(0, part)
        tmp = path + ".tmp.npz"
        with _obs.span("checkpoint-write", depth=depth, part=part or ""):
            # uncompressed (live fingerprints are high-entropy; zlib only
            # burns time — same rationale as the seed writer)
            np.savez(
                tmp, **{MANIFEST_KEY: json.dumps(build_manifest(arrays))},
                **arrays,
            )
            if self.fault_plan is not None:
                # torn-write rehearsal point: tmp written, nothing promoted
                self.fault_plan.crash("ckpt", depth)
            # shift existing generations up (newest-first so each replace's
            # target is the already-vacated slot); generation keep-1 falls
            # off
            for g in range(self.keep - 1, 0, -1):
                src = self.path(g - 1, part)
                if os.path.exists(src):
                    os.replace(src, self.path(g, part))
            os.replace(tmp, path)
        _met.inc("kspec_checkpoint_writes_total")
        if self.fault_plan is not None and self.fault_plan.should_corrupt(depth):
            from .faults import corrupt_file

            corrupt_file(path)
        return path

    # --- load ----------------------------------------------------------
    def _verify(self, path: str) -> dict:
        """Load `path` into a plain dict, checking the manifest checksums.

        Raises CheckpointCorrupt on any read/CRC/manifest failure.  A
        legacy file (no manifest) loads unchecked."""
        try:
            with np.load(path, allow_pickle=False) as snap:
                arrays = {k: snap[k] for k in snap.files}
        except Exception as e:  # zipfile/np errors: torn or rotted file
            raise CheckpointCorrupt(f"{path}: unreadable ({e})") from e
        man_raw = arrays.pop(MANIFEST_KEY, None)
        if man_raw is None:
            return arrays  # legacy pre-manifest checkpoint
        try:
            manifest = json.loads(str(man_raw))
        except ValueError as e:
            raise CheckpointCorrupt(f"{path}: bad manifest ({e})") from e
        if set(manifest) != set(arrays):
            raise CheckpointCorrupt(
                f"{path}: manifest/content mismatch "
                f"({sorted(set(manifest) ^ set(arrays))})"
            )
        for k, meta in manifest.items():
            if _crc(arrays[k]) != meta["crc32"]:
                raise CheckpointCorrupt(f"{path}: checksum mismatch on {k!r}")
        return arrays

    def _check_ident(self, path: str, arrays: dict) -> None:
        found = str(arrays["ident"]) if "ident" in arrays else "<none>"
        if found != self.ident:
            raise ValueError(
                f"checkpoint at {path} was written by a different "
                f"model/config:\n  checkpoint: {found}\n  this run:   {self.ident}"
            )

    def generations(self) -> list:
        """Generation indices present on disk (main files), newest first."""
        return [g for g in range(self.keep) if os.path.exists(self.path(g))]

    def _find_part(self, part: str, depth, errors: list):
        """Newest verifying generation of `part` at level `depth`, or None.

        Parts are matched to the main file BY LEVEL, not by generation
        index: part and main chains rotate at slightly different moments
        (every process promotes its part before the coordinator promotes
        the main file), so a crash in between skews the chains by one —
        pairing by index would make every generation look torn and defeat
        fallback entirely."""
        for pg in range(self.keep):
            path = self.path(pg, part)
            if not os.path.exists(path):
                continue
            try:
                pa = self._verify(path)
            except CheckpointCorrupt as e:
                errors.append(str(e))
                continue
            self._check_ident(path, pa)
            if "depth" not in pa or int(pa["depth"]) == depth:
                return pa
        return None

    def load(self, parts: tuple = ()) -> Optional[tuple]:
        """Newest verifying generation -> (main_arrays, {part: arrays}, gen).

        Walks main generations newest -> oldest; a generation is accepted
        only when the main file verifies and every requested part has a
        verifying copy AT THE SAME LEVEL (the cross-shard level-consistency
        check — a crash between part and main writes must not splice two
        different levels; the part may live at a different generation
        index, see _find_part).  Returns None when no checkpoint exists at
        all; raises CheckpointCorrupt when files exist but none verify;
        raises ValueError on an identity mismatch (never falls back past
        it)."""
        from ..obs import tracer as _obs  # lazy: cycle hygiene

        gens = self.generations()
        if not gens:
            return None
        errors = []
        for g in gens:
            try:
                with _obs.span("checkpoint-verify", generation=g):
                    main = self._verify(self.path(g))
            except CheckpointCorrupt as e:
                errors.append(str(e))
                continue
            self._check_ident(self.path(g), main)
            depth = int(main["depth"]) if "depth" in main else None
            part_arrays = {}
            torn = False
            for p in parts:
                pa = self._find_part(p, depth, errors)
                if pa is None:
                    errors.append(
                        f"generation {g}: no verifying part {p!r} at "
                        f"level {depth} (crash mid-checkpoint?)"
                    )
                    torn = True
                    break
                part_arrays[p] = pa
            if torn:
                continue
            if errors:
                import sys

                print(
                    f"[checkpoint] newest generation(s) failed verification; "
                    f"resuming from generation {g} (level {depth}):\n  "
                    + "\n  ".join(errors),
                    file=sys.stderr,
                )
                # run-correlated fallback record for `cli report`'s timeline
                _obs.event(
                    "checkpoint-fallback",
                    generation=g,
                    depth=depth,
                    errors=len(errors),
                )
            return main, part_arrays, g
        raise CheckpointCorrupt(
            "no checkpoint generation verified:\n  " + "\n  ".join(errors)
        )
