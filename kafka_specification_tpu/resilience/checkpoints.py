"""Hardened checkpoint store: checksums, keep-last-K rotation, fallback.

The seed engines wrote one `*.npz` per run whose only defense was an
identity stamp — a truncated or bit-rotted file aborted a 10-hour run.
`CheckpointStore` adds three guarantees:

- **Integrity**: every array in a checkpoint is CRC32-summed into a JSON
  manifest stored inside the npz (`__manifest__`).  Loads recompute and
  compare; the zip layer's own CRCs catch most torn writes, the manifest
  catches anything that slips through (and self-describes the format).
- **Keep-last-K rotation with atomic promote**: the newest generation
  always lives at the legacy filename (`<base>.npz`), older generations at
  `<base>.1.npz` ... `<base>.<K-1>.npz`.  A save writes a tmp file, shifts
  the existing generations up, then `os.replace`s the tmp into place — a
  crash at any point leaves at most one generation torn.
- **Automatic fallback**: `load()` walks generations newest -> oldest and
  returns the first one that verifies (checksums AND cross-file level
  consistency for per-shard part files).  Only if every present generation
  fails does it raise `CheckpointCorrupt` — a run never silently restarts
  from scratch while checkpoint data exists on disk.

Identity mismatches (a checkpoint from a different model/config/mesh) are
NOT corruption and still raise ValueError immediately: falling back past a
deliberate config change would silently resume the wrong search.

Per-shard part files (the sharded engine's per-host FpSet dumps) rotate in
lockstep with the main file — all processes checkpoint at the same levels —
and each generation is cross-checked: main and every part must record the
same `depth`, else that generation is treated as torn and skipped.

Legacy (pre-manifest) checkpoints load with the identity check only, so
existing checkpoint directories keep working.
"""

from __future__ import annotations

import json
import os
import re
import zlib
from typing import Optional

import numpy as np

from .. import durable_io as _dio
from .faults import FaultPlan

MANIFEST_KEY = "__manifest__"

#: machine-readable ownership contract (docs/analysis.md;
#: docs/resilience.md § Async checkpoint writes as data): the writer
#: thread runs `save()` — which mutates NOTHING on the store (files
#: only; every array handed to save_async is immutable from snapshot
#: time) — while the async bookkeeping (_async_job/_async_done and the
#: attached writer) belongs to the engine thread that polls it.
THREAD_CONTRACT = {
    "schema": "kspec-ownership/1",
    "classes": {
        "CheckpointStore": {
            "engine_only": ["_writer", "_async_job", "_async_done"],
            "immutable_after_init": ["directory", "basename", "ident",
                                     "ident_aliases", "keep",
                                     "fault_plan", "validators"],
            "worker_safe": ["save"],
        },
    },
}


class CheckpointCorrupt(Exception):
    """No on-disk checkpoint generation passed verification."""


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def build_manifest(arrays: dict) -> dict:
    """name -> {crc32, dtype, shape} for every array in a checkpoint."""
    man = {}
    for k, v in arrays.items():
        a = np.asarray(v)
        man[k] = {"crc32": _crc(a), "dtype": str(a.dtype), "shape": list(a.shape)}
    return man


def verify_file(path: str) -> dict:
    """Load `path` into a plain dict, checking the manifest checksums.

    Raises CheckpointCorrupt on any read/CRC/manifest failure.  A legacy
    file (no manifest) loads unchecked.  Module-level so the offline
    verifier (`verify_checkpoint_dir`) shares one definition of "this
    checkpoint file is intact" with the resume path."""
    try:
        with np.load(path, allow_pickle=False) as snap:
            arrays = {k: snap[k] for k in snap.files}
    except Exception as e:  # zipfile/np errors: torn or rotted file
        raise CheckpointCorrupt(f"{path}: unreadable ({e})") from e
    man_raw = arrays.pop(MANIFEST_KEY, None)
    if man_raw is None:
        return arrays  # legacy pre-manifest checkpoint
    try:
        manifest = json.loads(str(man_raw))
    except ValueError as e:
        raise CheckpointCorrupt(f"{path}: bad manifest ({e})") from e
    if set(manifest) != set(arrays):
        raise CheckpointCorrupt(
            f"{path}: manifest/content mismatch "
            f"({sorted(set(manifest) ^ set(arrays))})"
        )
    for k, meta in manifest.items():
        if _crc(arrays[k]) != meta["crc32"]:
            raise CheckpointCorrupt(f"{path}: checksum mismatch on {k!r}")
    return arrays


def part_matches(part_arrays: dict, match: dict) -> bool:
    """THE part-to-main pairing rule, shared by the resume path
    (CheckpointStore._find_part) and the offline verifier: a part pairs
    with a main iff every stamp the main carries (`depth`, and mesh
    layout when recorded) is either absent from the part (legacy) or
    equal."""
    return all(
        k not in part_arrays or v is None or int(part_arrays[k]) == v
        for k, v in match.items()
    )


class CheckpointStore:
    def __init__(
        self,
        directory: str,
        basename: str,
        ident: str,
        keep: int = 3,
        fault_plan: Optional[FaultPlan] = None,
        ident_aliases: tuple = (),
        validators: tuple = (),
    ):
        """`ident_aliases`: additional identity strings accepted on LOAD
        (new saves always stamp `ident`).  The sharded engine passes its
        pre-elastic ident form (which baked the mesh layout in) so
        checkpoints written by older code stay resumable on the same
        mesh after an upgrade.

        `validators`: callables ``arrays -> list[str]`` run on each main
        generation during load AFTER the CRC manifest passes; a non-empty
        return marks the generation corrupt and `load()` falls back to
        an older one exactly as it does for a checksum failure.  The
        engines pass the digest-chain validator
        (``resilience.integrity.checkpoint_chain_errors``) here — this is
        what makes the supervisor's restart-after-exit-76 policy "resume
        from the newest CHAIN-VERIFIED generation" without any new
        supervisor machinery: a CRC-consistent corrupted generation (one
        whose corruption happened before the write, so its checksums
        faithfully cover corrupt content) simply never resumes."""
        if not basename.endswith(".npz"):
            raise ValueError(f"basename must end in .npz, got {basename!r}")
        self.directory = directory
        self.basename = basename
        self.ident = ident
        self.ident_aliases = tuple(ident_aliases)
        self.keep = max(1, int(keep))
        self.fault_plan = fault_plan
        self.validators = tuple(validators)
        # async-write state (attach_writer): at most one save in flight,
        # completed (depth, path) pairs held until the engine polls them
        self._writer = None
        self._async_job = None
        self._async_done: list = []
        os.makedirs(directory, exist_ok=True)
        # startup janitor: a save killed mid-tmp-write leaves
        # `<name>.tmp.npz` behind (no manifest ever references it) —
        # sweep our own stem's strays so they never masquerade as disk
        # usage or confuse directory listings
        stem = basename[: -len(".npz")]
        for name in os.listdir(directory):
            if name.startswith(stem) and ".tmp." in name:
                try:
                    os.unlink(os.path.join(directory, name))
                except OSError:
                    pass

    # --- paths ---------------------------------------------------------
    def path(self, gen: int = 0, part: Optional[str] = None) -> str:
        """Generation `gen` (0 = newest) of the main file or a part file.

        gen 0 keeps the legacy names (`<base>.npz`, `<base>.npz.<part>`) so
        pre-rotation directories and tooling stay compatible."""
        stem = self.basename[: -len(".npz")]
        name = self.basename if gen == 0 else f"{stem}.{gen}.npz"
        if part is not None:
            name += f".{part}"
        return os.path.join(self.directory, name)

    # --- save ----------------------------------------------------------
    def save(self, depth: int, arrays: dict, part: Optional[str] = None) -> str:
        """Checksummed write + rotate + atomic promote; returns the path.

        `depth` is stamped into the file (and must match across the main
        file and every part of a generation for a load to accept it)."""
        # lazy import: obs <-> resilience must stay acyclic at module level
        from ..obs import metrics as _met
        from ..obs import tracer as _obs

        arrays = dict(arrays)
        arrays["ident"] = self.ident
        arrays["depth"] = depth
        path = self.path(0, part)
        tmp = path + ".tmp.npz"
        try:
            with _obs.span("checkpoint-write", depth=depth, part=part or ""):
                # uncompressed (live fingerprints are high-entropy; zlib
                # only burns time — same rationale as the seed writer)
                np.savez(
                    tmp, **{MANIFEST_KEY: json.dumps(build_manifest(arrays))},
                    **arrays,
                )
                _dio.note_write(tmp, fsynced=False)
                if self.fault_plan is not None:
                    # torn-write rehearsal points: tmp written, nothing
                    # promoted (crash@ckpt:N and the full-disk twin
                    # enospc@ckpt:N — resilience.resources)
                    self.fault_plan.crash("ckpt", depth)
                    self.fault_plan.enospc("ckpt", depth)
                # shift existing generations up (newest-first so each
                # replace's target is the already-vacated slot);
                # generation keep-1 falls off
                for g in range(self.keep - 1, 0, -1):
                    src = self.path(g - 1, part)
                    if os.path.exists(src):
                        _dio.replace(src, self.path(g, part))
                _dio.replace(tmp, path)
        except BaseException:
            # a failed save (ENOSPC, injected fault, kill) must not leave
            # its tmp behind: the promoted generations are the durable
            # state and they are untouched
            try:
                _dio.unlink(tmp)
            except OSError:
                pass
            raise
        _met.inc("kspec_checkpoint_writes_total")
        if self.fault_plan is not None and self.fault_plan.should_corrupt(depth):
            from .faults import corrupt_file

            corrupt_file(path)
        return path

    # --- async writes (KSPEC_OVERLAP; docs/resilience.md) ---------------
    def attach_writer(self, worker) -> None:
        """Enable :meth:`save_async` on an :class:`~..overlap.AsyncWorker`.

        The split of responsibilities is the async-checkpoint contract:
        the ENGINE snapshots the (small) level metadata, the digest
        chain, and the visited/frontier dumps synchronously — every
        array handed to save_async is immutable from then on — and the
        WRITER thread runs the pre-write chain verification, the
        checksummed tmp write, rotation and the atomic promote.  Errors
        (a real or injected ENOSPC, an injected crash) are stored on the
        job and re-raised on the engine thread at its next
        poll_async()/drain_async(), so the typed exit-75 path and the
        crash-restart contract fire exactly as in serial mode."""
        self._writer = worker

    def save_async(self, depth: int, arrays: dict,
                   part: Optional[str] = None,
                   pre_write=None, after_promote=None) -> None:
        """Queue one checksummed save on the attached writer thread.

        Serialized: a still-pending previous save is drained first (its
        error, if any, propagates here).  `pre_write` runs on the writer
        BEFORE the tmp write (the engines pass the digest-chain
        visited self-check — verification moves off the critical path
        but stays ahead of the promote, so detected corruption still
        never enters a checkpoint); `after_promote(path)` runs on the
        writer after the atomic promote (the chain read-back)."""
        assert self._writer is not None, "attach_writer first"
        # join the previous save WITHOUT consuming its completion record:
        # the engine's poll_async/drain_async is what processes the
        # (depth, path) pairs (barrier advance, durable-depth tracking)
        self._reap(block=True)

        def job():
            if pre_write is not None:
                pre_write()
            path = self.save(depth, arrays, part=part)
            if after_promote is not None:
                after_promote(path)
            return path

        self._async_job = (depth, self._writer.submit(
            "checkpoint-write-async", job
        ))

    def _reap(self, block: bool) -> None:
        if self._async_job is None:
            return
        depth, job = self._async_job
        if not block and not job.done.is_set():
            return
        try:
            # wait() re-raises THIS job's error (and consumes it from the
            # worker's failed queue) — never some other client's failure
            path = self._writer.wait(job)
        except BaseException:
            self._async_job = None
            raise
        self._async_job = None
        self._async_done.append((depth, path))

    def poll_async(self) -> list:
        """Non-blocking: -> newly completed [(depth, path)], raising any
        failed save's error on this (the engine's) thread."""
        self._reap(block=False)
        done, self._async_done = self._async_done, []
        return done

    def drain_async(self) -> list:
        """Block for the pending save (if any); -> completed pairs."""
        self._reap(block=True)
        done, self._async_done = self._async_done, []
        return done

    def prune(self, keep_gens: int = 1) -> list:
        """Resource reclamation: unlink every rotated generation (mains
        AND parts) at index >= `keep_gens`, keeping the newest.  Used by
        the engines' soft-breach reclaim right after a fresh save — the
        surviving generation's manifest is the one the deletion barrier
        may then be flushed against — and by the supervisor's --reclaim
        policy between attempts.  Returns the removed paths."""
        removed = []
        stem = self.basename[: -len(".npz")]
        pat = re.compile(
            re.escape(stem) + r"\.(\d+)\.npz(\..+)?$"
        )
        for name in os.listdir(self.directory):
            m = pat.match(name)
            if m is None or int(m.group(1)) < keep_gens:
                continue
            p = os.path.join(self.directory, name)
            try:
                _dio.unlink(p)
                removed.append(p)
            except OSError:
                pass
        return removed

    # --- load ----------------------------------------------------------
    def _verify(self, path: str) -> dict:
        return verify_file(path)

    def _check_ident(self, path: str, arrays: dict) -> None:
        found = str(arrays["ident"]) if "ident" in arrays else "<none>"
        if found != self.ident and found not in self.ident_aliases:
            raise ValueError(
                f"checkpoint at {path} was written by a different "
                f"model/config:\n  checkpoint: {found}\n  this run:   {self.ident}"
            )

    def generations(self) -> list:
        """Generation indices present on disk (main files), newest first."""
        return [g for g in range(self.keep) if os.path.exists(self.path(g))]

    def _find_part(self, part: str, match: dict, errors: list):
        """Newest verifying generation of `part` matching `match`, or None.

        Parts are matched to the main file BY LEVEL (plus any mesh-layout
        stamps the writer recorded — `match` maps array name -> required
        value), not by generation index: part and main chains rotate at
        slightly different moments (every process promotes its part
        before the coordinator promotes the main file), so a crash in
        between skews the chains by one — pairing by index would make
        every generation look torn and defeat fallback entirely.  The
        layout stamps matter after an elastic re-shard: the re-saved main
        and a stale old-layout part can share a depth, and splicing them
        would resume half a re-shard."""
        for pg in range(self.keep):
            path = self.path(pg, part)
            if not os.path.exists(path):
                continue
            try:
                pa = self._verify(path)
            except CheckpointCorrupt as e:
                errors.append(str(e))
                continue
            self._check_ident(path, pa)
            if part_matches(pa, match):
                return pa
        return None

    def load(self, parts=()) -> Optional[tuple]:
        """Newest verifying generation -> (main_arrays, {part: arrays}, gen).

        Walks main generations newest -> oldest; a generation is accepted
        only when the main file verifies and every requested part has a
        verifying copy AT THE SAME LEVEL (the cross-shard level-consistency
        check — a crash between part and main writes must not splice two
        different levels; the part may live at a different generation
        index, see _find_part).  `parts` is a tuple of part names or a
        callable main_arrays -> tuple: the sharded engine derives the
        part set from the mesh layout recorded IN the main file, because
        an elastic resume may need a different process count's parts than
        the resuming job runs with.  Returns None when no checkpoint
        exists at all; raises CheckpointCorrupt when files exist but none
        verify; raises ValueError on an identity mismatch (never falls
        back past it)."""
        from ..obs import tracer as _obs  # lazy: cycle hygiene

        gens = self.generations()
        if not gens:
            return None
        errors = []
        for g in gens:
            try:
                with _obs.span("checkpoint-verify", generation=g):
                    main = self._verify(self.path(g))
            except CheckpointCorrupt as e:
                errors.append(str(e))
                continue
            self._check_ident(self.path(g), main)
            val_errors = [
                err for v in self.validators for err in v(main)
            ]
            if val_errors:
                # semantically corrupt (CRC-consistent content corruption,
                # e.g. a digest-chain mismatch): same fallback as a
                # checksum failure — never resume a generation whose
                # content fails validation
                errors.extend(
                    f"{self.path(g)}: {err}" for err in val_errors
                )
                continue
            depth = int(main["depth"]) if "depth" in main else None
            match = {"depth": depth}
            for k in ("mesh_D", "mesh_P"):
                if k in main:
                    match[k] = int(main[k])
            part_arrays = {}
            torn = False
            for p in (parts(main) if callable(parts) else parts):
                pa = self._find_part(p, match, errors)
                if pa is None:
                    errors.append(
                        f"generation {g}: no verifying part {p!r} at "
                        f"level {depth} (crash mid-checkpoint?)"
                    )
                    torn = True
                    break
                part_arrays[p] = pa
            if torn:
                continue
            if errors:
                import sys

                print(
                    f"[checkpoint] newest generation(s) failed verification; "
                    f"resuming from generation {g} (level {depth}):\n  "
                    + "\n  ".join(errors),
                    file=sys.stderr,
                )
                # run-correlated fallback record for `cli report`'s timeline
                _obs.event(
                    "checkpoint-fallback",
                    generation=g,
                    depth=depth,
                    errors=len(errors),
                )
            return main, part_arrays, g
        raise CheckpointCorrupt(
            "no checkpoint generation verified:\n  " + "\n  ".join(errors)
        )


# --- offline verification (`cli verify-checkpoint`) -----------------------

_CKPT_RE = re.compile(
    r"^(?P<stem>.+?)(?:\.(?P<gen>\d+))?\.npz(?:\.(?P<part>.+))?$"
)


def _scan_checkpoint_files(directory: str) -> dict:
    """-> {stem: {"mains": {gen: path}, "parts": {part: {gen: path}}}}."""
    stores: dict = {}
    for name in sorted(os.listdir(directory)):
        path = os.path.join(directory, name)
        if not os.path.isfile(path) or ".tmp.npz" in name:
            continue
        m = _CKPT_RE.match(name)
        if m is None:
            continue
        st = stores.setdefault(m.group("stem"), {"mains": {}, "parts": {}})
        gen = int(m.group("gen") or 0)
        part = m.group("part")
        if part is None:
            st["mains"][gen] = path
        else:
            st["parts"].setdefault(part, {})[gen] = path
    return stores


def _resolve_spill(arrays: dict, spill_dir: str) -> dict:
    """Resolve a checkpoint's recorded storage manifest against the disk:
    every referenced run file / frontier segment must exist with the size
    its manifest entry implies — the checkpoint only *references* the
    disk tier (docs/storage.md's crash-safety contract), so a resumable
    generation is one whose references all still land."""
    from ..storage.runs import _HEADER as _RUN_HEADER  # jax-free

    problems = []
    checked = 0

    def check_run(run_dir: str, meta: dict) -> None:
        nonlocal checked
        checked += 1
        p = os.path.join(run_dir, meta["name"])
        if not os.path.isfile(p):
            problems.append(f"missing run file {p}")
            return
        want = _RUN_HEADER + 8 * int(meta["count"])
        size = os.path.getsize(p)
        if size != want:
            problems.append(f"{p}: size {size} != expected {want}")

    raw = json.loads(str(arrays["spill_manifest"]))
    if isinstance(raw, dict):  # single-device DiskTierStore manifest
        for meta in (raw.get("fpset") or {}).get("runs", ()):
            check_run(os.path.join(spill_dir, "fps"), meta)
        frontier_dir = os.path.join(spill_dir, "frontier")
        for seg in (raw.get("frontier") or {}).get("segments", ()):
            checked += 1
            p = os.path.join(frontier_dir, seg["name"])
            if not os.path.isfile(p):
                problems.append(f"missing frontier segment {p}")
    else:  # sharded: one tiered manifest per shard (None = unowned)
        for d, man in enumerate(raw):
            for meta in (man or {}).get("runs", ()):
                check_run(os.path.join(spill_dir, f"shard{d}"), meta)
    return {"ok": not problems, "files_checked": checked,
            "problems": problems}


def verify_checkpoint_dir(directory: str, spill_dir=None) -> dict:
    """Offline integrity report for a checkpoint directory — jax-free, so
    it runs from CI or an operator shell on a box whose accelerator stack
    is wedged (`cli verify-checkpoint` is the front-end).

    Checks, per checkpoint chain found in `directory`:

    - per-array CRC32 manifests of every main/part generation (the same
      `verify_file` the resume path trusts, without resuming anything);
    - cross-shard consistency: a generation is *resumable* only when
      every part file present has a verifying copy at the main file's
      depth (and mesh layout, when stamped) — the crash-between-promotes
      rule the sharded engine's per-host part files live by;
    - storage-manifest resolvability: a recorded `spill_manifest`'s run
      files / frontier segments must exist on disk at their manifest
      sizes (default spill dir: `<directory>/spill`, the engines'
      default placement; `--spill-dir` overrides).

    -> {"ok": bool, "dir": ..., "stores": [...]}: ok iff at least one
    chain exists and every chain has a fully-resumable generation.
    """
    directory = os.path.normpath(directory)
    spill_dir = spill_dir or os.path.join(directory, "spill")
    report: dict = {"dir": directory, "stores": [], "ok": False}
    if not os.path.isdir(directory):
        report["error"] = "not a directory"
        return report

    # checkpoint files are immutable once promoted; each part generation
    # may be consulted once per MAIN generation (keep of them), and a
    # full-CRC re-read of multi-GB fingerprint dumps per consult would
    # triple the verifier's disk traffic — memoize per path
    _verified: dict = {}

    def cached_verify(path: str) -> dict:
        if path not in _verified:
            try:
                _verified[path] = verify_file(path)
            except CheckpointCorrupt as e:
                _verified[path] = e
        out = _verified[path]
        if isinstance(out, CheckpointCorrupt):
            raise out
        return out
    for stem, files in sorted(_scan_checkpoint_files(directory).items()):
        store_rep = {"basename": f"{stem}.npz", "generations": [],
                     "ok": False}
        for gen in sorted(files["mains"]):
            path = files["mains"][gen]
            gen_rep: dict = {"gen": gen, "path": path, "ok": False,
                             "errors": []}
            store_rep["generations"].append(gen_rep)
            try:
                arrays = verify_file(path)
            except CheckpointCorrupt as e:
                gen_rep["errors"].append(str(e))
                continue
            depth = int(arrays["depth"]) if "depth" in arrays else None
            gen_rep["depth"] = depth
            if "ident" in arrays:
                gen_rep["ident"] = str(arrays["ident"])
            # level-digest-chain validation (resilience.integrity): the
            # layer ABOVE the per-array CRCs — a generation whose content
            # was corrupted before the write has internally consistent
            # checksums over corrupt data, and only the chain (linkage,
            # levels agreement, cumulative visited digest) flags it
            from .integrity import checkpoint_chain_errors

            if "digest_chain" in arrays:
                chain_errs = checkpoint_chain_errors(arrays)
                gen_rep["digest_chain"] = "ok" if not chain_errs else "FAILED"
                gen_rep["errors"].extend(chain_errs)
            else:
                gen_rep["digest_chain"] = "absent"
            match = {"depth": depth}
            for k in ("mesh_D", "mesh_P"):
                if k in arrays:
                    gen_rep[k] = match[k] = int(arrays[k])
            # required parts come from the MAIN's own stamps (the same
            # rule the resume path's _parts_for applies): per-host
            # `host<p>` part files exist only for the host visited
            # backend (the ident records `backend=...`), and only for
            # multi-process layouts — a stamped device/device-hash main
            # or a single-process main needs none.  Stale parts from a
            # pre-elastic layout are then ignored rather than failing a
            # perfectly resumable directory.  Unstamped (legacy) mains
            # fall back to requiring every part found on disk.
            if "mesh_P" in arrays:
                host_backend = "|backend=host|" in (gen_rep.get("ident") or "")
                needed = (
                    [f"host{p}" for p in range(match["mesh_P"])]
                    if match["mesh_P"] > 1 and host_backend
                    else []
                )
            else:
                needed = sorted(files["parts"])
            gen_rep["parts"] = {}
            for part in needed:
                found = None
                gens = files["parts"].get(part, {})
                for pg in sorted(gens):  # gen 0 = newest, as in load()
                    try:
                        pa = cached_verify(gens[pg])
                    except CheckpointCorrupt as e:
                        gen_rep["errors"].append(str(e))
                        continue
                    if part_matches(pa, match):
                        found = pg
                        break
                    pa = None
                gen_rep["parts"][part] = found
                if found is None:
                    gen_rep["errors"].append(
                        f"no verifying part {part!r} at depth {depth}"
                    )
                elif "spill_manifest" in pa:
                    # multi-process disk-tier runs record each host's
                    # spill manifest ONLY in its part file — resolve it
                    # there or missing run files go undetected
                    psp = _resolve_spill(pa, spill_dir)
                    gen_rep.setdefault("part_spill", {})[part] = psp
                    gen_rep["errors"].extend(psp["problems"])
            if "spill_manifest" in arrays:
                gen_rep["spill"] = _resolve_spill(arrays, spill_dir)
                gen_rep["errors"].extend(gen_rep["spill"]["problems"])
            gen_rep["ok"] = not gen_rep["errors"]
        store_rep["ok"] = any(g["ok"] for g in store_rep["generations"])
        report["stores"].append(store_rep)
    report["ok"] = bool(report["stores"]) and all(
        s["ok"] for s in report["stores"]
    )
    return report


# KSPEC_TSAN=1 (test-only): assert THREAD_CONTRACT ownership on every
# attribute write (analysis/ownership.py); zero overhead otherwise
from ..analysis.ownership import bind_contract as _bind_contract  # noqa: E402

_bind_contract(globals(), THREAD_CONTRACT)
