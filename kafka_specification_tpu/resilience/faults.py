"""Deterministic fault injection (`KSPEC_FAULT` env grammar).

The engines call into an active `FaultPlan` at their recovery-relevant
boundaries, so every recovery path (crash -> resume, corrupt checkpoint ->
fallback, transient backend error -> retry, escalated-compile OOM ->
uniform fallback) is drivable from a tier-1 CPU test or a supervised
production rehearsal — no real hardware failure needed.

Grammar (comma-separated specs in `KSPEC_FAULT` or `--fault`):

    crash@level:N             raise InjectedCrash at the level-N boundary
    crash@ckpt:N              raise InjectedCrash mid-checkpoint-write at
                              level N (after the tmp write, BEFORE the
                              atomic promote — the torn-write rehearsal)
    crash@merge:N             raise InjectedCrash mid-way through the Nth
                              disk-run merge of this process (merged tmp
                              written, BEFORE the atomic promote — the
                              disk tier's torn-write rehearsal,
                              storage/tiered.py).  Like crash@ckpt, meant
                              for in-process tests: N counts merges per
                              process, so a supervised restart that
                              re-reaches the Nth merge would re-fire
    corrupt_ckpt              corrupt the newest checkpoint right after its
                              first write (checksum-fallback rehearsal)
    corrupt_ckpt@ckpt:N       same, after the write at level N
    compile_oom               the next escalated (per-action-tuple) chunk
                              step raises an LLVM-OOM-shaped error once
                              (the reproducible wide-product XLA:CPU
                              failure, TODO.md)
    transient_device_err:N    the next N chunk/exchange step executions
                              raise a transient-classified backend error

Resource faults (the out-of-things failure family — resilience.resources;
every one must end in a typed RESOURCE_EXHAUSTED clean exit whose on-disk
state still passes `cli verify-checkpoint`):

    enospc@spill:N            the Nth spill-run write of this process
                              raises OSError(ENOSPC) after the tmp write,
                              before the atomic promote (the full-disk
                              rehearsal for storage/tiered.py; like
                              crash@merge, N is a per-process ordinal)
    enospc@merge:N            same, mid-way through the Nth disk-run merge
    enospc@ckpt:N             OSError(ENOSPC) mid-checkpoint-write at
                              level N (after the tmp write, before the
                              atomic promote — previous generations stay
                              intact and verifiable)
    enospc@plog:N             OSError(ENOSPC) publishing the level-N
                              parent-log segment
    stall@level:N             the per-level deadline watchdog reports
                              level N as stalled (the silent-stall
                              rehearsal; fires at the level-N boundary
                              once the run is durably past it)

Bit-flip faults (the silent-data-corruption family — resilience.integrity;
every one must be *detected* by the always-on integrity layer and end in a
typed INTEGRITY_VIOLATION exit 76 whose on-disk state resumes from the
newest chain-verified checkpoint generation):

    flip@frontier:N           flip one bit in the in-memory frontier
                              buffer at the level-N boundary (detected by
                              the level digest chain's frontier verify)
    flip@fpset:N              flip one bit in the visited-set dump taken
                              for the first checkpoint past level N
                              (detected by the save-time cumulative-digest
                              self-check BEFORE the write — corruption
                              never enters a checkpoint)
    flip@exchange:N           the level-N sharded exchange framing check
                              observes a corrupted payload digest on the
                              scoped shard (like stall@level, the fault
                              drives the detector's observation; the
                              in-jit sent/received digests are what a
                              real ICI bit flip would desync)
    flip@spill:N              flip bytes in the Nth spill-run file of this
                              process after its atomic promote (detected
                              by the read-side CRC verify on the run's
                              first lookup; N is a per-process ordinal —
                              in-process test use, like crash@merge)
    flip@ckpt:N               flip the `levels` array of the first
                              checkpoint written past level N BEFORE its
                              CRC manifest is built — a CRC-consistent
                              corrupted generation (detected by the
                              post-save chain read-back, by the resume
                              path's chain validator, and by the offline
                              `cli verify-checkpoint`)

    Level-keyed flip sites (frontier/fpset/exchange/ckpt) use the same
    checkpoint deferral as crash@level — on a checkpointing run they fire
    only once a generation at or past N exists, so a supervised restart
    resumes at or past N, the resume-depth relief applies, and the
    restart converges instead of flip-looping.

Shard scoping (the distributed engine's fault surface): any `@` fault may
carry a `shard<d>:` scope immediately after the `@`, and the bare faults
accept `@shard<d>` — the fault then fires only on the process that hosts
shard `d`'s device (`FaultPlan.set_local_shards`, wired by
`parallel/sharded.py` from the mesh's device->process map):

    crash@shard2:level:N          kill exactly the process hosting shard 2
                                  at the level-N boundary (its peers block
                                  in the next collective until the fleet
                                  supervisor tears the job down)
    crash@shard2:ckpt:N           torn-write rehearsal on one shard's host
    corrupt_ckpt@shard1           corrupt a checkpoint written by shard
    corrupt_ckpt@shard1:ckpt:N    1's host (its per-host part file, in a
                                  multi-process job)
    transient_device_err@shard0:N transient errors on shard 0's host only

In a single-process run every shard is local, so shard-scoped faults
degenerate to their unscoped forms — which is exactly what lets the whole
matrix run in tier-1 on the virtual CPU mesh.  Engines that never call
`set_local_shards` (the single-device engine) treat every scope as local
for the same reason.

Crash faults fire only when the run *started* below the target level
(`FaultPlan.set_start_depth` is called by the engines after a checkpoint
resume), and on a checkpointing run a `crash@level:N` additionally defers
until a checkpoint at or past level N exists — so a supervised restart
always resumes at or past the target and converges instead of
crash-looping, for any `checkpoint_every`.  `crash@ckpt:N` is the
exception — a resume from the previous good generation starts below N
again and would re-fire; it is meant for in-process torn-write tests, not
supervised runs.

Host faults (the cross-host service chaos family — service/router.py +
service/fleet.py; each host of a routed fleet is an isolated service dir
whose daemons run with `KSPEC_HOST_INSTANCE=<i>`, wired to
`FaultPlan.set_host`; one composed plan string can then drive a whole
multi-host drill, with each fault firing only on its targeted host):

    kill@host<i>:N            kill host i's serving daemon while it
                              handles its Nth job (before any verdict) —
                              the whole-host-death rehearsal when the
                              host runs one daemon.  Durable once per
                              service dir, like crash@daemon, so a
                              restarted host converges
    partition@host<i>[:N]     host i loses the shared state-cache
                              namespace for its next N jobs (default 1):
                              lookups degrade to typed `cache-fallback`
                              cold runs, publishes are DEFERRED and
                              re-published when the partition heals —
                              never a torn or unverified cross-host read
    skew@host<i>:SECS         shift host i's wall clock by SECS (may be
                              negative) in every timestamp it writes
                              into cross-host-visible metadata (claim
                              leases, heartbeats) — the drifted-clock
                              rehearsal behind the KSPEC_CLOCK_SKEW
                              allowance in lease expiry and router
                              heartbeat freshness

Budgeted faults (`compile_oom`, `transient_device_err:N`) are consumed
in-process and do not persist across restarts.
"""

from __future__ import annotations

import errno
import os
from dataclasses import dataclass
from typing import Optional

ENV_VAR = "KSPEC_FAULT"

# markers chosen so retry.classify() routes the injected error down the
# same branch a real backend error of that family would take
TRANSIENT_MARKER = "DATA_LOSS: injected transient device error (KSPEC_FAULT)"
OOM_MARKER = "LLVM ERROR: out of memory (injected by KSPEC_FAULT=compile_oom)"


class InjectedFault(RuntimeError):
    """Base class for all deliberately injected failures."""


class InjectedCrash(InjectedFault):
    """An injected hard crash (the process is expected to die)."""


#: THE single registry of injectable sites — the parser validates against
#: it and `cli faults --list` renders it, so a new fault family cannot be
#: added without becoming enumerable and parse-checked at the same time.
#: kind -> (valid sites (None = bare fault), grammar form, description)
FAULT_REGISTRY = (
    ("crash", ("level", "ckpt", "merge", "daemon"),
     "crash@level|ckpt|merge:N | crash@daemon<i>:N",
     "raise InjectedCrash at the level-N boundary / mid-checkpoint-write "
     "(tmp written, pre-promote) / mid-way through the Nth disk-run merge; "
     "the daemon<i> form kills serving-daemon instance i while it handles "
     "its Nth job (claims stay leased; a sibling's janitor requeues them "
     "and the verdict still publishes exactly once — service/fleet.py).  "
     "Daemon-scoped faults fire once per SERVICE DIR (durable "
     "fired-marker), so a fleet-restarted daemon converges instead of "
     "crash-looping — the crash@level checkpoint-deferral rule's twin"),
    ("corrupt_ckpt", ("ckpt",), "corrupt_ckpt[@ckpt:N]",
     "corrupt the newest checkpoint right after its write (checksum-"
     "fallback rehearsal); bytes flipped AFTER the CRC manifest, so the "
     "zip/manifest checks catch it on load"),
    ("compile_oom", None, "compile_oom",
     "the next escalated chunk step raises an LLVM-OOM-shaped error once "
     "(degrades fused/adaptive paths to the uniform fallback)"),
    ("transient_device_err", None, "transient_device_err:N",
     "the next N chunk/exchange steps raise a transient-classified "
     "backend error (bounded-backoff retry rehearsal)"),
    ("enospc", ("spill", "ckpt", "merge", "plog", "cache"),
     "enospc@spill|ckpt|merge|plog|cache:N",
     "OSError(ENOSPC) at the writer's pre-promote point (typed "
     "RESOURCE_EXHAUSTED exit 75; state stays verifiable).  The cache "
     "site is the Nth state-space-cache publish of this process "
     "(service/state_cache.py): publication aborts cleanly with a "
     "cache-fallback event, the job's verdict is untouched"),
    ("stall", ("level", "daemon"), "stall@level:N | stall@daemon<i>",
     "the per-level deadline watchdog reports level N stalled (typed "
     "exit 75); the daemon<i> form wedges serving-daemon instance i "
     "after its next claim sweep — heartbeat and lease renewal freeze, "
     "so the fleet supervisor stall-kills it and a sibling's janitor "
     "takes its leased claims over at lease expiry"),
    ("flip", ("frontier", "fpset", "exchange", "spill", "ckpt", "cache"),
     "flip@frontier|fpset|exchange|spill|ckpt|cache:N",
     "silent bit-flip at the named state surface (typed "
     "INTEGRITY_VIOLATION exit 76; detected by the digest-chain / "
     "framing / read-side-CRC layer — resilience.integrity).  The cache "
     "site flips bytes in the Nth published state-space-cache artifact "
     "of this process AFTER its promote: the next lookup's chain/CRC "
     "verification rejects it with a cache-fallback event and the check "
     "degrades to a cold run — never a wrong verdict"),
    ("kill", ("host",), "kill@host<i>:N",
     "kill host i's serving daemon while it handles its Nth job, before "
     "any verdict is derived (the whole-host-death rehearsal of the "
     "routed fleet — service/router.py detects the stale heartbeats and "
     "re-routes the host's pending jobs; its leased claims come back via "
     "the janitor takeover protocol at lease expiry).  Fires once per "
     "SERVICE DIR (durable fired-marker), so a restarted host converges; "
     "hosts are scoped by KSPEC_HOST_INSTANCE, so one composed plan "
     "string drives a whole multi-host drill"),
    ("partition", ("host",), "partition@host<i>[:N]",
     "host i loses the shared state-space-cache namespace for its next N "
     "jobs (default 1): every lookup in the window degrades to a typed "
     "cache-fallback cold run (reason 'partition') and every publish is "
     "DEFERRED, then re-published when the partition heals — verdicts "
     "are untouched and the federation never serves a torn read.  "
     "Durable once per service dir, like kill@host"),
    ("skew", ("host",), "skew@host<i>:SECS",
     "shift host i's wall clock by SECS (float, may be negative) in "
     "every timestamp it writes into cross-host-visible metadata — "
     "claim-lease stamps and heartbeat records — rehearsing a fleet "
     "member with a drifted clock.  The KSPEC_CLOCK_SKEW allowance in "
     "lease expiry (service/queue.py) and router heartbeat freshness "
     "(service/router.py) is what keeps a skewed-but-live host's claims "
     "from being stolen; persistent for the process lifetime"),
)

_SITES_BY_KIND = {k: sites for k, sites, _g, _d in FAULT_REGISTRY}


def list_faults() -> list:
    """[{kind, grammar, description, scopeable}] for `cli faults --list`
    (every fault composes with a `shard<d>:` scope)."""
    return [
        {"kind": k, "grammar": g, "sites": list(sites or ()),
         "description": d, "scopeable": True}
        for k, sites, g, d in FAULT_REGISTRY
    ]


@dataclass
class _Spec:
    kind: str  # crash | corrupt_ckpt | compile_oom | transient_device_err
    point: Optional[str]  # level | ckpt | None
    arg: Optional[float]  # level/ordinal (int) or seconds (skew) — None =
    # first
    budget: int  # remaining firings
    shard: Optional[int] = None  # fire only on this shard's host process
    instance: Optional[int] = None  # fire only on this daemon instance
    host: Optional[int] = None  # fire only on this service host


def _split_shard(rest: str, tok: str):
    """Peel an optional `shard<d>:`/`shard<d>` scope off `rest`."""
    if not rest.startswith("shard"):
        return None, rest
    head, _, tail = rest.partition(":")
    try:
        shard = int(head[len("shard"):])
    except ValueError:
        raise ValueError(
            f"fault {tok!r}: shard scope must be 'shard<index>', got {head!r}"
        )
    if shard < 0:
        raise ValueError(f"fault {tok!r}: shard index must be >= 0")
    return shard, tail


def _parse_token(tok: str) -> _Spec:
    if "@" in tok:
        name, _, rest = tok.partition("@")
        shard, rest = _split_shard(rest, tok)
        if name == "corrupt_ckpt" and shard is not None and not rest:
            return _Spec("corrupt_ckpt", "ckpt", None, 1, shard)
        if name == "transient_device_err" and shard is not None:
            if rest:
                try:
                    budget = int(rest)
                except ValueError:
                    raise ValueError(
                        f"fault {tok!r}: budget must be an integer"
                    )
            else:
                budget = 1
            return _Spec("transient_device_err", None, None, budget, shard)
        if name == "compile_oom" and shard is not None and not rest:
            return _Spec("compile_oom", None, None, 1, shard)
        point, _, arg = rest.partition(":")
        if point.startswith("daemon") and name in ("crash", "stall"):
            # serving-daemon instance scope (service/fleet.py): the
            # instance index is part of the site token, like shard<d>
            try:
                inst = int(point[len("daemon"):])
            except ValueError:
                raise ValueError(
                    f"fault {tok!r}: daemon scope must be 'daemon<index>', "
                    f"got {point!r}"
                )
            if inst < 0:
                raise ValueError(
                    f"fault {tok!r}: daemon index must be >= 0"
                )
            if name == "stall":
                if arg:
                    raise ValueError(
                        f"fault {tok!r}: stall@daemon<i> takes no ':N' "
                        "(the daemon wedges at its next claim sweep)"
                    )
                return _Spec("stall", "daemon", None, 1, instance=inst)
            try:
                nth = int(arg)
            except ValueError:
                raise ValueError(
                    f"fault {tok!r}: crash@daemon<i>:N needs an integer "
                    "job ordinal N"
                )
            if nth < 1:
                raise ValueError(f"fault {tok!r}: job ordinal must be >= 1")
            return _Spec("crash", "daemon", nth, 1, instance=inst)
        if point.startswith("host") and name in ("kill", "partition",
                                                 "skew"):
            # service-host scope (service/router.py): the host index is
            # part of the site token, like daemon<i> — the plan string is
            # shared by every host of the routed fleet and each fault
            # fires only on its target (KSPEC_HOST_INSTANCE -> set_host)
            try:
                host = int(point[len("host"):])
            except ValueError:
                raise ValueError(
                    f"fault {tok!r}: host scope must be 'host<index>', "
                    f"got {point!r}"
                )
            if host < 0:
                raise ValueError(f"fault {tok!r}: host index must be >= 0")
            if name == "kill":
                try:
                    nth = int(arg)
                except ValueError:
                    raise ValueError(
                        f"fault {tok!r}: kill@host<i>:N needs an integer "
                        "job ordinal N"
                    )
                if nth < 1:
                    raise ValueError(
                        f"fault {tok!r}: job ordinal must be >= 1"
                    )
                return _Spec("kill", "host", nth, 1, host=host)
            if name == "partition":
                if arg:
                    try:
                        njobs = int(arg)
                    except ValueError:
                        raise ValueError(
                            f"fault {tok!r}: partition@host<i>:N needs an "
                            "integer job count N"
                        )
                    if njobs < 1:
                        raise ValueError(
                            f"fault {tok!r}: job count must be >= 1"
                        )
                else:
                    njobs = 1
                return _Spec("partition", "host", njobs, 1, host=host)
            try:
                secs = float(arg)
            except ValueError:
                raise ValueError(
                    f"fault {tok!r}: skew@host<i>:SECS needs a number of "
                    "seconds (float, may be negative)"
                )
            if secs == 0.0:
                raise ValueError(
                    f"fault {tok!r}: a zero skew rehearses nothing — "
                    "give a nonzero SECS"
                )
            return _Spec("skew", "host", secs, 1, host=host)
        if not arg:
            raise ValueError(f"fault {tok!r}: '@{point}' needs ':<level>'")
        try:
            level = int(arg)
        except ValueError:
            raise ValueError(f"fault {tok!r}: level must be an integer")
        if level < 1:
            # crash faults fire only when the run STARTED below the target
            # level (start_depth < N), so level 0 could never fire — reject
            # it instead of silently rehearsing nothing
            raise ValueError(f"fault {tok!r}: level must be >= 1")
        if name in _SITES_BY_KIND and _SITES_BY_KIND[name]:
            if point in _SITES_BY_KIND[name]:
                return _Spec(name, point, level, 1, shard)
            # a typo'd SITE must be as loud as a typo'd kind: a silently
            # no-op'd `crash@lvl:3` would report the drill as passed
            raise ValueError(
                f"fault {tok!r}: unknown site {point!r} for {name!r} "
                f"(valid sites: {', '.join(_SITES_BY_KIND[name])}; "
                f"run `cli faults --list` for the full grammar)"
            )
        raise ValueError(
            f"unknown fault {tok!r} (known kinds: "
            f"{', '.join(k for k, *_ in FAULT_REGISTRY)}; run "
            f"`cli faults --list` for the full grammar)"
        )
    name, _, count = tok.partition(":")
    if name == "corrupt_ckpt":
        if count:
            raise ValueError(f"fault {tok!r}: use corrupt_ckpt@ckpt:<level>")
        return _Spec("corrupt_ckpt", "ckpt", None, 1)
    if name == "compile_oom":
        return _Spec("compile_oom", None, None, int(count) if count else 1)
    if name == "transient_device_err":
        return _Spec(
            "transient_device_err", None, None, int(count) if count else 1
        )
    raise ValueError(
        f"unknown fault {tok!r} (grammar: "
        + ", ".join(g for _k, _s, g, _d in FAULT_REGISTRY)
        + "; each '@'-scopeable as crash@shard<d>:level:N / "
        "corrupt_ckpt@shard<d> / transient_device_err@shard<d>:N; run "
        "`cli faults --list` for descriptions)"
    )


class FaultPlan:
    """A parsed set of faults plus their remaining budgets.

    Engines construct one per run via `FaultPlan.from_env()`; an unset env
    yields an empty plan whose hooks are all no-ops.
    """

    def __init__(self, spec: str = ""):
        self.spec = spec or ""
        self.start_depth = 0
        # None = no topology wired: every shard scope counts as local
        # (single-process runs, and the single-device engine)
        self.local_shards: Optional[frozenset] = None
        # which serving-daemon instance this process is (set_instance,
        # wired by service/daemon.py from KSPEC_DAEMON_INSTANCE); daemon-
        # scoped faults fire only on an exact match — None never fires,
        # so engine-side plans carrying daemon faults are inert there
        self.instance: Optional[int] = None
        # which service host this process serves (set_host, wired by
        # service/daemon.py from KSPEC_HOST_INSTANCE); host-scoped faults
        # fire only on an exact match — same contract as `instance`
        self.host: Optional[int] = None
        self.specs = [
            _parse_token(t.strip())
            for t in self.spec.split(",")
            if t.strip()
        ]

    @classmethod
    def from_env(cls, env_var: str = ENV_VAR) -> "FaultPlan":
        return cls(os.environ.get(env_var, ""))

    def __bool__(self) -> bool:
        return bool(self.specs)

    def set_start_depth(self, depth: int) -> None:
        """Record the depth a resumed run starts from: crash faults at or
        below it are considered already-fired (restart convergence)."""
        self.start_depth = int(depth)

    def set_instance(self, instance: int) -> None:
        """Record which serving-daemon instance this process is
        (service/fleet.py launches each `cli serve` child with
        KSPEC_DAEMON_INSTANCE=i).  `crash@daemon<i>:N` / `stall@daemon<i>`
        then fire only in the targeted instance's process — its fleet
        siblings sail past, which is exactly the one-daemon-died /
        one-daemon-wedged failure the fleet supervisor exists to catch."""
        self.instance = int(instance)

    def _instance_match(self, s: _Spec) -> bool:
        return (
            s.instance is not None
            and self.instance is not None
            and s.instance == self.instance
        )

    def daemon_crash(self, lo: int, hi: Optional[int] = None) -> None:
        """Raise InjectedCrash if a `crash@daemon<i>:N` fault targets this
        daemon instance and job ordinal N falls in [lo, hi] (the 1-based
        ordinals of the group the daemon is about to run).  Fires BEFORE
        any verdict is derived: the claims stay leased, the lease expires
        or the pid reads dead, and a sibling's janitor requeues them —
        the verdict still publishes exactly once."""
        hi = lo if hi is None else hi
        for s in self.specs:
            if s.kind != "crash" or s.point != "daemon" or s.budget <= 0:
                continue
            if not self._instance_match(s):
                continue
            if not (lo <= s.arg <= hi):
                continue
            s.budget -= 1
            raise InjectedCrash(
                f"injected daemon crash on instance {s.instance} at job "
                f"ordinal {s.arg} (KSPEC_FAULT)"
            )

    def daemon_stalled(self) -> bool:
        """True once per `stall@daemon<i>` fault targeting this instance:
        the daemon then wedges (stops heartbeating, stops renewing
        leases, stops claiming) so the fleet supervisor's stall detector
        kills it and a sibling takes over its claims at lease expiry."""
        for s in self.specs:
            if s.kind != "stall" or s.point != "daemon" or s.budget <= 0:
                continue
            if not self._instance_match(s):
                continue
            s.budget -= 1
            return True
        return False

    # --- host-scoped faults (the routed fleet's chaos family) -----------
    def set_host(self, host: int) -> None:
        """Record which service host this process serves (the router's
        per-host service dirs launch their daemons with
        KSPEC_HOST_INSTANCE=i).  `kill@host<i>:N` / `partition@host<i>` /
        `skew@host<i>:SECS` then fire only in the targeted host's
        processes — one composed plan string drives a whole multi-host
        drill, each fault landing on exactly its target."""
        self.host = int(host)

    def _host_match(self, s: _Spec) -> bool:
        return (
            s.host is not None
            and self.host is not None
            and s.host == self.host
        )

    def host_kill(self, lo: int, hi: Optional[int] = None) -> None:
        """Raise InjectedCrash if a `kill@host<i>:N` fault targets this
        host and job ordinal N falls in [lo, hi] — the daemon-side hook,
        called next to `daemon_crash` before any verdict is derived.
        The router sees the host's heartbeats go stale and re-routes its
        pending jobs; leased claims come back through the takeover
        protocol, so the verdict still publishes exactly once."""
        hi = lo if hi is None else hi
        for s in self.specs:
            if s.kind != "kill" or s.budget <= 0:
                continue
            if not self._host_match(s):
                continue
            if not (lo <= s.arg <= hi):
                continue
            s.budget -= 1
            raise InjectedCrash(
                f"injected host kill on host {s.host} at job ordinal "
                f"{int(s.arg)} (KSPEC_FAULT)"
            )

    def host_partition(self) -> int:
        """Number of jobs host i must run cache-partitioned (once per
        `partition@host<i>[:N]` fault targeting this host, then 0).  The
        daemon consumes it at a claim sweep: for that many jobs every
        state-cache lookup degrades to a typed `cache-fallback` cold run
        and every publish is deferred, re-published on heal."""
        for s in self.specs:
            if s.kind != "partition" or s.budget <= 0:
                continue
            if not self._host_match(s):
                continue
            s.budget -= 1
            return int(s.arg)
        return 0

    def skew_s(self) -> float:
        """Injected wall-clock shift for this host's cross-host-visible
        timestamps (claim leases, heartbeat records); 0.0 without a
        matching `skew@host<i>:SECS`.  Not budget-consumed: a drifted
        clock drifts for the whole process lifetime."""
        total = 0.0
        for s in self.specs:
            if s.kind == "skew" and self._host_match(s):
                total += float(s.arg)
        return total

    def set_local_shards(self, shards) -> None:
        """Record which shards this process hosts (the sharded engine's
        mesh device->process map).  Shard-scoped faults then fire only on
        the targeted shard's host — the peers sail past the injection
        point and block in their next collective, which is precisely the
        one-process-died failure the fleet supervisor exists to catch."""
        self.local_shards = frozenset(int(s) for s in shards)

    def validate_shards(self, shard_count: int) -> None:
        """Reject shard scopes outside the mesh (same fail-loudly rule as
        the level >= 1 parse check: a typo'd `crash@shard5:...` on a
        2-shard run would otherwise silently rehearse nothing on EVERY
        process and report the drill as passed)."""
        for s in self.specs:
            if s.shard is not None and s.shard >= shard_count:
                raise ValueError(
                    f"fault plan {self.spec!r}: shard {s.shard} is out of "
                    f"range for a {shard_count}-shard mesh (valid: "
                    f"0..{shard_count - 1})"
                )

    def _is_local(self, s: _Spec) -> bool:
        return (
            s.shard is None
            or self.local_shards is None
            or s.shard in self.local_shards
        )

    def crash(self, point: str, depth: int, ckpt_depth=None) -> None:
        """Raise InjectedCrash if a crash fault matches this (point, depth).

        `ckpt_depth` (level boundaries only): the newest durably
        checkpointed level, or None when the run isn't checkpointing.
        With checkpointing, a level crash is DEFERRED until a checkpoint
        at or past the target level exists — otherwise checkpoint_every>1
        would resume below the target and re-fire forever (e.g. crash@
        level:7 with saves only at even levels).  The crash then fires at
        the first level boundary where resuming cannot re-trigger it, so
        a supervised restart always converges."""
        for s in self.specs:
            if s.kind != "crash" or s.point != point or s.budget <= 0:
                continue
            if not self._is_local(s):
                continue
            # merge ordinals are per-process counters, not BFS levels:
            # the resume-depth relief below does not apply
            if point != "merge" and self.start_depth >= s.arg:
                continue  # resumed at/past the target: counts as fired
            if point == "level":
                if depth < s.arg:
                    continue
                if ckpt_depth is not None and ckpt_depth < s.arg:
                    continue  # not durably past the target yet: defer
            elif depth != s.arg:
                continue
            s.budget -= 1
            raise InjectedCrash(
                f"injected crash at {point}:{depth}"
                + (f" on shard {s.shard}" if s.shard is not None else "")
                + " (KSPEC_FAULT)"
            )

    def enospc(self, point: str, n: int) -> None:
        """Raise an injected OSError(ENOSPC) if an `enospc@<point>:N`
        fault matches.  `n` is the BFS level for ckpt/plog (resume-depth
        relief applies, like crash@level) and a per-process ordinal for
        spill/merge/cache (in-process test use, like crash@merge).  Raised at
        each writer's pre-promote point, so the on-disk state it leaves
        is exactly what a real full disk leaves: old files intact, tmp
        cleaned up, every promoted generation verifiable."""
        for s in self.specs:
            if s.kind != "enospc" or s.point != point or s.budget <= 0:
                continue
            if not self._is_local(s):
                continue
            if point in ("ckpt", "plog") and self.start_depth >= s.arg:
                continue  # resumed at/past the target: counts as fired
            if n != s.arg:
                continue
            s.budget -= 1
            raise OSError(
                errno.ENOSPC,
                f"No space left on device (injected by KSPEC_FAULT "
                f"enospc@{point}:{n})",
            )

    def stalled(self, depth: int) -> bool:
        """True once per `stall@level:N` fault when level N is done: the
        resource governor's deadline watchdog then reports the level as
        stalled (resilience.resources).  Resume-depth relief applies, so
        a post-reclaim resume converges instead of stall-looping."""
        for s in self.specs:
            if s.kind != "stall" or s.budget <= 0 or not self._is_local(s):
                continue
            if s.point == "daemon":
                continue  # daemon wedges fire via daemon_stalled(), never
                # at an engine level boundary (their arg is no level)
            if self.start_depth >= s.arg:
                continue
            if depth >= s.arg:
                s.budget -= 1
                return True
        return False

    def chunk_error(self, escalated: bool) -> Optional[Exception]:
        """Error to inject into the next chunk/exchange step, or None.

        compile_oom fires only on escalated (per-action width tuple)
        attempts — matching the real failure mode it rehearses, and the
        only attempt shape for which the engines have a compile fallback.
        """
        for s in self.specs:
            if not self._is_local(s):
                continue
            if s.kind == "transient_device_err" and s.budget > 0:
                s.budget -= 1
                return RuntimeError(TRANSIENT_MARKER)
            if s.kind == "compile_oom" and s.budget > 0 and escalated:
                s.budget -= 1
                return RuntimeError(OOM_MARKER)
        return None

    def flip(self, site: str, n: int, ckpt_depth=None):
        """The matching `flip@<site>:N` spec (truthy; carries the shard
        scope so the sharded engine flips the TARGETED shard's buffer),
        once per spec, else None — the caller then performs the actual
        bit flip (or, for the exchange framing check, the
        corrupted-digest observation) at its site.

        Level-keyed sites (frontier/fpset/exchange/ckpt): `n` is a BFS
        level; resume-depth relief applies, and with `ckpt_depth` given
        (a checkpointing run's newest durable level) firing DEFERS until
        a generation at or past the target exists — the same convergence
        rule as FaultPlan.crash, so a supervised restart resumes at or
        past N and never flip-loops.  `spill`: `n` is a per-process
        ordinal (in-process test use, like crash@merge)."""
        for s in self.specs:
            if s.kind != "flip" or s.point != site or s.budget <= 0:
                continue
            if not self._is_local(s):
                continue
            if site in ("spill", "cache"):
                # per-process ordinals (in-process test use, like
                # crash@merge): cache = the Nth state-space-cache
                # artifact published by this process
                if n != s.arg:
                    continue
            else:
                if self.start_depth >= s.arg:
                    continue  # resumed at/past the target: counts as fired
                if n < s.arg:
                    continue
                if ckpt_depth is not None and ckpt_depth < s.arg:
                    continue  # not durably past the target yet: defer
            s.budget -= 1
            return s
        return None

    def should_corrupt(self, depth: int) -> bool:
        """True if the checkpoint just written at `depth` must be corrupted."""
        for s in self.specs:
            if s.kind == "corrupt_ckpt" and s.budget > 0 and self._is_local(s):
                if s.arg is None or s.arg == depth:
                    s.budget -= 1
                    return True
        return False


#: injected_skew_s cache: (KSPEC_FAULT, KSPEC_HOST_INSTANCE) -> seconds.
#: The lease-stamp path calls this on every renewal; re-parsing the plan
#: each time would put a parser on the queue hot path for nothing — the
#: env pair is fixed for a process's lifetime in production and varies
#: only across monkeypatched tests, which the keyed cache handles.
_SKEW_CACHE: dict = {}


def injected_skew_s() -> float:
    """Wall-clock shift (seconds) the `skew@host<i>:SECS` fault injects
    into timestamps THIS process writes into cross-host-visible metadata
    (claim leases — service/queue.py — and heartbeat records).  0.0
    unless KSPEC_FAULT carries a skew spec targeting this process's
    KSPEC_HOST_INSTANCE; never raises (an unparseable plan is the
    engine/CLI's error to report, not the lease writer's)."""
    key = (
        os.environ.get(ENV_VAR, ""),
        os.environ.get("KSPEC_HOST_INSTANCE", ""),
    )
    if key not in _SKEW_CACHE:
        skew = 0.0
        if key[0] and key[1]:
            try:
                plan = FaultPlan(key[0])
                plan.set_host(int(key[1]))
                skew = plan.skew_s()
            except (ValueError, TypeError):
                skew = 0.0
        _SKEW_CACHE[key] = skew
    return _SKEW_CACHE[key]


def corrupt_file(path: str, n_bytes: int = 64) -> None:
    """Flip a run of bytes in the middle of `path` (simulated bit rot).

    Lands inside an npz member's compressed/stored data, so both the zip
    CRC and the manifest checksums must catch it on the next load."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(max(0, size // 2 - n_bytes // 2))
        chunk = fh.read(n_bytes)
        fh.seek(max(0, size // 2 - n_bytes // 2))
        fh.write(bytes(b ^ 0xFF for b in chunk))
