"""End-to-end state-integrity defense: level digest chains + typed exits.

The resilience arc (crash resume, resource governance, fleet supervision)
defends the checker against failures that ANNOUNCE themselves.  Nothing
before this module defended the *verdict* against silent corruption: a
flipped bit in a frontier buffer, a torn spill run, or a garbled exchange
payload yields a confidently wrong "no violation" with no trace.  This
module is the detection layer:

- :class:`LevelDigestChain` — an always-on, order-invariant digest over
  each BFS level's new-state fingerprint multiset.  Per level it keeps
  ``(count, xor, sum)`` accumulators over the 64-bit fingerprints (XOR
  and wrapping sum are commutative, so chunk order, shard order, and
  pipeline choice cannot change the digest — the multiset is the
  engine-invariant object the bit-identity contract already pins), plus
  a splitmix64 hash-chain value linking every level to its predecessor.
  The chain is stamped into checkpoints and run manifests; resume and
  ``cli verify-checkpoint`` re-verify it offline, so a resumed run
  provably continues the *same* exploration and a CRC-consistent
  corrupted generation (one whose per-array checksums were recomputed
  after the corruption, or whose corruption happened before the write)
  is still flagged.

- :func:`fingerprint_rows` — a bit-exact NUMPY twin of the engines'
  jax fingerprint kernel (``ops.fingerprint``), so host code (the
  digest fold over arena-assembled rows, the frontier verify at each
  level boundary, the tiny-chunk shadow oracle, the offline verifier)
  can recompute fingerprints without touching an accelerator.
  ``tests/test_integrity.py`` pins numpy == jax on random rows.

- :class:`IntegrityError` + :data:`EXIT_INTEGRITY` (76) — the typed
  terminal.  The engines stamp the run manifest ``integrity-violation``
  and re-raise; the CLI maps it to exit 76 (one past the resource exit
  75, same sysexits-adjacent convention); the supervisor classifies it
  as restartable — the load path's chain validator skips corrupted
  generations, so a restart resumes from the newest *chain-verified*
  checkpoint generation automatically.

- :func:`checkpoint_chain_errors` — the jax-free validator shared by
  the resume path (``CheckpointStore(validators=...)``) and the offline
  ``cli verify-checkpoint``: chain linkage, per-level count agreement
  with the ``levels`` array, and (when the generation carries the full
  fingerprint set: ``host_fps`` dumps, ``vhi``/``vlo`` prefixes,
  ``hash_hi``/``hash_lo`` live slots) the cumulative multiset digest of
  the stored visited set against the chain's running total.

Must stay jax-free at import (the offline verifier and the supervisor
parent both run on boxes whose accelerator stack may be wedged).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

#: one past EXIT_RESOURCE_EXHAUSTED (75): "the run's state failed an
#: integrity check" — distinct from crashes (restart blindly) and from
#: resource exits (do NOT restart), because the correct supervisor policy
#: is its own: restart from the newest chain-verified generation.
EXIT_INTEGRITY = 76

ENV_DISABLE = "KSPEC_INTEGRITY"  # "0" disables every always-on check
ENV_SHADOW = "KSPEC_INTEGRITY_SHADOW"  # sampled shadow re-execution rate

_U64 = np.uint64
_MASK64 = _U64(0xFFFFFFFFFFFFFFFF)


class IntegrityError(RuntimeError):
    """Typed terminal: a state-integrity check failed — the run's data
    (not its progress) can no longer be trusted.  The engines convert it
    into an ``integrity-violation`` manifest stamp; the CLI maps it to
    :data:`EXIT_INTEGRITY`; the supervisor restarts from the newest
    chain-verified checkpoint generation (corrupted generations are
    skipped by the load-time chain validator)."""

    def __init__(self, site: str, detail: str = "", depth=None):
        self.site = site  # frontier | fpset | exchange | spill | ckpt |
        # shadow | storage | chain
        self.detail = detail
        self.depth = depth
        super().__init__(
            f"INTEGRITY_VIOLATION[{site}]"
            + (f" at level {depth}" if depth is not None else "")
            + (f": {detail}" if detail else "")
        )


def enabled() -> bool:
    """Always-on unless explicitly disabled (bench baselines, escape
    hatch); the kill switch is an env var so a production operator can
    flip it without a redeploy."""
    return os.environ.get(ENV_DISABLE, "1") != "0"


def shadow_rate(arg: Optional[float] = None) -> float:
    """Resolve the shadow re-execution sample rate: explicit arg >
    $KSPEC_INTEGRITY_SHADOW > 0 (off)."""
    if arg is not None:
        rate = float(arg)
    else:
        rate = float(os.environ.get(ENV_SHADOW) or "0")
    if not (0.0 <= rate <= 1.0):
        raise ValueError(f"integrity shadow rate must be in [0, 1], got {rate}")
    return rate


def sample_chunk(depth: int, start: int, rate: float) -> bool:
    """Deterministic chunk sampler: the same (depth, chunk-start) is
    sampled identically on every run and after every resume, so shadow
    re-execution never perturbs bit-identity contracts."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = ((depth * 1000003 ^ start * 2654435761) * 0x9E3779B9) & 0xFFFFFFFF
    return h < rate * 4294967296.0


# --------------------------------------------------------------------------
# numpy twin of ops.fingerprint (bit-exact; pinned by tests)
# --------------------------------------------------------------------------

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_SEED_HI = np.uint32(0x9747B28C)
_SEED_LO = np.uint32(0x3C6EF372)


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _fmix32(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    return h ^ (h >> np.uint32(16))


def _murmur3_rows(rows: np.ndarray, seed: np.uint32) -> np.ndarray:
    k = rows.shape[-1]
    h = np.full(rows.shape[:-1], seed, np.uint32)
    for i in range(k):
        kx = rows[..., i] * _C1
        kx = _rotl32(kx, 15) * _C2
        h = h ^ kx
        h = _rotl32(h, 13) * np.uint32(5) + np.uint32(0xE6546B64)
    return _fmix32(h ^ np.uint32(4 * k))


def fingerprint_rows(rows: np.ndarray, exact: bool) -> np.ndarray:
    """uint32[n, K] packed states -> uint64[n] fingerprints, bit-exact
    with ``ops.fingerprint.fingerprint_lanes`` (incl. the all-ones
    sentinel remap in hashed mode)."""
    rows = np.ascontiguousarray(rows, np.uint32)
    if exact:
        k = rows.shape[-1]
        lo = rows[..., 0]
        hi = rows[..., 1] if k > 1 else np.zeros_like(lo)
    else:
        with np.errstate(over="ignore"):
            hi = _murmur3_rows(rows, _SEED_HI)
            lo = _murmur3_rows(rows, _SEED_LO)
        sent = np.uint32(0xFFFFFFFF)
        lo = np.where((hi == sent) & (lo == sent), np.uint32(0xFFFFFFFE), lo)
    return (hi.astype(_U64) << _U64(32)) | lo.astype(_U64)


def pair_u64(hi, lo) -> np.ndarray:
    """(hi, lo) uint32 fingerprint lanes -> uint64 values."""
    return (np.asarray(hi).astype(_U64) << _U64(32)) | np.asarray(lo).astype(
        _U64
    )


# --------------------------------------------------------------------------
# multiset digests + the level chain
# --------------------------------------------------------------------------


def digest_fps(fps: np.ndarray) -> tuple:
    """-> (count, xor, sum) over a uint64 fingerprint multiset.  XOR and
    wrapping sum are commutative and associative, so the digest is
    invariant to chunking, shard order, and pipeline choice — and two
    digests combine by (count+count, xor^xor, sum+sum)."""
    fps = np.asarray(fps, _U64)
    if fps.size == 0:
        return 0, 0, 0
    with np.errstate(over="ignore"):
        x = int(np.bitwise_xor.reduce(fps))
        s = int(np.sum(fps, dtype=_U64))
    return int(fps.size), x, s


def _splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


def chain_link(prev: int, count: int, xor: int, total: int) -> int:
    """One hash-chain step: the level-d chain value commits to the whole
    exploration prefix (every earlier level's digest), so two runs with
    equal chain values at depth d provably explored the same multiset
    sequence — the "a resumed run continues the SAME exploration" stamp."""
    h = _splitmix64(prev ^ _splitmix64(count))
    h = _splitmix64(h ^ xor)
    return _splitmix64(h ^ total)


class LevelDigestChain:
    """Per-level (count, xor, sum) digests + the linking hash chain.

    One instance per run; both engines drive the same protocol:

        chain.fold(fps_u64)      # any number of times per level, any order
        chain.seal(depth, n)     # at the level boundary (n = new states)

    ``entries[d] = (count, xor, sum, chain)`` as python ints;
    ``to_array()``/``from_array()`` round-trip through the uint64[L, 4]
    checkpoint stamp.  ``anchored`` is False when the chain was rebuilt
    from a pre-integrity checkpoint (counts known from ``levels``, digests
    unknown) — digest-dependent checks then skip, linkage-dependent ones
    still run from the resume point on.
    """

    COLS = 4  # count, xor, sum, chain

    def __init__(self):
        self.entries: list[tuple] = []
        self.anchored = True
        self._fold_count = 0
        self._fold_xor = 0
        self._fold_sum = 0

    # --- build ----------------------------------------------------------
    def fold(self, fps) -> None:
        c, x, s = digest_fps(fps)
        self._fold_count += c
        self._fold_xor ^= x
        self._fold_sum = (self._fold_sum + s) & 0xFFFFFFFFFFFFFFFF

    def fold_digest(self, count: int, xor: int, total: int) -> None:
        """Fold a PRE-COMPUTED (count, xor, sum) multiset digest — the
        device-resident pipeline's per-level accumulator, computed
        in-jit (ops/devlevel.py) bit-exactly with :func:`digest_fps`
        over the same fingerprints.  Digests combine by (c+c, x^x, s+s)
        (see digest_fps), so this is exactly fold() minus the host
        recomputation."""
        self._fold_count += int(count)
        self._fold_xor ^= int(xor)
        self._fold_sum = (self._fold_sum + int(total)) & 0xFFFFFFFFFFFFFFFF

    def seal(self, depth: int, count: int) -> None:
        """Close level `depth` (must be len(entries)): the folded digest
        becomes the level's entry.  A count disagreement between the
        engine's accounting and the folded multiset is itself an
        integrity violation (it means novelty masks and emitted rows
        diverged somewhere between the kernel and the host)."""
        assert depth == len(self.entries), (depth, len(self.entries))
        if self._fold_count != int(count):
            raise IntegrityError(
                "chain",
                f"level {depth}: folded {self._fold_count} fingerprints "
                f"but the engine accounted {int(count)} new states",
                depth=depth,
            )
        prev = self.entries[-1][3] if self.entries else 0
        link = chain_link(prev, self._fold_count, self._fold_xor,
                          self._fold_sum)
        self.entries.append(
            (self._fold_count, self._fold_xor, self._fold_sum, link)
        )
        self._fold_count = self._fold_xor = self._fold_sum = 0

    def reset_fold(self) -> None:
        self._fold_count = self._fold_xor = self._fold_sum = 0

    # --- verify ---------------------------------------------------------
    def verify_level(self, depth: int, fps) -> None:
        """The level-boundary frontier check: the multiset about to be
        expanded must be exactly the multiset sealed when the level was
        discovered — a bit flipped in the frontier buffer (or a frontier
        loaded from a CRC-consistent corrupted checkpoint) lands here."""
        if not self.anchored or depth >= len(self.entries):
            return
        c, x, s = digest_fps(fps)
        want = self.entries[depth]
        if (c, x, s) != want[:3]:
            raise IntegrityError(
                "frontier",
                f"level {depth} frontier digest (n={c}, xor={x:#x}) does "
                f"not match the sealed chain entry (n={want[0]}, "
                f"xor={want[1]:#x}) — the frontier buffer was corrupted "
                f"after the level was discovered",
                depth=depth,
            )

    def cumulative(self) -> tuple:
        """(count, xor, sum) over EVERY sealed level — the digest of the
        whole visited set (levels are disjoint by construction)."""
        c = x = s = 0
        for ec, ex, es, _ in self.entries:
            c += ec
            x ^= ex
            s = (s + es) & 0xFFFFFFFFFFFFFFFF
        return c, x, s

    def verify_visited(self, fps, depth=None, what: str = "fpset") -> None:
        """The save-time self-check: the visited-set dump about to be
        checkpointed must digest to the chain's running total.  Runs
        BEFORE the write, so detected corruption never enters a
        checkpoint."""
        if not self.anchored:
            return
        c, x, s = digest_fps(fps)
        wc, wx, ws = self.cumulative()
        if (c, x, s) != (wc, wx, ws):
            raise IntegrityError(
                what,
                f"visited-set dump digest (n={c}, xor={x:#x}) does not "
                f"match the chain's cumulative digest (n={wc}, "
                f"xor={wx:#x}) — the fingerprint set was corrupted in "
                f"memory",
                depth=depth,
            )

    # --- (de)serialization ---------------------------------------------
    def to_array(self) -> np.ndarray:
        return np.asarray(
            [[c, x, s, h] for c, x, s, h in self.entries], _U64
        ).reshape(len(self.entries), self.COLS)

    @classmethod
    def from_array(cls, arr) -> "LevelDigestChain":
        chain = cls()
        for row in np.asarray(arr, _U64).reshape(-1, cls.COLS):
            chain.entries.append(tuple(int(v) for v in row))
        return chain

    @classmethod
    def from_levels(cls, levels) -> "LevelDigestChain":
        """Rebuild from a pre-integrity checkpoint: counts only, digests
        unknown — the chain keeps extending but is unanchored below the
        resume point."""
        chain = cls()
        chain.anchored = False
        prev = 0
        for n in levels:
            prev = chain_link(prev, int(n), 0, 0)
            chain.entries.append((int(n), 0, 0, prev))
        return chain


# --------------------------------------------------------------------------
# checkpoint-side validation (shared: resume fallback + offline verifier)
# --------------------------------------------------------------------------


def chain_array_errors(arr, levels=None) -> list:
    """Validate a stamped ``digest_chain`` array: internal hash-chain
    linkage, and per-level count agreement with the checkpoint's own
    ``levels`` array.  -> list of error strings (empty = ok)."""
    errors = []
    try:
        rows = np.asarray(arr, _U64).reshape(-1, LevelDigestChain.COLS)
    except (ValueError, TypeError) as e:
        return [f"digest chain unparseable: {e}"]
    prev = 0
    for d, (c, x, s, h) in enumerate(rows.tolist()):
        want = chain_link(prev, int(c), int(x), int(s))
        if int(h) != want:
            errors.append(
                f"digest chain broken at level {d}: stored link "
                f"{int(h):#x} != recomputed {want:#x}"
            )
            break
        prev = int(h)
    if levels is not None:
        lv = [int(v) for v in np.asarray(levels).ravel().tolist()]
        cc = [int(c) for c in rows[:, 0].tolist()]
        if lv != cc:
            errors.append(
                f"digest chain counts {cc[:8]}{'...' if len(cc) > 8 else ''} "
                f"disagree with the levels array "
                f"{lv[:8]}{'...' if len(lv) > 8 else ''}"
            )
    return errors


def _visited_fps_of(arrays: dict):
    """The full visited-set uint64 multiset stored in a (single-device)
    checkpoint, or None when the generation doesn't carry one (disk-tier
    hot dumps are a budget-bounded subset; sharded mains may hold only
    per-shard concatenations, which still digest identically)."""
    if "spill_manifest" in arrays:
        return None  # hot dump only; the runs carry their own CRCs
    if "host_fps" in arrays:
        return np.asarray(arrays["host_fps"], _U64)
    if "hash_hi" in arrays:
        return pair_u64(arrays["hash_hi"], arrays["hash_lo"])
    if "vhi" in arrays and "vn" in arrays:
        vhi = np.asarray(arrays["vhi"], np.uint32)
        vlo = np.asarray(arrays["vlo"], np.uint32)
        if vhi.ndim == 1:
            return pair_u64(vhi, vlo)
        # sharded device backend: [D, w] per-shard prefixes of vn[d] rows
        vn = np.asarray(arrays["vn"]).ravel()
        parts = [
            pair_u64(vhi[d, : int(n)], vlo[d, : int(n)])
            for d, n in enumerate(vn.tolist())
        ]
        return np.concatenate(parts) if parts else np.empty(0, _U64)
    return None


def checkpoint_chain_errors(arrays: dict) -> list:
    """THE digest-chain validator for one checkpoint generation's arrays:
    linkage + levels agreement + (when the generation carries the full
    fingerprint set) cumulative visited digest.  Shared by the resume
    fallback (``CheckpointStore(validators=[...])``) and the offline
    ``cli verify-checkpoint`` — this is what flags a corrupted generation
    whose per-array CRCs still pass (the CRC faithfully checksums
    corrupted content; the chain does not).  Pre-integrity generations
    (no ``digest_chain``) validate vacuously."""
    if "digest_chain" not in arrays:
        return []
    errors = chain_array_errors(
        arrays["digest_chain"], levels=arrays.get("levels")
    )
    if "total" in arrays and not errors:
        rows = np.asarray(arrays["digest_chain"], _U64).reshape(
            -1, LevelDigestChain.COLS
        )
        tot = int(np.sum(rows[:, 0], dtype=_U64))
        if tot != int(arrays["total"]):
            errors.append(
                f"digest chain total {tot} != checkpoint total "
                f"{int(arrays['total'])}"
            )
    fps = _visited_fps_of(arrays) if not errors else None
    if fps is not None:
        chain = LevelDigestChain.from_array(arrays["digest_chain"])
        chain.anchored = True
        c, x, s = digest_fps(fps)
        wc, wx, ws = chain.cumulative()
        if (c, x, s) != (wc, wx, ws):
            errors.append(
                f"visited fingerprint set digest (n={c}, xor={x:#x}) does "
                f"not match the digest chain's cumulative (n={wc}, "
                f"xor={wx:#x}) — CRC-consistent content corruption"
            )
    return errors


def spill_run_errors(directory: str, metas) -> list:
    """CRC-verify every spill run a checkpoint generation REFERENCES —
    the shared core of both engines' disk-tier load validators (one
    implementation, like readback_chain: the accept/reject contract for
    generations must not drift between engines).  -> error strings."""
    from ..storage.runs import RunCorrupt, SortedRun

    errs = []
    for meta in metas:
        try:
            SortedRun(directory, meta, verify=True)
        except RunCorrupt as e:
            errs.append(f"referenced spill run corrupt: {e}")
    return errs


def readback_chain(path: str, depth=None) -> None:
    """Cheap post-save verification of a freshly promoted checkpoint's
    chain members only (digest_chain / levels / total — the big arrays
    were self-checked BEFORE the write).  A CRC-consistent corruption
    inside the writer (flip@ckpt rehearses it: the manifest checksums
    corrupt content faithfully) is caught here, typed, before the run
    sails on trusting a poisoned newest generation.  ONE implementation
    for both engines — the read-back contract must not drift between
    them."""
    for _attempt in range(3):
        try:
            with np.load(path, allow_pickle=False) as z:
                small = {
                    k: z[k]
                    for k in ("digest_chain", "levels", "total", "depth")
                    if k in z.files
                }
            break
        except FileNotFoundError:
            # the NEXT save's keep-K rotation window: generation 0 is
            # briefly renamed to .1 before its replacement promotes
            # (checkpoints.CheckpointStore.save).  The promote that
            # triggered THIS readback already succeeded, so the path can
            # only be missing because a newer generation superseded it
            # mid-rotate — wait out the window, and if it stays gone the
            # superseding save's own readback verifies the new newest.
            import time

            time.sleep(0.02)
    else:
        return
    count_check()
    errs = checkpoint_chain_errors(small)
    if errs:
        raise IntegrityError(
            "ckpt",
            f"post-save chain read-back of {path} failed: "
            + "; ".join(errs),
            depth=depth,
        )


def record_violation(err: "IntegrityError") -> None:
    """THE record-a-violation protocol (obs event + metric), shared by
    both engines' terminal handlers so the telemetry cannot drift."""
    from ..obs import metrics as _met  # lazy: cycle hygiene
    from ..obs import tracer as _obs

    _obs.event(
        "integrity-violation",
        site=err.site,
        depth=err.depth,
        detail=str(err)[:300],
    )
    _met.inc("kspec_integrity_violations_total")


def flip_bit(arr: np.ndarray) -> None:
    """In-place single-bit corruption of a (writable) numpy buffer — the
    injected SDC the flip@ faults rehearse.  Flips one bit in the middle
    element so interval gates and shape checks still pass (the corruption
    must be detectable only by content checks)."""
    if arr.size == 0:
        return
    flat = arr.reshape(-1).view(np.uint8)
    flat[flat.shape[0] // 2] ^= 0x10


def count_check(n: int = 1) -> None:
    """Bump the integrity-check counter (the obs beat's numerator)."""
    from ..obs import metrics as _met

    _met.inc("kspec_integrity_checks_total", n)


def fold_shard_device_digests(chain: "LevelDigestChain", counts,
                              xors_hi, xors_lo, limbs) -> None:
    """Fold per-SHARD device-computed level digests into a chain — the
    sharded device-resident level path's twin of the single-device
    fold_digest call.  `counts`/`xors_hi`/`xors_lo` are the fetched [D]
    accumulator lanes and `limbs` the [D, 4] 16-bit wrapping-sum limbs
    (ops/devlevel.masked_digest's accumulator, one per shard).  Digests
    combine commutatively, so folding shard by shard lands the exact
    value the per-chunk path's per-shard host folds produce over the
    same rows — chains stay comparable across pipelines, engines and
    elastic reshards."""
    from ..ops import devlevel

    for d in range(len(counts)):
        chain.fold_digest(
            *devlevel.digest_ints(
                (counts[d], xors_hi[d], xors_lo[d], limbs[d])
            )
        )
