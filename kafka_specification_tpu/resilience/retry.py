"""Failure classification + bounded exponential backoff for chunk steps.

The engines' known failure ladder (TODO.md, RUNPROD464_r5.log):

- **transient**: the backend hiccuped (tunnel RPC drop, preempted device,
  transient DATA_LOSS/UNAVAILABLE status).  The chunk is side-effect-free
  until its results are committed, so the right response is to re-run the
  same attempt after a short, bounded, exponentially-backed-off sleep.
- **compile_oom**: the reproducible wide-product XLA:CPU LLVM OOM on
  escalated per-action programs.  Retrying identically cannot help; the
  engines instead pin adaptation off (`AdaptiveCompact.compile_fallback`)
  and record the degradation in `result.stats`.
- **device_resource**: the backend ran out of device memory executing a
  chunk (`RESOURCE_EXHAUSTED` in the XLA status).  Re-running the
  identical chunk would allocate the identical buffers and die
  identically, so the engines degrade the WORK SHAPE instead: the
  current chunk re-runs on the uniform compact path (smaller device
  buffers) and the streaming chunk size halves for the rest of the run —
  both recorded in `result.stats["degradations"]`
  (`kind: "chunk_degrade"`).
- **other**: a real bug — propagate.

Classification is substring-based over the exception text (JAX surfaces
backend errors as `XlaRuntimeError` with the gRPC status name embedded),
with the injected-fault markers from `faults` matching their families.
"""

from __future__ import annotations

import os
import random
import sys
from dataclasses import dataclass, field

from ..utils import clock as _clk

TRANSIENT_PATTERNS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "DATA_LOSS",
    "ABORTED",
    "CANCELLED",
    "Socket closed",
    "connection reset",
)
OOM_PATTERNS = (
    "LLVM ERROR",
    "out of memory",
    "bad_alloc",
)
# device allocation failure at chunk-execute time: its own class (it used
# to be lumped into the compile-OOM family, but pinning *adaptation* off
# does nothing for a table/buffer that simply doesn't fit — the right
# degradation is a smaller chunk)
DEVICE_RESOURCE_PATTERNS = ("RESOURCE_EXHAUSTED",)


def classify(exc: BaseException) -> str:
    """-> 'transient' | 'device_resource' | 'compile_oom' | 'other'."""
    text = f"{type(exc).__name__}: {exc}"
    if any(p in text for p in TRANSIENT_PATTERNS):
        return "transient"
    if any(p in text for p in DEVICE_RESOURCE_PATTERNS):
        return "device_resource"
    if any(p in text for p in OOM_PATTERNS):
        return "compile_oom"
    return "other"


@dataclass
class RetryPolicy:
    """Bounded exponential backoff with jitter for transient errors."""

    max_retries: int = 3
    base_delay: float = 0.5
    factor: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.5  # delay *= 1 + U(0, jitter)
    rng: random.Random = field(default_factory=random.Random, repr=False)

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(
            max_retries=int(os.environ.get("KSPEC_RETRY_MAX", "3")),
            base_delay=float(os.environ.get("KSPEC_RETRY_BASE_DELAY", "0.5")),
            max_delay=float(os.environ.get("KSPEC_RETRY_MAX_DELAY", "30")),
        )

    def delay(self, attempt: int) -> float:
        """Backoff before retry number `attempt` (1-based)."""
        d = min(self.base_delay * self.factor ** (attempt - 1), self.max_delay)
        return d * (1.0 + self.jitter * self.rng.random())


@dataclass
class ChunkRetryHandler:
    """One copy of the chunk-step failure policy for both engines.

    Called from the engines' chunk-attempt except blocks; decides between
    - 'retry'   — transient error with budget left: sleeps the backoff and
                  tells the caller to re-run the same attempt;
    - 'degrade' — non-transient failure of an ESCALATED (per-action tuple)
                  program: records the degradation and tells the caller to
                  fall back to the uniform compact path;
    - 'degrade_chunk' — a device RESOURCE_EXHAUSTED on a NON-escalated
                  attempt: the identical chunk would allocate the
                  identical buffers and die again, so the caller re-runs
                  it on the uniform compact path AND halves its streaming
                  chunk size for the rest of the run (bounded by
                  `max_chunk_degrades`; recorded in
                  result.stats["degradations"]).  An escalated attempt's
                  RESOURCE_EXHAUSTED instead takes the 'degrade' path
                  below (lockstep-safe, same as before the class split);
    - re-raise  — anything else, including a transient error that exhausted
                  its retry budget (the supervisor's restart-from-checkpoint
                  layer owns that case; degrading on it would mislabel an
                  outage as a compile failure and pin adaptation off for the
                  rest of the run).

    The transient counter is per-chunk (`reset_chunk`); the totals and the
    degradation records accumulate per-run and land in result.stats.
    """

    policy: RetryPolicy
    tag: str  # "[engine]" / "[sharded]" stderr prefix
    transient_try: int = 0
    retries_total: int = 0
    chunk_degrades: int = 0
    max_chunk_degrades: int = 6  # 64x shrink, then surface the outage
    degradations: list = field(default_factory=list)

    @classmethod
    def from_env(cls, tag: str) -> "ChunkRetryHandler":
        return cls(policy=RetryPolicy.from_env(), tag=tag)

    def reset_chunk(self) -> None:
        self.transient_try = 0

    def handle(
        self,
        e: BaseException,
        escalated: bool,
        depth: int,
        retry_transient: bool = True,
    ) -> str:
        kind = classify(e)
        if kind == "transient":
            if not retry_transient:
                # retry-in-place is unsound here (e.g. a per-host error in
                # a multi-process collective: one host re-issuing the step
                # would desync the lockstep loop) — surface it instead
                raise e
            if self.transient_try >= self.policy.max_retries:
                raise e  # budget exhausted: surface the outage
            self.transient_try += 1
            self.retries_total += 1
            pause = self.policy.delay(self.transient_try)
            print(
                f"{self.tag} transient backend error "
                f"({type(e).__name__}: {e}); retry "
                f"{self.transient_try}/{self.policy.max_retries} in "
                f"{pause:.2f}s",
                file=sys.stderr,
            )
            # run-correlated retry record (obs/tracer; no-op without a run
            # context — lazy import keeps obs <-> resilience acyclic)
            from ..obs import tracer as _obs

            _obs.event(
                "retry",
                depth=depth,
                attempt=self.transient_try,
                backoff_s=round(pause, 2),
                error=f"{type(e).__name__}: {e}"[:200],
            )
            _clk.sleep(pause)
            return "retry"
        if kind == "device_resource" and not escalated:
            # (an ESCALATED attempt's RESOURCE_EXHAUSTED falls through to
            # the uniform-path degrade below — the family it shared with
            # compile_oom before this class existed; that response is
            # deterministic and replicated, hence lockstep-safe, whereas
            # the chunk shrink here is only sound where a lone
            # retry-in-place is: a multi-process peer shrinking its chunk
            # alone would desync the lockstep loop, so fleets surface it
            # to the supervisor instead)
            if not retry_transient:
                raise e
            if self.chunk_degrades >= self.max_chunk_degrades:
                raise e  # shrinking isn't helping: a real capacity wall
            self.chunk_degrades += 1
            print(
                f"{self.tag} device RESOURCE_EXHAUSTED executing a chunk "
                f"({type(e).__name__}); degrading work shape "
                f"({self.chunk_degrades}/{self.max_chunk_degrades}: uniform "
                f"compact now, half chunk size from the next level)",
                file=sys.stderr,
            )
            self.degradations.append(
                {
                    "kind": "chunk_degrade",
                    "depth": depth,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }
            )
            from ..obs import tracer as _obs

            _obs.event(
                "chunk-degrade",
                depth=depth,
                attempt=self.chunk_degrades,
                error=f"{type(e).__name__}: {e}"[:200],
            )
            return "degrade_chunk"
        if not escalated:
            raise e
        print(
            f"{self.tag} adaptive compact step failed "
            f"({type(e).__name__}); falling back to the uniform compact "
            f"path for the rest of the run",
            file=sys.stderr,
        )
        self.degradations.append(
            {
                "kind": "compile_fallback",
                "depth": depth,
                "error": f"{type(e).__name__}: {e}"[:300],
            }
        )
        from ..obs import tracer as _obs

        _obs.event(
            "compile-fallback",
            depth=depth,
            error=f"{type(e).__name__}: {e}"[:200],
        )
        return "degrade"
