"""Bloom filter over 64-bit fingerprints (the disk tier's lookup gate).

One filter per sorted run keeps negative membership queries off disk: a
miss in every run's filter means the fingerprint is definitely not in the
visited set, so only *probable* hits pay a binary search through the
mmap'd run.  At the default 16 bits/key with k=2 probes the false-positive
rate is ~1.5% — i.e. >98% of novel-fingerprint lookups never touch a run.

Correctness note: a bloom false POSITIVE only costs a wasted searchsorted;
a false NEGATIVE would mis-classify a visited state as new and corrupt the
search.  False negatives are impossible for a filter built from the run it
guards — which is why the sidecar file carries a CRC and a corrupt or
missing sidecar triggers a rebuild from the run instead of being trusted.
"""

from __future__ import annotations

import os
import zlib

import numpy as np

from .atomic import atomic_write

# bits of bloom per fingerprint (RAM residency ~bits/8 B per DISK
# fingerprint — see docs/storage.md "Capacity arithmetic"); 16 -> ~1.5%
# false-positive at k=2.  Env-tunable: at the multi-billion scale the
# filters themselves are gigabytes, and halving the density doubles only
# the *wasted-searchsorted* rate, never correctness.
DEFAULT_BITS_PER_KEY = int(os.environ.get("KSPEC_SPILL_BLOOM_BITS", "16"))

_MAGIC = b"KBLM1\x00"
# splitmix64 finalizer constants — decorrelates the probe positions from
# the fingerprint bits (fingerprints are themselves hashes, but exact64
# mode packs raw state lanes whose low bits are highly structured)
_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)


def _mix(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, np.uint64).copy()
    x ^= x >> np.uint64(30)
    x *= _C1
    x ^= x >> np.uint64(27)
    x *= _C2
    x ^= x >> np.uint64(31)
    return x


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


class BloomFilter:
    """k=2 blocked-free bloom filter with a power-of-two bit count."""

    def __init__(self, bits: np.ndarray):
        self.bits = bits  # uint8 byte array, len a power of two
        self.nbits = bits.shape[0] * 8
        self._mask = np.uint64(self.nbits - 1)

    @classmethod
    def build(cls, fps: np.ndarray, bits_per_key=None) -> "BloomFilter":
        if bits_per_key is None:
            bits_per_key = DEFAULT_BITS_PER_KEY
        nbits = _next_pow2(max(1 << 13, bits_per_key * int(fps.shape[0])))
        bf = cls(np.zeros(nbits // 8, np.uint8))
        bf.add(fps)
        return bf

    def _positions(self, fps: np.ndarray):
        h = _mix(fps)
        return h & self._mask, (h >> np.uint64(17)) & self._mask

    def add(self, fps: np.ndarray) -> None:
        for pos in self._positions(fps):
            np.bitwise_or.at(
                self.bits, (pos >> np.uint64(3)).astype(np.int64),
                np.left_shift(np.uint8(1), (pos & np.uint64(7)).astype(np.uint8)),
            )

    def maybe(self, fps: np.ndarray) -> np.ndarray:
        """bool mask: False = definitely absent, True = probably present."""
        out = np.ones(fps.shape[0], bool)
        for pos in self._positions(fps):
            byte = self.bits[(pos >> np.uint64(3)).astype(np.int64)]
            out &= (byte >> (pos & np.uint64(7)).astype(np.uint8)) & 1 != 0
        return out

    # --- sidecar persistence (missing/corrupt -> caller rebuilds) -------
    def save(self, path: str) -> None:
        def write(fh):
            fh.write(_MAGIC)
            fh.write(np.uint64(self.nbits).tobytes())
            fh.write(np.uint32(zlib.crc32(self.bits.tobytes())).tobytes())
            fh.write(self.bits.tobytes())

        atomic_write(path, write)

    @classmethod
    def load(cls, path: str):
        """The filter, or None when the sidecar is missing/corrupt (a
        false negative from trusting a rotted filter would corrupt the
        search — rebuild instead)."""
        try:
            with open(path, "rb") as fh:
                if fh.read(len(_MAGIC)) != _MAGIC:
                    return None
                nbits = int(np.frombuffer(fh.read(8), np.uint64)[0])
                crc = int(np.frombuffer(fh.read(4), np.uint32)[0])
                bits = np.frombuffer(fh.read(nbits // 8), np.uint8).copy()
        except (OSError, ValueError, IndexError):
            return None
        if bits.shape[0] != nbits // 8 or zlib.crc32(bits.tobytes()) != crc:
            return None
        return cls(bits)
