"""Out-of-core state storage: the disk tier (SURVEY.md "scales it further").

The checker's three dedup/state stores form a memory hierarchy:

- **device** — the existing HBM-resident backends (sorted pair set,
  open-addressing hash table; ops/dedup, ops/hashset).  Unchanged: the hot
  path while fingerprints fit on the accelerator.
- **host** — the native C++ open-addressing FpSet (native/fpset.cpp), the
  spill tier for state spaces that outgrow HBM.  Unchanged.
- **disk** (this package) — sorted, mmap-read fingerprint runs with a
  bloom + interval filter per run and periodic k-way merges, plus a
  disk-spilled frontier queue (chunked segments consumed in discovery
  order) and an append-only on-disk parent log for counterexample traces.
  This is the tier that takes a run past RAM: the 463.8M-state product
  (RUNPROD464_r5.log) filled the box; 2-5B states do not fit at
  ~16 B/fingerprint of host-set residency, which is exactly the wall TLC's
  disk-backed FPSet exists for.

Components:

- `TieredFpSet`   — host FpSet bounded by a byte budget; overflow spills
                    sorted immutable runs to disk, lookups touch disk only
                    on a bloom/interval probable hit (storage/tiered).
- `FrontierWriter`/`FrontierReader` — the disk-spilled frontier queue
                    (storage/frontier).
- `ParentLog`     — level-segmented, CRC-framed parent-pointer log; trace
                    reconstruction reads the log instead of in-RAM parent
                    arrays, so traces survive checkpoint/resume
                    (storage/parent_log).
- `DiskTierStore` — the single-device engine's composition of all three
                    plus the checkpoint-generation deletion barrier
                    (storage/store).

Crash-safety contract (docs/storage.md): every file is written to a tmp
name and atomically `os.replace`d; run/segment files are immutable once
named; deletions are deferred until `checkpoint_keep` newer checkpoint
generations exist, so every retained generation's manifest resolves; the
engine checkpoint records the storage *manifest* (run names + frontier
segment offsets), never the data itself.
"""

from .bloom import BloomFilter
from .frontier import FrontierReader, FrontierWriter
from .parent_log import ParentLog
from .runs import SortedRun, merge_runs, write_run
from .store import DiskTierStore
from .tiered import DeferredDeleter, TieredFpSet

DEFAULT_MEM_BUDGET = 4 << 30  # bytes of host FpSet residency before spilling


def parse_mem_budget(text) -> int:
    """'512M' / '4G' / '65536' / '1.5G' -> bytes (CLI --mem-budget)."""
    if isinstance(text, (int, float)):
        return int(text)
    s = str(text).strip()
    mult = 1
    suffixes = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30, "T": 1 << 40}
    if s and s[-1].upper() in suffixes:
        mult = suffixes[s[-1].upper()]
        s = s[:-1]
    try:
        v = float(s)
    except ValueError:
        raise ValueError(f"bad --mem-budget {text!r} (use e.g. 512M, 4G)")
    if v <= 0:
        raise ValueError(f"--mem-budget must be positive, got {text!r}")
    return int(v * mult)


def resolve_store(store: str, mem_budget) -> bool:
    """Map the --store knob to use_disk.  'auto' turns the disk tier on
    exactly when a memory budget was given."""
    if store not in ("auto", "ram", "disk"):
        raise ValueError(f"store must be 'auto', 'ram' or 'disk', got {store!r}")
    if store == "ram":
        return False
    if store == "disk":
        return True
    return mem_budget is not None


__all__ = [
    "BloomFilter",
    "DEFAULT_MEM_BUDGET",
    "DeferredDeleter",
    "DiskTierStore",
    "FrontierReader",
    "FrontierWriter",
    "ParentLog",
    "SortedRun",
    "TieredFpSet",
    "merge_runs",
    "parse_mem_budget",
    "resolve_store",
    "write_run",
]
