"""The one copy of the disk tier's crash-safety idiom.

Every file this package publishes — runs, bloom sidecars, frontier
segments, parent-log levels — goes through the same sequence: write to a
`.tmp` sibling, flush + fsync, then atomically `os.replace` into the
final name, then fsync the parent directory so the *rename itself* is
durable (a power loss after the replace but before the directory entry
hits disk would otherwise resurrect the old name).  A crash at any point
leaves either the old file or no file, never a torn one; a failed write
(ENOSPC, injected or real) additionally cleans up its own tmp so the
directory stays exactly what the last manifest describes.

`sweep_tmp` is the startup janitor for the one gap cleanup-on-raise
cannot cover: a process killed *mid-write* leaves its `.tmp` sibling
behind with no except block left to run.  Every storage structure sweeps
its directory at open — tmp files are never referenced by any manifest,
so removing them is always safe.
"""

from __future__ import annotations

import os


def fsync_dir(path: str) -> None:
    """Best-effort fsync of a directory entry (some filesystems refuse
    O_RDONLY dir fsync; the data-file fsync already happened either way)."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, write_fn, before_replace=None,
                 tmp_nonce=None) -> None:
    """Write `path` crash-safely: `write_fn(fh)` fills the tmp file, then
    it is fsync'd, atomically promoted, and the parent directory entry is
    fsync'd.  `before_replace` (if given) runs between the durable tmp
    write and the promote — the torn-write fault-injection point
    (`KSPEC_FAULT=crash@merge:N` / `enospc@...:N`).  Any failure unlinks
    the tmp before propagating, so a caller that survives the error (the
    engines' RESOURCE_EXHAUSTED clean-exit path) leaves no orphan.

    `tmp_nonce` privatises the tmp name (`path.<nonce>.tmp`) for callers
    whose writers race each other to the SAME final path — the default
    shared `path.tmp` would let one racer replace/unlink the sibling's
    half-written tmp out from under it.  Nonce'd names still match
    `sweep_tmp`'s pattern, so a crash mid-promote leaves nothing behind
    that the janitor cannot collect."""
    tmp = path + ".tmp" if tmp_nonce is None else f"{path}.{tmp_nonce}.tmp"
    try:
        with open(tmp, "wb") as fh:
            write_fn(fh)
            fh.flush()
            os.fsync(fh.fileno())
        if before_replace is not None:
            before_replace()
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(os.path.dirname(path))


def sweep_tmp(directory: str) -> list:
    """Startup janitor: remove stale `.tmp` siblings (and `.tmp.npz`
    checkpoint tmps) left by a mid-write death.  Safe by construction —
    no manifest ever references a tmp name.  Returns the removed paths."""
    removed = []
    if not os.path.isdir(directory):
        return removed
    for name in os.listdir(directory):
        if not (name.endswith(".tmp") or ".tmp." in name):
            continue
        p = os.path.join(directory, name)
        if not os.path.isfile(p):
            continue
        try:
            os.unlink(p)
            removed.append(p)
        except OSError:
            pass
    return removed
