"""The one copy of the disk tier's crash-safety idiom.

Every file this package publishes — runs, bloom sidecars, frontier
segments, parent-log levels — goes through the same sequence: write to a
`.tmp` sibling, flush + fsync, then atomically `os.replace` into the
final name, then fsync the parent directory so the *rename itself* is
durable (a power loss after the replace but before the directory entry
hits disk would otherwise resurrect the old name).  A crash at any point
leaves either the old file or no file, never a torn one; a failed write
(ENOSPC, injected or real) additionally cleans up its own tmp so the
directory stays exactly what the last manifest describes.

`sweep_tmp` is the startup janitor for the one gap cleanup-on-raise
cannot cover: a process killed *mid-write* leaves its `.tmp` sibling
behind with no except block left to run.  Every storage structure sweeps
its directory at open — tmp files are never referenced by any manifest,
so removing them is always safe.
"""

from __future__ import annotations

import os

from .. import durable_io as _dio

# canonical implementations live in the durable_io leaf so the
# crash-consistency harness sees one op vocabulary; these names stay
# re-exported here because every storage structure imports them from
# this module
fsync_dir = _dio.fsync_dir


def atomic_write(path: str, write_fn, before_replace=None,
                 tmp_nonce=None) -> None:
    """Write `path` crash-safely: `write_fn(fh)` fills the tmp file, then
    it is fsync'd, atomically promoted, and the parent directory entry is
    fsync'd.  `before_replace` (if given) runs between the durable tmp
    write and the promote — the torn-write fault-injection point
    (`KSPEC_FAULT=crash@merge:N` / `enospc@...:N`).  Any failure unlinks
    the tmp before propagating, so a caller that survives the error (the
    engines' RESOURCE_EXHAUSTED clean-exit path) leaves no orphan.

    `tmp_nonce` privatises the tmp name (`path.<nonce>.tmp`) for callers
    whose writers race each other to the SAME final path — the default
    shared `path.tmp` would let one racer replace/unlink the sibling's
    half-written tmp out from under it.  Nonce'd names still match
    `sweep_tmp`'s pattern, so a crash mid-promote leaves nothing behind
    that the janitor cannot collect."""
    tmp = path + ".tmp" if tmp_nonce is None else f"{path}.{tmp_nonce}.tmp"
    try:
        with open(tmp, "wb") as fh:
            write_fn(fh)
            fh.flush()
            os.fsync(fh.fileno())
        _dio.note_write(tmp, fsynced=True)
        if before_replace is not None:
            before_replace()
        _dio.replace(tmp, path)
    except BaseException:
        try:
            _dio.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(os.path.dirname(path))


sweep_tmp = _dio.sweep_tmp
