"""The one copy of the disk tier's crash-safety idiom.

Every file this package publishes — runs, bloom sidecars, frontier
segments, parent-log levels — goes through the same sequence: write to a
`.tmp` sibling, flush + fsync, then atomically `os.replace` into the
final name.  A crash at any point leaves either the old file or no file,
never a torn one.  Centralized here so a future hardening (e.g. fsyncing
the parent directory entry) lands everywhere at once.
"""

from __future__ import annotations

import os


def atomic_write(path: str, write_fn, before_replace=None) -> None:
    """Write `path` crash-safely: `write_fn(fh)` fills the tmp file, then
    it is fsync'd and atomically promoted.  `before_replace` (if given)
    runs between the durable tmp write and the promote — the torn-write
    fault-injection point (`KSPEC_FAULT=crash@merge:N`)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        write_fn(fh)
        fh.flush()
        os.fsync(fh.fileno())
    if before_replace is not None:
        before_replace()
    os.replace(tmp, path)
